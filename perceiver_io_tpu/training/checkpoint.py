"""Checkpoint / resume: async, multi-host-safe, best-by-metric retention.

The TPU-native replacement for the reference's Lightning ``ModelCheckpoint``
(reference ``train/utils.py:11-13``: monitor ``val_loss`` min, ``save_top_k=1``,
hyperparameters embedded via ``save_hyperparameters`` at ``lightning.py:46``)
and its ``load_from_checkpoint`` transfer path (reference
``train_seq_clf.py:18-28``: reuse a pretrained MLM encoder inside a fresh
classifier).

Built on Orbax, which writes sharded arrays in parallel from every host and
supports async save — the idiomatic way to checkpoint a pjit-sharded
params/opt-state pytree. The reference's "checkpoint surgery" (moving the
encoder ``nn.Module`` between Lightning models) becomes a pure pytree-subtree
swap: ``restore_encoder_params`` returns the ``encoder`` subtree to graft into
any other model's params.
"""

from __future__ import annotations

import dataclasses
import json
import os
import warnings
from typing import Any, Dict, Optional

import jax
import numpy as np
import orbax.checkpoint as ocp

HPARAMS_FILE = "hparams.json"
LAST_SUBDIR = "last"  # unconditional newest-state slot (preemption/crash)
METRICS_FILE = "metrics.json"
# Content-digest sidecar: {step: sha256-over-params} per manager directory,
# written at save() time and VERIFIED by restore_train_state(prefer_latest=
# True) before a step is trusted — extending the truncated-newest fallback
# (a partial save that fails to restore) to SILENT bit corruption (a save
# that restores fine but holds different bytes than were written). Same
# digest definition the deploy publications carry (utils/treepath).
DIGESTS_FILE = "digests.json"


def _record_digest(directory: str, step: int, params) -> None:
    """Append ``{step: digest}`` to the sidecar (atomic tmp+replace).

    Multi-process: process 0 alone writes (every host racing one json would
    corrupt it), and only when every leaf is fully REPLICATED (the standard
    data-parallel layout — note a multi-host global array is never fully
    *addressable*, but a replicated one is device_get-able from any one
    host's replica). A ZeRO-3 tree is sharded across hosts and gets no
    sidecar; its restores fall back to Orbax's atomic-commit guarantee, as
    before r19."""
    leaves = jax.tree.leaves(params)
    if jax.process_count() > 1:
        if jax.process_index() != 0 or not all(
            getattr(leaf, "is_fully_replicated", True) for leaf in leaves
        ):
            return
    from perceiver_io_tpu.utils.treepath import tree_digest

    digest = tree_digest(jax.device_get(params))
    path = os.path.join(directory, DIGESTS_FILE)
    try:
        with open(path) as f:
            data = json.load(f)
    except (OSError, ValueError):
        data = {}
    data[str(int(step))] = digest
    os.makedirs(directory, exist_ok=True)
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w") as f:
        json.dump(data, f, indent=2, sort_keys=True)
    os.replace(tmp, path)


def _expected_digest(directory: str, step: int) -> Optional[str]:
    try:
        with open(os.path.join(directory, DIGESTS_FILE)) as f:
            return json.load(f).get(str(int(step)))
    except (OSError, ValueError):
        return None  # no sidecar (pre-digest checkpoints): nothing to check


def _to_save_tree(state) -> Dict[str, Any]:
    """TrainState → pure-array pytree Orbax can serialize.

    Typed PRNG key arrays carry an opaque dtype; store the raw key data and
    re-wrap on restore.
    """
    return {
        "step": state.step,
        "params": state.params,
        "opt_state": state.opt_state,
        "rng": jax.random.key_data(state.rng),
    }


def _from_save_tree(tree: Dict[str, Any], like_state):
    rng = jax.random.wrap_key_data(np.asarray(tree["rng"], dtype=np.uint32))
    return like_state.replace(
        step=tree["step"],
        params=tree["params"],
        opt_state=tree["opt_state"],
        rng=rng,
    )


def host_state_snapshot(state) -> Dict[str, Any]:
    """In-memory host-local snapshot of a TrainState (the elastic buddy-
    mirror payload, and the resume point for an in-process world rebuild).

    A pure-numpy tree in the ``_to_save_tree`` layout (PRNG keys as raw key
    data), holding this host's addressable view of every leaf: fully
    replicated leaves — the standard data-parallel layout — come back
    complete and identical on every host, so the snapshot IS the whole
    state; a cross-host-sharded leaf (ZeRO over ``data``) contributes only
    this host's first addressable shard. Elastic resume requires the
    complete flavor — gate on :func:`snapshot_is_complete` before trusting
    a snapshot to seed a resized world.
    """

    def to_host(x):
        if isinstance(x, jax.Array) and not x.is_fully_addressable:
            return np.asarray(x.addressable_data(0))
        return np.asarray(jax.device_get(x))

    return jax.tree.map(to_host, _to_save_tree(state))


def snapshot_is_complete(state) -> bool:
    """True when every leaf of ``state`` is fully replicated (multi-host)
    or fully addressable (single-process) — i.e. :func:`host_state_snapshot`
    captures the COMPLETE state, not one host's shard of it."""
    return all(
        getattr(leaf, "is_fully_replicated", True)
        or getattr(leaf, "is_fully_addressable", True)
        for leaf in jax.tree.leaves(state)
    )


def restore_from_snapshot(snapshot: Dict[str, Any], like_state):
    """Snapshot → TrainState shaped like ``like_state`` (host-resident
    leaves; place onto a mesh via ``make_sharded_train_step`` /
    ``shard_train_state`` as with any restored state)."""
    return _from_save_tree(snapshot, like_state)


def snapshot_digest(snapshot: Dict[str, Any]) -> str:
    """Content digest of a snapshot — the same ``utils/treepath`` digest the
    checkpoint sidecar and deploy manifests use, so a buddy mirror is
    verifiable with the one digest discipline (``DIGESTS_FILE`` above)."""
    from perceiver_io_tpu.utils.treepath import tree_digest

    return tree_digest(snapshot)


class CheckpointManager:
    """Top-k-by-metric checkpointing of TrainState pytrees + hparams.

    Semantics mirror the reference callback (``train/utils.py:11-13``):
    ``monitor='val_loss'``, ``mode='min'``, ``max_to_keep=1`` by default.
    ``hparams`` (any JSON-serializable dict, e.g. a dataclass config) are
    written once per checkpoint, giving ``save_hyperparameters`` parity —
    a checkpoint is self-describing enough to rebuild its model.
    """

    def __init__(
        self,
        directory: str,
        max_to_keep: int = 1,
        monitor: str = "val_loss",
        mode: str = "min",
        hparams: Optional[Dict[str, Any]] = None,
        async_save: bool = True,
    ):
        if mode not in ("min", "max"):
            raise ValueError(f"mode must be 'min' or 'max', got {mode!r}")
        self.directory = os.path.abspath(directory)
        self.monitor = monitor
        self.mode = mode
        self._hparams = _jsonable(hparams) if hparams is not None else None

        def best_fn(metrics: Dict[str, float]) -> float:
            return float(metrics[monitor])

        self._mngr = ocp.CheckpointManager(
            self.directory,
            options=ocp.CheckpointManagerOptions(
                max_to_keep=max_to_keep,
                best_fn=best_fn,
                best_mode=mode,
                enable_async_checkpointing=async_save,
            ),
        )
        self._last_mngr: Optional[ocp.CheckpointManager] = None
        self._async_save = async_save
        if self._hparams is not None and jax.process_index() == 0:
            os.makedirs(self.directory, exist_ok=True)
            with open(os.path.join(self.directory, HPARAMS_FILE), "w") as f:
                json.dump(self._hparams, f, indent=2, sort_keys=True)

    # -- save ---------------------------------------------------------------

    def save_last(self, step: int, state) -> None:
        """Unconditionally save the CURRENT state to the ``last/`` slot
        (one kept), regardless of metric rank — the preemption/crash
        checkpoint. The best-by-metric policy above would GC a state whose
        monitored metric is worse than the champion's, which is exactly the
        state a preempted run needs to resume from."""
        if self._last_mngr is None:
            self._last_mngr = ocp.CheckpointManager(
                os.path.join(self.directory, LAST_SUBDIR),
                options=ocp.CheckpointManagerOptions(
                    max_to_keep=1,
                    enable_async_checkpointing=self._async_save,
                ),
            )
        self._last_mngr.save(
            int(step),
            args=ocp.args.Composite(
                state=ocp.args.StandardSave(_to_save_tree(state))
            ),
        )
        self._last_mngr.wait_until_finished()
        _record_digest(os.path.join(self.directory, LAST_SUBDIR),
                       step, state.params)

    def save(self, step: int, state, metrics: Dict[str, float]) -> bool:
        """Save if ``metrics[monitor]`` ranks in the top-k. Returns whether a
        save was issued (Orbax applies the best-k policy internally)."""
        metrics = {k: float(v) for k, v in metrics.items()}
        if self.monitor not in metrics:
            raise KeyError(
                f"monitored metric {self.monitor!r} missing from metrics "
                f"{sorted(metrics)}"
            )
        # item name 'val_metrics': orbax reserves 'metrics' for itself on
        # the release this runs under (RESERVED_ITEM_NAMES)
        saved = self._mngr.save(
            int(step),
            args=ocp.args.Composite(
                state=ocp.args.StandardSave(_to_save_tree(state)),
                val_metrics=ocp.args.JsonSave(metrics),
            ),
            metrics=metrics,
        )
        if saved:
            # the digest hashes the IN-MEMORY tree being saved (the intended
            # content), so it needs no wait on the async write — a restore
            # that later hashes differently read corrupted bytes
            _record_digest(self.directory, step, state.params)
        return saved

    def wait(self) -> None:
        """Block until in-flight async saves land (call before reading)."""
        self._mngr.wait_until_finished()

    # -- introspection ------------------------------------------------------

    @property
    def all_steps(self):
        self.wait()
        return sorted(self._mngr.all_steps())

    @property
    def best_step(self) -> Optional[int]:
        self.wait()
        return self._mngr.best_step()

    @property
    def latest_step(self) -> Optional[int]:
        self.wait()
        return self._mngr.latest_step()

    # -- restore ------------------------------------------------------------

    def restore_state(self, like_state, step: Optional[int] = None):
        """Restore a full TrainState (resume). ``like_state`` supplies the
        tree structure, shardings and dtypes; ``step=None`` → best step."""
        step = self._resolve(step)
        restored = self._mngr.restore(
            step,
            args=ocp.args.Composite(
                state=ocp.args.StandardRestore(_to_save_tree(like_state))
            ),
        )["state"]
        return _from_save_tree(restored, like_state)

    def restore_metrics(self, step: Optional[int] = None) -> Dict[str, float]:
        step = self._resolve(step)
        return dict(
            self._mngr.restore(
                step,
                args=ocp.args.Composite(val_metrics=ocp.args.JsonRestore()),
            )["val_metrics"]
        )

    def _resolve(self, step: Optional[int]) -> int:
        self.wait()
        if step is None:
            step = self._mngr.best_step()
            if step is None:
                step = self._mngr.latest_step()
        if step is None:
            raise FileNotFoundError(f"no checkpoints in {self.directory}")
        return int(step)

    def close(self) -> None:
        self.wait()
        self._mngr.close()
        if self._last_mngr is not None:
            self._last_mngr.close()

    def __enter__(self) -> "CheckpointManager":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


# -- module-level restore helpers (no manager required) ---------------------


def resolve_checkpoint_step(directory: str, step: Optional[int] = None,
                            monitor: str = "val_loss",
                            mode: str = "min") -> int:
    """The step a param restore from ``directory`` would use (explicit →
    best → latest) WITHOUT reading any arrays — e.g. the deploy watcher's
    ``min_step`` floor, so a restarted serve process never replays
    publications older than the checkpoint it booted from."""
    if step is not None:
        return int(step)
    with _read_manager(directory, monitor, mode) as mngr:
        return _resolve_step(mngr, None, directory)


def load_hparams(directory: str) -> Dict[str, Any]:
    """Read the hparams embedded in a checkpoint directory
    (``save_hyperparameters`` parity, reference ``lightning.py:46``)."""
    with open(os.path.join(os.path.abspath(directory), HPARAMS_FILE)) as f:
        return json.load(f)


def _read_manager(directory: str, monitor: str, mode: str) -> ocp.CheckpointManager:
    """Read-side manager with ranking configured, so best_step() works on a
    directory written by some other process/session."""
    return ocp.CheckpointManager(
        os.path.abspath(directory),
        options=ocp.CheckpointManagerOptions(
            best_fn=lambda metrics: float(metrics[monitor]),
            best_mode=mode,
            # read-only usage: never garbage-collect existing checkpoints
            max_to_keep=None,
        ),
    )


def restore_train_state(
    directory: str, like_state, step: Optional[int] = None,
    monitor: str = "val_loss", mode: str = "min",
    prefer_latest: bool = False,
):
    """Restore a TrainState from ``directory`` (best step by default).

    ``prefer_latest=True`` is the crash/preemption-resume mode: it considers
    both the ranked checkpoints and the unconditional ``last/`` slot
    (``CheckpointManager.save_last``) and restores whichever holds the highest
    step — continuing training from the newest state rather than the champion.

    In that mode a candidate that fails to restore — the signature of a run
    killed MID-SAVE, leaving a truncated/partial step dir — is skipped with a
    warning and the next-newest step is tried instead of crashing the resume
    (exactly the moment a corrupted checkpoint must not be fatal). Only when
    every candidate fails does the last error propagate.
    """
    restore_args = ocp.args.Composite(
        state=ocp.args.StandardRestore(_to_save_tree(like_state))
    )
    last_dir = os.path.join(os.path.abspath(directory), LAST_SUBDIR)
    if prefer_latest and step is None:
        # open each manager once: construction re-scans the directory (and
        # synchronizes cross-host), so probing and restoring reuse the handle
        import contextlib

        with contextlib.ExitStack() as stack:
            last_mngr = None
            candidates = []
            if os.path.isdir(last_dir):
                last_mngr = stack.enter_context(
                    ocp.CheckpointManager(last_dir))
                candidates += [(int(s), "last") for s in last_mngr.all_steps()]
            # a PLAIN (rank-free) manager for the main slot: prefer_latest
            # never needs best_fn, and a ranked manager eagerly json-parses
            # every step's metrics at construction — a truncated step from a
            # killed-mid-save run would crash the scan before the per-step
            # fallback below could skip it
            mngr = stack.enter_context(
                ocp.CheckpointManager(os.path.abspath(directory)))
            candidates += [(int(s), "main") for s in mngr.all_steps()]
            # newest step first; on a tie the last/ slot wins (it is by
            # construction at least as new as the ranked save of that step)
            candidates.sort(key=lambda c: (c[0], c[1] == "last"), reverse=True)
            if not candidates:
                raise FileNotFoundError(f"no checkpoints in {directory}")
            errors = []
            for cand_step, source in candidates:
                use = last_mngr if source == "last" else mngr
                cand_dir = last_dir if source == "last" \
                    else os.path.abspath(directory)
                try:
                    restored = use.restore(cand_step, args=restore_args)["state"]
                except Exception as e:  # corrupt/partial step dir
                    errors.append(e)
                    warnings.warn(
                        f"checkpoint step {cand_step} ({source} slot) failed "
                        f"to restore ({type(e).__name__}: {e}) — likely a "
                        f"partial save from an interrupted run; falling back "
                        f"to the previous checkpoint",
                        stacklevel=2,
                    )
                    continue
                # digest sidecar: a restore can SUCCEED while holding
                # silently corrupted bytes — verify the params content
                # against the digest recorded at save time before trusting
                # the step (no sidecar entry = pre-digest checkpoint: trust).
                # Multi-process: every host verifies whenever the restored
                # tree is fully REPLICATED (each host hashes its own full
                # replica); hosts read the same bytes off the shared
                # checkpoint filesystem, so a mismatch — and the fallback
                # to the previous candidate — is observed identically on
                # every rank and the restore collectives stay in lockstep.
                # (single-process trees are always verifiable — sharded or
                # not, every leaf is host-addressable, as pre-r19)
                verifiable = jax.process_count() == 1 or all(
                    getattr(leaf, "is_fully_replicated", True)
                    for leaf in jax.tree.leaves(restored["params"])
                )
                expected = (_expected_digest(cand_dir, cand_step)
                            if verifiable else None)
                if expected is not None:
                    from perceiver_io_tpu.utils.treepath import tree_digest

                    got = tree_digest(jax.device_get(restored["params"]))
                    if got != expected:
                        err = ValueError(
                            f"checkpoint step {cand_step} ({source} slot) "
                            f"restored but its params digest {got[:12]} does "
                            f"not match the save-time sidecar "
                            f"{expected[:12]} — silent corruption"
                        )
                        errors.append(err)
                        warnings.warn(
                            f"{err}; falling back to the previous checkpoint",
                            stacklevel=2,
                        )
                        continue
                return _from_save_tree(restored, like_state)
            raise errors[-1]
    with _read_manager(directory, monitor, mode) as mngr:
        step = _resolve_step(mngr, step, directory)
        restored = mngr.restore(step, args=restore_args)["state"]
    return _from_save_tree(restored, like_state)


def restore_params(
    directory: str, like_params, step: Optional[int] = None,
    monitor: str = "val_loss", mode: str = "min",
):
    """Restore only the params tree (inference / export)."""
    with _read_manager(directory, monitor, mode) as mngr:
        step = _resolve_step(mngr, step, directory)
        restored = mngr.restore(
            step,
            args=ocp.args.Composite(state=_partial_restore({"params": like_params})),
        )["state"]
    return restored["params"]


def restore_raw_params(directory: str, step: Optional[int] = None,
                       monitor: str = "val_loss", mode: str = "min"):
    """Restore the params tree WITHOUT a caller template, as ``(params,
    step)`` with host numpy/jax arrays in the saved structure — for tools
    that only re-serialize the weights (e.g. the reference-checkpoint
    export) and have no model to build a ``like`` tree from.

    The template comes from the checkpoint's own metadata, restricted to
    the ``params`` subtree — a full TrainState checkpoint also stores the
    optimizer moments (~2x the param bytes), which a templateless restore
    would read and materialize only to discard."""
    with _read_manager(directory, monitor, mode) as mngr:
        step = _resolve_step(mngr, step, directory)
        # reading metadata (vs restoring) needs the handler declared upfront
        with ocp.CheckpointManager(
            os.path.abspath(directory),
            options=ocp.CheckpointManagerOptions(
                best_fn=lambda m: m.get(monitor, 0.0), best_mode=mode
            ),
            item_handlers={"state": ocp.StandardCheckpointHandler()},
        ) as meta_mngr:
            meta = meta_mngr.item_metadata(step)["state"]
        like = jax.tree.map(
            lambda m: np.zeros(m.shape, m.dtype), meta["params"]
        )
        restored = mngr.restore(
            step, args=ocp.args.Composite(state=_partial_restore({"params": like}))
        )["state"]
    return restored["params"], int(step)


def restore_encoder_params(
    directory: str, like_encoder_params, step: Optional[int] = None,
    subtree: str = "encoder", monitor: str = "val_loss", mode: str = "min",
):
    """Restore one params subtree — the transfer-learning path.

    The reference moves a pretrained MLM encoder module into a fresh text
    classifier (``train_seq_clf.py:18-24``); here the same capability is a
    partial pytree restore: read only ``params/<subtree>`` from the checkpoint
    (Orbax restores just the requested leaves) and graft it into the new
    model's params: ``params['encoder'] = restore_encoder_params(...)``.
    """
    with _read_manager(directory, monitor, mode) as mngr:
        step = _resolve_step(mngr, step, directory)
        restored = mngr.restore(
            step,
            args=ocp.args.Composite(
                state=_partial_restore({"params": {subtree: like_encoder_params}})
            ),
        )["state"]
    return restored["params"][subtree]


def _partial_restore(item):
    """Restore only the leaves present in ``item`` (subtree loading).

    ``transforms={}`` is the pre-``partial_restore`` spelling this orbax
    release supports: the output takes ``item``'s structure, every key falls
    through to the stored value, and leaves absent from ``item`` are never
    read."""
    return ocp.args.PyTreeRestore(
        item=item,
        restore_args=ocp.checkpoint_utils.construct_restore_args(item),
        transforms={},
    )


def _resolve_step(mngr, step: Optional[int], directory: str) -> int:
    if step is not None:
        return int(step)
    try:
        step = mngr.best_step()
    except KeyError:  # checkpoints saved without the monitored metric
        step = None
    if step is None:
        step = mngr.latest_step()
    if step is None:
        raise FileNotFoundError(f"no checkpoints in {directory}")
    return int(step)


def _jsonable(obj: Any) -> Any:
    """Best-effort JSON projection for hparams (dataclasses, argparse
    namespaces, numpy scalars)."""
    if dataclasses.is_dataclass(obj) and not isinstance(obj, type):
        return _jsonable(dataclasses.asdict(obj))
    if hasattr(obj, "__dict__") and not isinstance(obj, (dict, list, tuple, str)):
        try:
            return _jsonable(vars(obj))
        except TypeError:
            return str(obj)
    if isinstance(obj, dict):
        return {str(k): _jsonable(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return [_jsonable(v) for v in obj]
    if isinstance(obj, (np.integer,)):
        return int(obj)
    if isinstance(obj, (np.floating,)):
        return float(obj)
    if obj is None or isinstance(obj, (bool, int, float, str)):
        return obj
    return str(obj)
