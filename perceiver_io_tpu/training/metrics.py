"""Host-side metrics logging: TensorBoard + JSONL.

The replacement for the reference's Lightning/TensorBoard observability
(reference ``train_mlm.py:69``: ``TensorBoardLogger('logs', name=experiment)``
with scalar logging via ``self.log`` and free-text sample predictions via
``add_text``). Keeps the same on-disk layout — ``logs/<experiment>/version_n``
— so existing TensorBoard workflows carry over unchanged.

TensorBoard events are written through ``torch.utils.tensorboard`` when
available (torch is host-side only here — nothing touches the device path);
every scalar is also appended to ``metrics.jsonl`` so runs remain greppable
and the logger degrades gracefully on boxes without a TB writer.

Every scalar is ALSO published as a gauge to the process-wide metrics
registry (``perceiver_io_tpu.obs``) — TB/JSONL and the live exporters
(``/metrics``, ``/statz``) see the same numbers from one source of truth.

Only process 0 writes files (multi-host safe); gauges are local to every
process, and the export edge (the HTTP sidecar) is process-0-gated.
"""

from __future__ import annotations

import json
import os
import re
from typing import Dict, Optional

import jax

import perceiver_io_tpu.obs as obs


def next_version_dir(logdir: str, experiment: str) -> str:
    """``<logdir>/<experiment>/version_n`` with the smallest unused n —
    the Lightning layout (reference ``README.md:123-144``). Multi-host: the
    index chosen by process 0 is broadcast so every process agrees even when
    their directory scans race."""
    base = os.path.join(logdir, experiment)
    n = 0
    if os.path.isdir(base):
        versions = [
            int(m.group(1))
            for name in os.listdir(base)
            if (m := re.fullmatch(r"version_(\d+)", name))
        ]
        n = max(versions) + 1 if versions else 0
    if jax.process_count() > 1:
        from jax.experimental import multihost_utils
        import numpy as np

        n = int(multihost_utils.broadcast_one_to_all(np.int32(n)))
    run_dir = os.path.join(base, f"version_{n}")
    if jax.process_index() == 0:
        os.makedirs(run_dir, exist_ok=True)
    return run_dir


class MetricsLogger:
    """Scalar + text logging to TensorBoard events and ``metrics.jsonl``."""

    def __init__(self, run_dir: str, use_tensorboard: bool = True,
                 registry: Optional[obs.MetricsRegistry] = None):
        self.run_dir = run_dir
        self._registry = registry if registry is not None else obs.get_registry()
        self._is_writer = jax.process_index() == 0
        self._jsonl = None
        self._tb = None
        if not self._is_writer:
            return
        os.makedirs(run_dir, exist_ok=True)
        self._jsonl = open(os.path.join(run_dir, "metrics.jsonl"), "a")
        if use_tensorboard:
            try:
                from torch.utils.tensorboard import SummaryWriter

                self._tb = SummaryWriter(log_dir=run_dir)
            except Exception:
                self._tb = None

    def log_scalars(self, step: int, metrics: Dict[str, float]) -> None:
        values = {k: float(v) for k, v in metrics.items()}
        # registry gauges first: every process records locally (the export
        # edge is process-0-gated), so /statz mirrors metrics.jsonl exactly
        self._registry.gauge("logged_step", "last step log_scalars saw").set(step)
        for k, v in values.items():
            self._registry.gauge(k).set(v)
        if not self._is_writer:
            return
        self._jsonl.write(json.dumps({"step": int(step), **values}) + "\n")
        if self._tb is not None:
            for k, v in values.items():
                self._tb.add_scalar(k, v, int(step))

    def log_text(self, tag: str, step: int, text: str) -> None:
        """Free-text logging — the sample-prediction channel (reference
        ``train_mlm.py:55-56``)."""
        if not self._is_writer:
            return
        self._jsonl.write(
            json.dumps({"step": int(step), "tag": tag, "text": text}) + "\n"
        )
        if self._tb is not None:
            self._tb.add_text(tag, text, int(step))

    def flush(self) -> None:
        if self._jsonl is not None:
            self._jsonl.flush()
        if self._tb is not None:
            self._tb.flush()

    def close(self) -> None:
        if self._jsonl is not None:
            self.flush()
            self._jsonl.close()
            self._jsonl = None
        if self._tb is not None:
            self._tb.close()
            self._tb = None

    def __enter__(self) -> "MetricsLogger":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


def read_metrics(run_dir: str) -> list:
    """Parse ``metrics.jsonl`` back (tests / analysis)."""
    path = os.path.join(run_dir, "metrics.jsonl")
    if not os.path.exists(path):
        return []
    with open(path) as f:
        return [json.loads(line) for line in f if line.strip()]
