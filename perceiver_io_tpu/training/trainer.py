"""The training driver: epochs, eval, checkpointing, metrics, profiling.

The TPU-native replacement for the reference's ``pl.Trainer`` usage
(reference ``train_mlm.py:59-76``): the loop owns

- the jitted/pjitted step (single device, or SPMD over a mesh — the DDP
  replacement; pass a ``Mesh`` and the batch axis shards over ``data``),
- per-epoch (or every-N-steps) validation with weighted metric averaging,
- best-by-``val_loss`` top-k checkpointing with embedded hparams (reference
  ``train/utils.py:11-13`` + ``lightning.py:46`` semantics),
- TensorBoard/JSONL scalar logging incl. per-step LR (the reference's
  ``LearningRateMonitor``) and throughput/MFU accounting the reference lacks,
- optional profiler trace capture and per-step trace annotations,
- a ``predict_hook`` called after each validation pass — the sample-prediction
  channel (reference ``train_mlm.py:44-56``).

The trainer is model-agnostic: it drives any ``(state, batch) → (state,
metrics)`` train step and ``(state, batch, key) → metrics`` eval step over
dict-of-arrays loaders (``data/pipeline.py``).
"""

from __future__ import annotations

import dataclasses
import os
import signal
import threading
import time
import warnings
from typing import Any, Callable, Dict, Optional, Sequence, Tuple

import jax
import numpy as np

import perceiver_io_tpu.obs as obs
from perceiver_io_tpu.parallel.mesh import AXIS_SEQ, sequence_parallel_context
from perceiver_io_tpu.resilience import (
    RetryPolicy,
    call_with_retry,
    faults,
    is_transient,
)
from perceiver_io_tpu.parallel.sharding import (
    PARAM_RULES,
    batch_shardings,
    make_sharded_train_step,
)
from perceiver_io_tpu.training.checkpoint import CheckpointManager
from perceiver_io_tpu.training.metrics import MetricsLogger, next_version_dir
from perceiver_io_tpu.utils import profiling

Batch = Dict[str, np.ndarray]
Metrics = Dict[str, Any]

# bit 0 of the coordination-flags bitmask: this host observed SIGTERM and
# asks the fleet to checkpoint-and-exit at the next agreed step boundary
_PREEMPT_BIT = 1


@dataclasses.dataclass(frozen=True)
class TrainerConfig:
    """Loop-control surface (the reference's Trainer argparse flags)."""

    max_epochs: Optional[int] = None
    max_steps: Optional[int] = None
    log_every_n_steps: int = 50
    eval_every_n_steps: Optional[int] = None  # None → validate per epoch
    # Multi-step dispatch: lax.scan K optimizer steps per device call. On
    # dispatch-latency-bound hosts (remote/tunneled accelerators) this is
    # what closes the trainer-loop vs device-step gap (PERF.md); K=1 keeps
    # classic per-step dispatch. Logging/eval cadences still count optimizer
    # steps (boundaries are honored at the next dispatch edge).
    steps_per_dispatch: int = 1
    logdir: str = "logs"
    experiment: str = "default"
    monitor: str = "val_loss"
    mode: str = "min"
    max_to_keep: int = 1
    async_checkpoint: bool = True
    use_tensorboard: bool = True
    # XLA cost-analysis FLOPs → in-loop MFU metric. Two caveats vs the
    # authoritative tools/hbm_roofline.py number: cost analysis counts ZERO
    # flops for Pallas custom-calls (configs whose hot ops run in the
    # kernels — e.g. flow — under-report here), and the denominator is WALL
    # time (tunnel/dispatch stalls deflate it relative to device time).
    compute_mfu: bool = True
    profile_steps: int = 0  # capture a trace of this many steps after warmup
    profile_start_step: int = 10
    # In-loop self-profiling watchdog (obs.SelfProfiler): every N optimizer
    # steps, capture a short device trace, analyze it in-process (the
    # utils/xplane.py lower-quartile discipline — the clock the tunnel cannot
    # distort), and publish device/host step time + MFU + compile count as
    # registry gauges AND metrics.jsonl rows. 0 disables. Unlike the in-loop
    # wall-clock MFU above, these numbers ride the DEVICE clock.
    selfprofile_every_n_steps: int = 0
    selfprofile_steps: int = 4  # dispatches per capture window
    # preemption safety (SURVEY.md §5, restart-on-failure): on SIGTERM, save
    # the CURRENT state to the checkpoint dir's unconditional last/ slot and
    # stop cleanly; restore_train_state(prefer_latest=True) resumes from it.
    checkpoint_on_sigterm: bool = True
    # failure detection (SURVEY.md §5): a non-finite train loss means the
    # params are already poisoned (NaN grads → NaN moments) and the run can
    # never recover — halt at the next log point instead of burning the rest
    # of the schedule. Checked only at log boundaries, where the loss scalar
    # is fetched anyway (no extra device sync on the hot path).
    halt_on_nonfinite: bool = True
    # NaN LOCALIZATION (the sanitizer tier above halt_on_nonfinite, which
    # only says THAT the run diverged): enables jax_debug_nans, so the first
    # dispatch producing a NaN/Inf re-runs de-optimized and raises
    # FloatingPointError pointing at the originating op. Debug mode: every
    # dispatch syncs to host, and the single-device path stops donating the
    # train state (the de-optimized re-run replays the same arguments, which
    # donation would have invalidated). Use for post-mortems, not production.
    debug_nans: bool = False
    # SELF-HEALING (SURVEY.md §5 actuation; perceiver_io_tpu.resilience).
    # skip_nonfinite_steps: check the loss after EVERY dispatch; a non-finite
    # step is SKIPPED (the pre-step state is kept, the poisoned update
    # discarded) instead of silently poisoning the moments the way the
    # halt_on_nonfinite log-boundary check can only report after the fact.
    # After rollback_after_bad_steps CONSECUTIVE bad steps the trainer
    # restores the newest checkpoint (prefer_latest — the last/ slot when
    # present; one is saved at fit() start if none exists yet) and continues.
    # Recovery mode costs one host sync per dispatch and disables train-state
    # donation (the kept pre-step state must stay alive) — a measured
    # robustness/throughput trade, off by default.
    skip_nonfinite_steps: bool = False
    rollback_after_bad_steps: int = 3
    # dispatch_error_retries: re-dispatch the SAME batch with exponential
    # backoff when the step raises an error the taxonomy calls transient
    # (tunnel drops, PJRT UNAVAILABLE); fatal errors raise immediately.
    # Implies the per-dispatch sync too (async errors must surface inside
    # the retry scope). 0 disables.
    dispatch_error_retries: int = 0
    # fit_attempts: budget for fit_with_recovery's supervisor loop — on a
    # transient failure escaping the per-dispatch retries, auto-resume from
    # the newest checkpoint up to this many total attempts.
    fit_attempts: int = 1
    # MULTI-HOST FAULT TOLERANCE (resilience/multihost.py, PERF.md
    # §Multi-host recovery). step_timeout_s: bounded-exit deadline on the
    # dispatch cycle — if the host observes no step completion within this
    # window (the wedged-dead-collective signature) it dumps thread stacks
    # and exits with the TRANSIENT code so the restart-the-world supervisor
    # (--spawn_attempts) relaunches from the newest checkpoint. None = off.
    step_timeout_s: Optional[float] = None
    # peer_heartbeat_s: publish/scan cadence of the KV-store peer-liveness
    # monitor (multi-host only; detects a SILENTLY dead peer even between
    # collectives). Peer declared down after 5 missed beats. 0 = off.
    peer_heartbeat_s: float = 0.0
    # coord_check_dispatches: cadence (in dispatches) of the agreement-flag
    # READ on the coordination channel. The flag always rides every
    # dispatch on device; fetching its scalar is a host sync on the
    # previous dispatch, so 1 (the default, and what the chaos drills pin)
    # trades host run-ahead for a 2-dispatch preemption response, while a
    # dispatch-latency-bound transport (the axon tunnel: ~100 ms per scalar
    # fetch, PERF.md) should raise it — the schedule is identical on every
    # host for ANY value, so agreement stays deadlock-free, just later.
    coord_check_dispatches: int = 1
    # testing only: run the multi-host coordination channel on a single
    # process (agreement degenerates to one host's flags) — the tier-1
    # harness for the preemption-agreement plumbing, which otherwise only
    # executes under jax.process_count() > 1.
    force_coordination: bool = False
    # CONTINUOUS DEPLOYMENT (perceiver_io_tpu.deploy, PERF.md §Deployment):
    # every publish_every_n_steps optimizer steps, atomically publish the
    # CURRENT params to publish_dir with a manifest (step, val metrics,
    # content digest, package version) — the trainer half of the train→serve
    # loop. The serving side (cli/serve.py --watch_checkpoints) admission-
    # gates each publication before any replica sees it. Publication is
    # fail-soft: a failed publish warns and counts, never kills the run.
    # Single-process only (publishing device_gets the full tree; multi-host
    # global arrays are not host-addressable from one process).
    publish_dir: Optional[str] = None
    publish_every_n_steps: int = 0
    # COLD START (perceiver_io_tpu.aot, PERF.md §Cold start): point jax's
    # persistent compilation cache here so the train/eval step compiles
    # become disk hits across restarts/resumes — the tier the AOT executable
    # cache can't cover (the trainer's pjitted step is donation/sharding-
    # specialized and recompiles legitimately across config changes, but an
    # UNCHANGED config restarting — preemption resume, fit_with_recovery,
    # repeat bench sessions — should never re-pay the remote compile).
    # Fail-soft: an unusable directory warns and trains uncached.
    compile_cache: Optional[str] = None

    def __post_init__(self):
        if self.max_epochs is None and self.max_steps is None:
            raise ValueError("set max_epochs and/or max_steps")
        if self.dispatch_error_retries < 0:
            raise ValueError(
                f"dispatch_error_retries must be >= 0, got "
                f"{self.dispatch_error_retries}"
            )
        if self.fit_attempts < 1:
            raise ValueError(f"fit_attempts must be >= 1, got {self.fit_attempts}")
        if self.step_timeout_s is not None and self.step_timeout_s <= 0:
            raise ValueError(
                f"step_timeout_s must be positive, got {self.step_timeout_s}")
        if self.peer_heartbeat_s < 0:
            raise ValueError(
                f"peer_heartbeat_s must be >= 0, got {self.peer_heartbeat_s}")
        if self.coord_check_dispatches < 1:
            raise ValueError(
                f"coord_check_dispatches must be >= 1, got "
                f"{self.coord_check_dispatches}")
        if (self.publish_dir is None) != (self.publish_every_n_steps <= 0):
            raise ValueError(
                "checkpoint publication needs BOTH publish_dir and "
                "publish_every_n_steps > 0 (got "
                f"publish_dir={self.publish_dir!r}, "
                f"publish_every_n_steps={self.publish_every_n_steps})"
            )

    @property
    def recovery_active(self) -> bool:
        """True when fit() runs the per-dispatch recovery path (loss sync,
        no state donation)."""
        return self.skip_nonfinite_steps or self.dispatch_error_retries > 0


class Trainer:
    """Drives jitted steps over data loaders; owns logging and checkpoints.

    Args:
      train_step: pure ``(state, batch) → (state, metrics)``.
      eval_step: pure ``(state, batch, key) → metrics`` (the key feeds
        stochastic eval such as MLM masking; ignore it for deterministic eval).
      state: initial ``TrainState``.
      example_batch: defines the step input contract (keys + shapes); loader
        batches may carry extra keys, which the trainer drops.
      mesh: optional ``jax.sharding.Mesh`` — SPMD mode: params/opt-state are
        placed by the sharding rules, the batch shards over ``data`` (and
        optionally ``seq``), gradient sync becomes a compiler-inserted psum.
      zero_opt: shard the optimizer state over ``data`` (ZeRO-style; SURVEY
        §2.3) — per-chip Adam mu/nu footprint drops by the dp size.
      hparams: JSON-serializable config embedded in checkpoints
        (``save_hyperparameters`` parity).
      predict_hook: ``(state, logger, step) → None`` called after each
        validation pass.
      tokens_per_example: when set, throughput is also logged as tokens/sec.
    """

    def __init__(
        self,
        train_step: Callable,
        eval_step: Optional[Callable],
        state,
        config: TrainerConfig,
        example_batch: Batch,
        mesh=None,
        shard_seq: bool = False,
        zero_opt: bool = False,
        rules: Sequence = PARAM_RULES,
        hparams: Optional[Dict[str, Any]] = None,
        predict_hook: Optional[Callable] = None,
        tokens_per_example: Optional[int] = None,
        run_dir: Optional[str] = None,
    ):
        self.config = config
        if config.compile_cache:
            from perceiver_io_tpu.aot import (
                enable_persistent_compilation_cache,
            )

            # before the first step compiles (reset_cache inside makes this
            # safe even though the backend is already up)
            enable_persistent_compilation_cache(config.compile_cache)
        if ((config.dispatch_error_retries > 0 or config.fit_attempts > 1)
                and jax.process_count() > 1):
            # A dispatch retry RE-ENTERS a collective a peer already left
            # (the peers advanced past the program the retry replays), and a
            # fit_with_recovery restart does the same one level up — both
            # deadlock the job in mismatched programs, so they stay
            # single-process only. skip_nonfinite_steps is DIFFERENT since
            # r19: the skip is a device-side select driven by the globally
            # psummed loss (training/steps.py make_guarded_step), so every
            # host takes the identical branch and no program diverges.
            # Multi-host process-death recovery is restart-the-world
            # (--spawn_attempts supervision / --resume), which every host
            # performs identically.
            raise ValueError(
                "trainer dispatch retries / fit attempts "
                "(dispatch_error_retries / fit_attempts > 1) are "
                "single-process only — multi-host runs recover by "
                "restarting the world from the newest checkpoint "
                "(--spawn_attempts / --resume)"
            )
        if (config.skip_nonfinite_steps and jax.process_count() > 1
                and mesh is None):
            raise ValueError(
                "skip_nonfinite_steps under multiple processes needs a mesh: "
                "without one there is no collective for hosts to agree on "
                "the bad-step flag over (each host would train — and skip — "
                "independently)"
            )
        self._publisher = None
        if config.publish_dir:
            if jax.process_count() > 1:
                # publishing device_gets the FULL param tree; a multi-host
                # global array is not addressable from one process — the
                # multi-host deployment story is checkpoint-dir based
                raise ValueError(
                    "checkpoint publication (publish_dir) is single-process "
                    "only"
                )
            from perceiver_io_tpu.deploy import CheckpointPublisher

            self._publisher = CheckpointPublisher(config.publish_dir)
        self.mesh = mesh
        self.predict_hook = predict_hook
        self.tokens_per_example = tokens_per_example
        self._keys = tuple(sorted(example_batch))
        self._example_batch = {k: example_batch[k] for k in self._keys}

        self.run_dir = run_dir or next_version_dir(config.logdir, config.experiment)
        self.logger = MetricsLogger(self.run_dir, use_tensorboard=config.use_tensorboard)
        self.checkpoints = CheckpointManager(
            os.path.join(self.run_dir, "checkpoints"),
            max_to_keep=config.max_to_keep,
            monitor=config.monitor,
            mode=config.mode,
            hparams=hparams,
            async_save=config.async_checkpoint,
        )

        self._raw_train_step = train_step
        self._k = max(1, int(config.steps_per_dispatch))
        self._prev_debug_nans = None
        if config.debug_nans:
            # restored in __exit__ — a post-mortem Trainer must not leak
            # process-global debug mode into later work
            self._prev_debug_nans = jax.config.jax_debug_nans
            jax.config.update("jax_debug_nans", True)
        step_fn = train_step
        if config.skip_nonfinite_steps:
            # device-side collective-consistent skip: the select rides the
            # step itself, so the decision is bit-identical on every host
            # (and on every sub-step of a scanned window — wrap BEFORE scan)
            from perceiver_io_tpu.training.steps import make_guarded_step

            step_fn = make_guarded_step(step_fn)
        step_example = self._example_batch
        if self._k > 1:
            from perceiver_io_tpu.training.steps import make_scanned_step

            step_fn = make_scanned_step(step_fn)
            step_example = {
                k: np.stack([v]) for k, v in self._example_batch.items()
            }
        # Multi-host coordination channel (the preemption-agreement psum):
        # host-local flags ride every dispatch as a sharded int32 vector and
        # come back agreed (see parallel/sharding.py coord_flags_sharding).
        self._coord = (
            mesh is not None
            and config.checkpoint_on_sigterm
            and (jax.process_count() > 1 or config.force_coordination)
        )
        # donation is off under debug_nans (the de-optimized re-run replays
        # the original arguments) AND under recovery (a skipped bad step
        # keeps serving the PRE-step state, and a transient retry re-runs the
        # dispatch with it — donation would have invalidated both)
        no_donate = config.debug_nans or config.recovery_active
        self.donates_state = not no_donate
        if mesh is not None:
            self._train_step, self.state, self._batch_shardings = (
                make_sharded_train_step(
                    step_fn, mesh, state, step_example,
                    rules=rules, shard_seq=shard_seq, zero_opt=zero_opt,
                    stacked=self._k > 1,
                    donate_state=not no_donate,
                    coord_flags=self._coord,
                )
            )
            # Eval batches are never stacked (no scan axis) — with
            # steps_per_dispatch > 1 the train shardings above carry a leading
            # scan rank that would not match an eval array, so eval keeps its
            # own unstacked sharding plan.
            self._eval_batch_shardings = batch_shardings(
                self._example_batch, mesh, shard_seq
            )
        else:
            donate = () if no_donate else (0,)
            jitted = jax.jit(step_fn, donate_argnums=donate)
            self._train_step = lambda s, b: jitted(s, {k: b[k] for k in self._keys})
            self._train_step.jitted = jitted
            self.state = state
            self._batch_shardings = None
            self._eval_batch_shardings = None

        self._eval_step = None
        if eval_step is not None:
            if mesh is not None and shard_seq and mesh.shape[AXIS_SEQ] > 1:
                # same sequence-parallel kernel routing as the train step
                inner_eval = eval_step

                def eval_step(s, b, k):
                    with sequence_parallel_context(mesh):
                        return inner_eval(s, b, k)

            jitted_eval = jax.jit(eval_step)
            self._eval_step = lambda s, b, k: jitted_eval(
                s, {key: b[key] for key in self._keys}, k
            )

        self._flops_per_step: Optional[float] = None
        self._flops_attempted = False
        self._eval_key = jax.random.key(4242)

        # recovery telemetry: the chaos drills (tests/test_resilience.py)
        # assert these, and operators watch them the same way they watch the
        # serving shed/retry counters
        reg = obs.get_registry()
        self._m_bad_steps = reg.counter(
            "trainer_bad_steps_total", "non-finite train steps skipped")
        self._m_rollbacks = reg.counter(
            "trainer_rollbacks_total",
            "checkpoint rollbacks after consecutive bad steps")
        self._m_step_retries = reg.counter(
            "trainer_dispatch_retries_total",
            "transient train-dispatch retries")
        self._m_restarts = reg.counter(
            "trainer_fit_restarts_total",
            "fit_with_recovery auto-resumes after transient failures")
        self._m_preempt_saves = reg.counter(
            "trainer_preempt_saves_total",
            "SIGTERM-triggered preemption checkpoints (coordinated across "
            "all hosts under multi-process)")
        self._g_agreed = reg.gauge(
            "multihost_last_step_agreed",
            "optimizer step of the newest completed cross-host flag "
            "agreement round (coordination-channel liveness)")
        self._retry_policy = RetryPolicy(
            max_retries=config.dispatch_error_retries)
        self._bad_streak = 0
        self._sigterm = False
        self._pending_flags = None
        self._agreed_preempt = False
        self._coord_dispatch = 0
        self._last_val_metrics: Dict[str, float] = {}
        self._last_train_loss = float("nan")

        self._selfprof = None
        if config.selfprofile_every_n_steps > 0:
            from perceiver_io_tpu.obs import SelfProfiler

            self._selfprof = SelfProfiler(
                every_n=config.selfprofile_every_n_steps,
                trace_steps=config.selfprofile_steps,
                prefix="train",
                flops_per_step=lambda: self._flops_per_step,
                num_devices=(mesh.size if mesh is not None else 1),
            )

    # -- internals -----------------------------------------------------------

    def _to_global(self, batch: Batch, shardings=None) -> Batch:
        """Host-local loader batch → global sharded arrays (multi-host only).

        Per-host loaders yield each process's shard of the global batch
        (reference DDP semantics: Lightning's DistributedSampler gives every
        rank its own slice). A mesh-sharded jit consumes GLOBAL arrays, so in
        multi-process mode each local batch becomes this process's shard of a
        global ``jax.Array`` — the multi-host equivalent of device_put.

        ``shardings`` defaults to the train-step plan; eval passes its own
        (unstacked) plan, which differs whenever ``steps_per_dispatch > 1``.
        """
        if shardings is None:
            shardings = self._batch_shardings
        if shardings is None or jax.process_count() == 1:
            return batch
        return {
            k: jax.make_array_from_process_local_data(
                shardings[k], np.asarray(batch[k])
            )
            for k in self._keys
        }

    def _maybe_compute_flops(self, batch: Batch) -> None:
        """Lazily derive per-step FLOPs from XLA cost analysis (once).

        Only attempted on devices with a known peak (TPUs) — elsewhere MFU is
        undefined and the lowering is wasted work. The lowering reuses the
        exact jit wrapper driving training (same shardings/donation), so the
        compiled executable comes from jit's cache — no second compile.

        The dispatch width (``steps_per_dispatch``) deliberately does NOT
        enter here: XLA cost analysis counts a ``lax.scan`` body ONCE
        regardless of trip count (``test_scanned_step_cost_analysis_is_per_
        step``), so the K-step scanned executable's reported flops already
        ARE per-step flops. Dividing by K made the in-loop MFU metric K×
        too low under multi-step dispatch (r4: the flagship_tpu soak logged
        3.1% in-loop vs 53.6% trace-measured at K=16).
        """
        if self._flops_attempted or not self.config.compute_mfu:
            return
        self._flops_attempted = True
        if jax.process_count() > 1:
            # lowering with a host-local example would trace a second (wrong)
            # shape; per-host cost attribution is not meaningful anyway
            return
        if profiling.device_peak_flops() is None:
            return
        flops = profiling.compiled_flops(
            self._train_step.jitted,
            self.state,
            {k: batch[k] for k in self._keys},
        )
        self._flops_per_step = flops

    def _warn_if_trace_empty(self) -> None:
        """Post-capture sanity: very long profile windows (tens of device-
        seconds — e.g. profile_steps counting optimizer steps under a large
        steps_per_dispatch) can silently overflow the xplane export, leaving
        a 0-byte ``*.xplane.pb`` next to a populated json trace (observed
        r4: a 320-step K=16 window). Warn instead of letting the user
        discover it at analysis time."""
        import glob as _glob

        dirs = sorted(_glob.glob(os.path.join(
            self.run_dir, "plugins", "profile", "*")))
        if not dirs:
            return
        # newest capture dir only (timestamp-named), ANY empty per-host file
        # counts — one overflowed host must not hide behind another's
        # populated export
        paths = _glob.glob(os.path.join(dirs[-1], "*.xplane.pb"))
        if paths and any(os.path.getsize(p) == 0 for p in paths):
            warnings.warn(
                "profiler capture produced an EMPTY xplane.pb — the profile "
                "window was likely too long for the xplane export (note "
                "profile_steps counts OPTIMIZER steps: a K-step dispatch "
                "advances it by K). Use a window of at most a few seconds "
                "of device time.", stacklevel=2,
            )

    def _dispatch_batches(self, loader):
        """Yield ``(batch, n_steps)`` dispatch units: single loader batches
        (K=1), or up to K of them stacked on a new leading scan axis. A
        window is flushed early when the next batch's SHAPES differ (width-
        bucketed text loaders emit same-width runs of K — data/pipeline.py
        ``group_size`` — so early flushes only happen at run boundaries);
        partial windows compile once per (length, shape) and are cached
        across epochs. Batches are always consumed in loader order, which is
        what keeps the mid-epoch resume arithmetic (``skip_next``) exact."""
        if self._k <= 1:
            for batch in loader:
                yield batch, 1
            return
        buf, sig = [], None
        for batch in loader:
            shapes = {k: np.asarray(batch[k]).shape for k in self._keys}
            if buf and shapes != sig:
                yield self._stack(buf), len(buf)
                buf = []
            buf.append(batch)
            sig = shapes
            if len(buf) == self._k:
                yield self._stack(buf), self._k
                buf = []
        if buf:
            yield self._stack(buf), len(buf)

    def _stack(self, batches):
        return {
            k: np.stack([np.asarray(b[k]) for b in batches])
            for k in self._keys
        }

    def _throughput_metrics(
        self, n_steps: int, elapsed: float, batch_size: int
    ) -> Dict[str, float]:
        out: Dict[str, float] = {}
        if elapsed <= 0 or n_steps == 0:
            return out
        steps_per_sec = n_steps / elapsed
        out["steps_per_sec"] = steps_per_sec
        out["examples_per_sec"] = steps_per_sec * batch_size
        if self.tokens_per_example:
            out["tokens_per_sec"] = out["examples_per_sec"] * self.tokens_per_example
        if self._flops_per_step:
            u = profiling.mfu(
                self._flops_per_step * n_steps, elapsed,
                num_devices=(self.mesh.size if self.mesh is not None else 1),
            )
            if u is not None:
                out["mfu"] = u
        return out

    # -- multi-host coordination (resilience) --------------------------------

    def _local_flags_array(self):
        """This host's flag bitmask as its shard of the coordination vector
        (one int32 per local device, all equal — see ``coord_flags_sharding``
        for why the per-device layout is irrelevant)."""
        bits = _PREEMPT_BIT if self._sigterm else 0
        n = jax.local_device_count()
        return jax.make_array_from_process_local_data(
            self._train_step.coord_flags_sharding,
            np.full((n,), bits, np.int32),
            (jax.device_count(),),
        )

    def _dispatch(self, batch):
        """One train dispatch; feeds the coordination flags when the
        multi-host agreement channel is active."""
        # chaos hook over the HOST-LOCAL batch: nan = one host's shard
        # corrupted (its NaN rides the global loss reduction to every peer —
        # the agreement drill), hang/slow = a wedged/throttled host
        batch = faults.fire("trainer.collective", batch)
        gb = self._to_global(batch)
        if self._coord:
            return self._train_step(self.state, gb, self._local_flags_array())
        return self._train_step(self.state, gb)

    def _note_coord(self, metrics: Metrics, step_i: int) -> None:
        """Consume the agreed-flags output of THIS dispatch, and read the
        one from the PREVIOUS dispatch (already complete, so the read never
        waits on in-flight device work — though it IS one scalar fetch, a
        host round-trip the ``coord_check_dispatches`` cadence amortizes on
        dispatch-latency-bound transports). Every host runs this identical
        deterministic schedule over identical device-agreed values, so
        every host observes an agreed preemption at the same dispatch
        boundary — ``coord_check_dispatches + 1`` dispatches after the
        first host's SIGTERM at the latest."""
        if not self._coord or metrics is None:
            return
        flags = metrics.pop("coord_flags", None)
        prev, self._pending_flags = self._pending_flags, flags
        self._coord_dispatch += 1
        if prev is None or (
                self._coord_dispatch % self.config.coord_check_dispatches):
            return
        agreed = int(jax.device_get(prev))
        self._g_agreed.set(step_i)
        if agreed & _PREEMPT_BIT:
            self._agreed_preempt = True

    def _preempt_save(self, step_i: int) -> None:
        """The preemption checkpoint: save the CURRENT state to the
        unconditional ``last/`` slot and flush logs. Under multi-process
        every host reaches this at the SAME dispatch boundary (the agreed
        flag is device-replicated), so the Orbax save's internal collectives
        line up and every rank exits 0."""
        self.checkpoints.save_last(step_i, self.state)
        self._m_preempt_saves.inc()
        obs.event("trainer_preempt_save", step=step_i,
                  coordinated=self._coord)
        self.logger.log_text(
            "events", step_i,
            f"SIGTERM: saved last/ checkpoint at step {step_i}"
            + (" (coordinated across hosts)" if self._coord else ""),
        )
        self.logger.flush()

    # -- self-healing (resilience) -------------------------------------------

    def _ensure_rollback_target(self, step_i: int) -> None:
        """Make sure a rollback has somewhere to land: with no checkpoint yet
        (bad steps can hit before the first validation pass), save the
        CURRENT state to the unconditional ``last/`` slot."""
        if self.checkpoints.latest_step is None:
            self.checkpoints.save_last(step_i, self.state)

    def _rollback(self, step_i: int) -> None:
        """K consecutive bad steps: the in-memory state is presumed poisoned
        (NaN moments survive a skipped update's discard only if the corruption
        predates the streak) — restore the newest checkpoint and continue."""
        from perceiver_io_tpu.training.checkpoint import restore_train_state

        self.checkpoints.wait()
        restored = restore_train_state(
            self.checkpoints.directory, self.state, prefer_latest=True
        )
        self.state = restored
        self._bad_streak = 0
        self._m_rollbacks.inc()
        to_step = int(jax.device_get(restored.step))
        obs.event("trainer_rollback", from_step=step_i, to_step=to_step)
        self.logger.log_text(
            "events", step_i,
            f"{self.config.rollback_after_bad_steps} consecutive non-finite "
            f"steps: rolled back to checkpoint step {to_step}",
        )
        self.logger.flush()

    def _recovering_step(self, batch, step_i: int):
        """One dispatch under the recovery config: transient-error retry with
        backoff, per-dispatch finite check, skip / rollback. Returns
        ``(status, metrics)`` with status ``'ok'`` (state advanced),
        ``'skipped'`` (bad step discarded — the caller must re-read
        ``state.step``, since a scanned window may have applied its good
        sub-steps on device) or ``'rolled_back'`` (state restored from
        checkpoint — same re-read contract).

        The ``float(loss)`` here is the recovery mode's per-dispatch host
        sync: it surfaces async dispatch errors INSIDE the retry scope and
        feeds the finite guard (the documented robustness/throughput trade).

        The skip DECISION comes from two tiers: the device-agreed
        ``bad_step`` flag (``make_guarded_step`` — the select already kept
        the pre-step state on device, identically on every host), and — on a
        single process only — the host-observed loss value, which catches
        host-side corruption (the ``trainer.metrics`` chaos drills). Under
        multiple processes the host-side observation deliberately does NOT
        drive the decision: a per-host verdict on a per-host value is
        exactly the program divergence that deadlocks collectives.
        """
        cfg = self.config

        def attempt():
            faults.inject("trainer.dispatch")  # chaos hook (no-op unless
            with profiling.annotate_step(step_i):  # an injector is live)
                new_state, metrics = self._dispatch(batch)
            metrics = faults.corrupt("trainer.metrics", metrics)
            loss = float(metrics["loss"]) if "loss" in metrics else None
            return new_state, metrics, loss

        def on_retry(retry: int, error: BaseException, pause: float) -> None:
            self._m_step_retries.inc()
            obs.event("trainer_dispatch_retry", retry=retry,
                      error=type(error).__name__, backoff_s=round(pause, 4))
            self.logger.log_text(
                "events", step_i,
                f"transient dispatch error ({type(error).__name__}: {error});"
                f" retry {retry}/{self._retry_policy.max_retries} after "
                f"{pause:.2f}s",
            )

        new_state, metrics, loss = call_with_retry(
            attempt, policy=self._retry_policy, on_retry=on_retry
        )
        self._note_coord(metrics, step_i)
        flag = metrics.get("bad_step")
        # int32 flag: immune to host-side NaN corruption of the metrics, and
        # already the fleet-agreed verdict (see make_guarded_step)
        device_bad = flag is not None and int(jax.device_get(flag)) > 0
        host_bad = loss is not None and not np.isfinite(loss)
        single = jax.process_count() == 1
        if cfg.skip_nonfinite_steps and (device_bad or (host_bad and single)):
            if device_bad:
                # the device select already kept the pre-step state (and
                # applied any good sub-steps of a scanned window) — adopt it
                self.state = new_state
            self._bad_streak += 1
            self._m_bad_steps.inc()
            obs.event("trainer_bad_step", step=step_i, loss=str(loss),
                      streak=self._bad_streak)
            self.logger.log_text(
                "events", step_i,
                f"non-finite loss {loss} at step {step_i}: step skipped, "
                f"pre-step state kept (streak {self._bad_streak})",
            )
            if (cfg.rollback_after_bad_steps > 0
                    and self._bad_streak >= cfg.rollback_after_bad_steps):
                self._rollback(step_i)
                return "rolled_back", None
            return "skipped", None
        self._bad_streak = 0
        self.state = new_state
        return "ok", metrics

    def fit_with_recovery(self, train_loader, val_loader=None,
                          max_attempts: Optional[int] = None):
        """:meth:`fit` under a supervisor: an attempt that dies with a
        TRANSIENT error (``resilience.classify_error`` — tunnel drops, PJRT
        UNAVAILABLE; never divergence or shape bugs) auto-resumes from the
        newest checkpoint (``prefer_latest``, the same path ``--resume``
        takes — falling back to the in-memory state when none exists yet) and
        retries, up to ``max_attempts`` total attempts (default
        ``config.fit_attempts``). Completes the SIGTERM/resume story for
        failures that kill the step instead of the process."""
        from perceiver_io_tpu.training.checkpoint import restore_train_state

        attempts = max(1, int(self.config.fit_attempts if max_attempts is None
                              else max_attempts))
        for attempt in range(1, attempts + 1):
            try:
                return self.fit(train_loader, val_loader)
            except Exception as e:
                if attempt >= attempts or not is_transient(e):
                    raise
                self._m_restarts.inc()
                obs.event("trainer_fit_restart", attempt=attempt,
                          error=type(e).__name__)
                try:
                    self.checkpoints.wait()
                    self.state = restore_train_state(
                        self.checkpoints.directory, self.state,
                        prefer_latest=True,
                    )
                except FileNotFoundError:
                    pass  # nothing saved yet: resume from the in-memory state
                resumed = int(jax.device_get(self.state.step))
                self.logger.log_text(
                    "events", resumed,
                    f"fit attempt {attempt} failed with transient "
                    f"{type(e).__name__}: {e}; auto-resuming from step "
                    f"{resumed} ({attempts - attempt} attempts left)",
                )
                self.logger.flush()

    def _run_eval(self, val_loader) -> Dict[str, float]:
        totals: Dict[str, float] = {}
        weight = 0.0
        for i, batch in enumerate(val_loader):
            self._eval_key, key = jax.random.split(self._eval_key)
            metrics = self._eval_step(
                self.state,
                self._to_global(batch, self._eval_batch_shardings),
                key,
            )
            # weight by the LOCAL shard size: with global eval batches every
            # host computes identical metrics, and the cross-host sum below
            # then weights each global batch by its true global size
            n = len(batch[self._keys[0]])
            for k, v in metrics.items():
                totals[k] = totals.get(k, 0.0) + float(v) * n
            weight += n
        if jax.process_count() > 1:
            # every host evaluates its own shard; reduce sums so all hosts log
            # identical metrics and make identical best-checkpoint decisions
            from jax.experimental import multihost_utils

            names = sorted(totals)
            local = np.asarray([totals[k] for k in names] + [weight], np.float64)
            summed = np.sum(multihost_utils.process_allgather(local), axis=0)
            totals = dict(zip(names, summed[:-1]))
            weight = summed[-1]
        if weight == 0:
            return {}
        return {f"val_{k}": v / weight for k, v in totals.items()}

    def _publish(self, step_i: int) -> None:
        """Publish the CURRENT params (deploy.CheckpointPublisher — atomic,
        manifest-carrying, fail-soft). Metrics in the manifest: the newest
        validation pass plus the last logged train loss, so the serving-side
        gate (and operators) can see what quality the tree claims."""
        metrics = dict(self._last_val_metrics)
        if np.isfinite(self._last_train_loss):
            metrics.setdefault("train_loss", float(self._last_train_loss))
        self._publisher.publish(
            step_i, jax.device_get(self.state.params), val_metrics=metrics)

    def _validate_and_checkpoint(self, step_i: int, val_loader) -> Dict[str, float]:
        val_metrics = self._run_eval(val_loader) if val_loader is not None else {}
        self._last_val_metrics = dict(val_metrics)
        if val_metrics:
            self.logger.log_scalars(step_i, val_metrics)
        ckpt_metrics = dict(val_metrics)
        if self.config.monitor in ckpt_metrics or val_loader is None:
            if val_loader is None:
                ckpt_metrics = {self.config.monitor: self._last_train_loss}
            self.checkpoints.save(step_i, self.state, ckpt_metrics)
        if self.predict_hook is not None:
            self.predict_hook(self.state, self.logger, step_i)
        self.logger.flush()
        return val_metrics

    def test(self, test_loader) -> Dict[str, float]:
        """One evaluation pass over a held-out split, logged as ``test_*``
        (the reference's ``test_step``/``test_epoch`` path,
        ``lightning.py:141-147`` — there the IMDB test split doubles as val,
        ``imdb.py:133``, so this is the explicit variant)."""
        if self._eval_step is None:
            raise ValueError("Trainer.test() needs an eval_step; this trainer "
                             "was constructed with eval_step=None")
        metrics = {
            k.replace("val_", "test_", 1): v
            for k, v in self._run_eval(test_loader).items()
        }
        if metrics:
            step_i = int(jax.device_get(self.state.step))
            self.logger.log_scalars(step_i, metrics)
            self.logger.flush()
        return metrics

    # -- the loop ------------------------------------------------------------

    def fit(self, train_loader, val_loader=None):
        """Run the training loop; returns the final state.

        ``train_loader`` is re-iterated per epoch (fresh shuffle each time);
        ``val_loader`` per validation pass.
        """
        cfg = self.config
        step_i = int(jax.device_get(self.state.step))
        epoch = 0
        done = False
        self._last_train_loss = float("nan")

        # restoring a completed run is a no-op, not one extra step
        if cfg.max_steps is not None and step_i >= cfg.max_steps:
            return self.state

        # Deterministic resume (SURVEY.md §5, failure detection): a restored
        # state starts at step > 0 — fast-forward the loader to the epoch and
        # in-epoch offset that step corresponds to, so the resumed run sees
        # exactly the batches the uninterrupted run would have (the loader
        # shuffles by seed ⊕ epoch, so epoch alignment is all it takes).
        if step_i > 0:
            try:
                steps_per_epoch = len(train_loader)
            except TypeError:
                steps_per_epoch = 0
            if steps_per_epoch > 0 and hasattr(train_loader, "epoch"):
                epoch = step_i // steps_per_epoch
                train_loader.epoch = epoch
                skip = step_i % steps_per_epoch
                if skip and hasattr(train_loader, "skip_next"):
                    train_loader.skip_next(skip)

        window_start = time.perf_counter()
        window_steps = 0
        seen_shapes: set = set()
        profiling_active = False
        profile_captured = False
        last_validated_step = step_i
        self._bad_streak = 0
        if cfg.skip_nonfinite_steps and cfg.rollback_after_bad_steps > 0:
            self._ensure_rollback_target(step_i)

        # SIGTERM = preemption notice: finish the in-flight step, save the
        # newest state unconditionally, stop cleanly. The handler only sets a
        # flag — all real work happens on the main thread between steps.
        # Single-process: the flag is acted on directly at the next step
        # boundary. Multi-process (coordination channel active): hosts
        # observe SIGTERM at different step boundaries, and Orbax saves of
        # mesh-sharded arrays are multi-host collectives — so the local flag
        # only rides the next dispatch's agreement psum, and EVERY host acts
        # on the agreed verdict at the same boundary (one coordinated
        # save_last, every rank exits 0). Multi-process WITHOUT a mesh has
        # no agreement channel: the handler stays uninstalled, and recovery
        # is restart-the-world (--spawn_attempts / --resume).
        self._sigterm = False
        self._pending_flags = None
        self._agreed_preempt = False
        self._coord_dispatch = 0
        handler_installed = False
        prev_handler = None
        if (cfg.checkpoint_on_sigterm
                and (jax.process_count() == 1 or self._coord)
                and threading.current_thread() is threading.main_thread()):
            def _on_sigterm(signum, frame):
                self._sigterm = True

            prev_handler = signal.signal(signal.SIGTERM, _on_sigterm)
            handler_installed = True

        # bounded-exit machinery (resilience/multihost.py): a per-step
        # deadline on the dispatch cycle, and — multi-process — the KV-store
        # peer-liveness monitor, so a surviving host never blocks past the
        # configured window inside a collective whose peer died
        step_guard = None
        peer_monitor = None
        if cfg.step_timeout_s:
            from perceiver_io_tpu.resilience.multihost import StepDeadline

            step_guard = StepDeadline("trainer_step", cfg.step_timeout_s)
        if cfg.peer_heartbeat_s > 0 and jax.process_count() > 1:
            from perceiver_io_tpu.resilience.multihost import (
                PeerLivenessMonitor,
            )

            peer_monitor = PeerLivenessMonitor(
                interval_s=cfg.peer_heartbeat_s).start()

        metrics: Metrics = {}
        try:
            while not done:
                if cfg.max_epochs is not None and epoch >= cfg.max_epochs:
                    break
                steps_this_epoch = 0
                batches_this_epoch = 0
                for batch, ksteps in self._dispatch_batches(train_loader):
                    batches_this_epoch += 1
                    # single-process: act on the local flag directly;
                    # coordinated: only on the fleet-AGREED flag, which every
                    # host observes at the same boundary
                    if (self._agreed_preempt
                            or (self._sigterm and not self._coord)):
                        self._preempt_save(step_i)
                        done = True
                        break
                    if cfg.max_steps is not None:
                        # never overshoot max_steps: trim the final window
                        remaining = cfg.max_steps - step_i
                        if remaining < ksteps:
                            batch = {
                                k: v[:remaining] for k, v in batch.items()
                            }
                            ksteps = remaining
                    if (
                        cfg.profile_steps > 0
                        and not profiling_active
                        and not profile_captured
                        and step_i >= cfg.profile_start_step
                        # the watchdog may hold the process's one trace slot
                        and not (self._selfprof is not None
                                 and self._selfprof._tracing)
                    ):
                        jax.profiler.start_trace(self.run_dir)
                        profiling_active = True
                        profile_start = step_i

                    # the first dispatch of every NEW batch-shape signature
                    # carries a jit compile (tens of seconds on CPU, minutes
                    # through a remote compiler — and width-bucketed loaders
                    # introduce new shapes mid-run): the per-step deadline
                    # only means something on already-compiled shapes; a
                    # peer dead during a compile is the peer-liveness
                    # monitor's catch
                    sig = (ksteps,) + tuple(
                        np.asarray(batch[k]).shape for k in self._keys)
                    if step_guard is not None and sig in seen_shapes:
                        step_guard.arm()
                    seen_shapes.add(sig)
                    if cfg.recovery_active:
                        status, stepped = self._recovering_step(batch, step_i)
                        if step_guard is not None:
                            step_guard.disarm()  # the recovery path synced
                        if status == "rolled_back":
                            # the restored state's step is authoritative; the
                            # loader stream continues from its current
                            # position (recovery favors forward progress over
                            # exact batch replay — logged above)
                            step_i = int(jax.device_get(self.state.step))
                            window_start = time.perf_counter()
                            window_steps = 0
                            continue
                        if status == "skipped":
                            # batch consumed; a scanned window may still have
                            # applied its good sub-steps on device — the
                            # selected state's step is authoritative
                            step_i = int(jax.device_get(self.state.step))
                            continue
                        metrics = stepped
                    else:
                        with profiling.annotate_step(step_i):
                            self.state, metrics = self._dispatch(batch)
                        self._note_coord(metrics, step_i)
                    prev_step = step_i
                    step_i += ksteps
                    window_steps += ksteps
                    steps_this_epoch += ksteps

                    if profiling_active and step_i >= profile_start + cfg.profile_steps:
                        jax.block_until_ready(metrics["loss"])
                        jax.profiler.stop_trace()
                        profiling_active = False
                        profile_captured = True
                        self._warn_if_trace_empty()

                    if self._selfprof is not None and not profiling_active:
                        sp = self._selfprof.tick(
                            ksteps,
                            sync=lambda: jax.block_until_ready(metrics),
                        )
                        if sp:
                            self.logger.log_scalars(step_i, sp)

                    n = cfg.log_every_n_steps
                    if step_i // n > prev_step // n:
                        self._maybe_compute_flops(batch)
                        # the float() conversions are the only host syncs in the loop
                        host_metrics = {
                            f"train_{k}" if k in ("loss", "acc") else k: float(v)
                            for k, v in metrics.items()
                        }
                        self._last_train_loss = host_metrics.get(
                            "train_loss", self._last_train_loss
                        )
                        if (
                            cfg.halt_on_nonfinite
                            and "train_loss" in host_metrics
                            and not np.isfinite(host_metrics["train_loss"])
                        ):
                            self.logger.log_scalars(step_i, host_metrics)
                            self.logger.flush()
                            raise FloatingPointError(
                                f"non-finite train loss "
                                f"{host_metrics['train_loss']} at step {step_i} — "
                                f"training diverged (disable with "
                                f"halt_on_nonfinite=False)"
                            )
                        now = time.perf_counter()
                        leaf = batch[self._keys[0]]
                        # per-step batch size: stacked dispatches carry the
                        # scan axis in front
                        batch_size = leaf.shape[1] if self._k > 1 else len(leaf)
                        if self.mesh is not None:
                            # loaders are per-host; the global batch spans processes
                            batch_size *= jax.process_count()
                        host_metrics.update(
                            self._throughput_metrics(
                                window_steps, now - window_start, batch_size
                            )
                        )
                        self.logger.log_scalars(step_i, host_metrics)
                        window_start, window_steps = now, 0

                    if step_guard is not None:
                        # DISARM (not beat) only now: the guard must cover
                        # every host sync that can block on THIS dispatch —
                        # _note_coord's pipelined flag read, the selfprof
                        # tick, the log-boundary metric fetches — but not
                        # the legitimately unbounded work past this point
                        # (first-eval compiles, checkpoint saves). With no
                        # sync this iteration the wedge is caught at the
                        # next one that blocks (bounded by the log cadence
                        # on the async fast path).
                        step_guard.disarm()

                    ev = cfg.eval_every_n_steps
                    if ev and step_i // ev > prev_step // ev:
                        self._validate_and_checkpoint(step_i, val_loader)
                        last_validated_step = step_i
                        window_start, window_steps = time.perf_counter(), 0

                    # train→serve publication cadence (AFTER a same-boundary
                    # eval, so the manifest carries the fresh val metrics)
                    pn = cfg.publish_every_n_steps
                    if (self._publisher is not None
                            and step_i // pn > prev_step // pn):
                        self._publish(step_i)

                    if cfg.max_steps is not None and step_i >= cfg.max_steps:
                        done = True
                        break
                if (self._agreed_preempt
                        or (self._sigterm and not self._coord)):
                    break
                if batches_this_epoch == 0:
                    raise ValueError(
                        "train_loader produced no batches (dataset shard smaller "
                        "than the batch size with drop_last?)"
                    )
                if steps_this_epoch == 0:
                    # batches flowed but EVERY step was skipped as non-finite
                    # (and rollback is off or landed back in the same state):
                    # the run cannot progress — surface the real diagnosis
                    # instead of looping epochs forever
                    raise FloatingPointError(
                        f"every train step of epoch {epoch} was skipped as "
                        f"non-finite ({batches_this_epoch} batches) — the "
                        f"run cannot make progress; inspect with debug_nans "
                        f"or lower the learning rate"
                    )
                epoch += 1
                if not cfg.eval_every_n_steps:
                    if not np.isfinite(self._last_train_loss) and "loss" in metrics:
                        self._last_train_loss = float(metrics["loss"])
                    self._validate_and_checkpoint(step_i, val_loader)
                    last_validated_step = step_i
                    window_start, window_steps = time.perf_counter(), 0

        finally:
            # a halt_on_nonfinite raise (or any other error) must not leak
            # an active profiler trace into the process
            if profiling_active:
                jax.profiler.stop_trace()
            if self._selfprof is not None:
                self._selfprof.close()  # abort an open watchdog window
            if step_guard is not None:
                step_guard.close()
            if peer_monitor is not None:
                peer_monitor.close()
            if handler_installed:
                # signal.signal returned None when the prior disposition was
                # installed outside Python — restore the default, never leave
                # the flag-setter swallowing SIGTERM after fit() returns
                signal.signal(
                    signal.SIGTERM,
                    prev_handler if prev_handler is not None else signal.SIG_DFL,
                )
        # the final-interval guard must branch IDENTICALLY on every host:
        # under coordination only the fleet-agreed preemption counts (the
        # raw local flag is per-host and would diverge the final collectives)
        preempted = self._agreed_preempt or (
            self._sigterm and not self._coord)
        if step_i > last_validated_step and not preempted:
            # final partial interval (eval_every_n_steps runs): don't lose the
            # tail — validate and give the checkpointer a shot at it
            if not np.isfinite(self._last_train_loss) and "loss" in metrics:
                self._last_train_loss = float(metrics["loss"])
            self._validate_and_checkpoint(step_i, val_loader)
        self.checkpoints.wait()
        self.logger.flush()
        return self.state

    def set_flops_per_step(self, flops: Optional[float]) -> None:
        """Install the per-step FLOP count used for the MFU metric (compute it
        once via ``profiling.compiled_flops`` on the caller's jitted step)."""
        self._flops_per_step = flops

    def close(self) -> None:
        self.checkpoints.close()
        self.logger.close()
        if self._prev_debug_nans is not None:
            jax.config.update("jax_debug_nans", self._prev_debug_nans)
            self._prev_debug_nans = None

    def __enter__(self) -> "Trainer":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
