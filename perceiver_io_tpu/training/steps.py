"""Jitted train/eval step builders.

The replacement for the reference's Lightning step methods
(``lightning.py:127-177``): each builder returns pure functions
``(state, batch) → (state, metrics)`` that the caller jits (single device) or
pjits over a mesh (SPMD — the DDP replacement; gradient sync becomes a
compiler-inserted psum when the batch axis is sharded).

Batches are dicts of arrays:

- MLM / text:  ``{'token_ids': (B, L) int, 'pad_mask': (B, L) bool[, 'label': (B,) int]}``
- image:       ``{'image': (B, *image_shape) float, 'label': (B,) int}``

Transfer learning (reference ``train_seq_clf.py:18-28``): ``freeze_subtrees``
masks optimizer updates for a params subtree (requires_grad=False parity) and
the classifier steps run a frozen encoder in eval mode (``.eval()`` parity).
"""

from __future__ import annotations

from typing import Callable, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import optax

from perceiver_io_tpu.training.losses import (
    classification_loss_and_accuracy,
    cross_entropy_with_ignore,
    fused_linear_cross_entropy_with_ignore,
    pallas_linear_cross_entropy_with_ignore,
)
from perceiver_io_tpu.training.train_state import TrainState

Array = jax.Array
Metrics = dict
Schedule = Callable[[Array], Array]


def freeze_subtrees(
    tx: optax.GradientTransformation, params, frozen_keys: Sequence[str]
) -> optax.GradientTransformation:
    """Zero out updates for top-level params subtrees named in ``frozen_keys``.

    The functional analogue of the reference's ``freeze()``
    (``train/utils.py:5-8``): frozen params receive no updates but still flow
    through the forward/backward pass.
    """
    frozen = set(frozen_keys)

    def label(tree):
        return {k: ("frozen" if k in frozen else "trainable") for k in tree}

    return optax.multi_transform(
        {"trainable": tx, "frozen": optax.set_to_zero()}, param_labels=label(params)
    )


def _lr_metric(schedule: Optional[Schedule], step: Array) -> dict:
    return {} if schedule is None else {"lr": schedule(step)}


def make_scanned_step(train_step):
    """Wrap a ``(state, batch) → (state, metrics)`` step into a
    ``(state, stacked_batches) → (state, window_metrics)`` multi-step
    dispatch: ``lax.scan`` over a leading K axis of per-step batches.

    One dispatch then covers K optimizer steps — on dispatch-latency-bound
    hosts (remote/tunneled accelerators, or very fast steps) this amortizes
    the per-call overhead that otherwise gates the whole training loop
    (PERF.md: the flagship trainer loop reached ~40% of the pure device-step
    rate on the tunneled backend). Float metrics come back as the window
    mean; integer metrics as the window MAX (for a monotonic counter that is
    its last value, and an any-fired flag — :func:`make_guarded_step`'s
    ``bad_step`` — survives the reduction instead of being masked by a clean
    final sub-step); anything else as the last value.
    """

    def scanned(state, stacked):
        def body(s, b):
            return train_step(s, b)

        state, ms = jax.lax.scan(body, state, stacked)

        def reduce(leaf):
            if jnp.issubdtype(leaf.dtype, jnp.floating):
                return leaf.mean(axis=0)
            if jnp.issubdtype(leaf.dtype, jnp.integer):
                return leaf.max(axis=0)
            return leaf[-1]

        return state, jax.tree.map(reduce, ms)

    return scanned


def make_guarded_step(train_step):
    """Collective-consistent non-finite-step guard: wrap a ``(state, batch) →
    (state, metrics)`` step so a non-finite loss SKIPS the update ON DEVICE —
    every leaf of the returned state is selected between the pre-step and
    post-step value by the same device-resident flag, and ``metrics`` gains
    ``bad_step`` (int32 0/1, deliberately non-float so a host-side NaN
    corruption of the fetched metrics cannot forge or erase it).

    This is what lifts the r9 single-process-only restriction on
    ``skip_nonfinite_steps``: under a multi-host data-sharded mesh the loss
    is already the output of the compiler-inserted cross-host psum (a NaN in
    ANY host's batch shard poisons the global scalar for every peer
    identically), so the flag derived from it — and therefore the
    skip-or-keep select — is bit-identical on all hosts by construction. No
    host ever makes a local decision that could desynchronize the fleet's
    collective programs, and no extra host round-trip is spent agreeing.

    Wrap BEFORE :func:`make_scanned_step` so each sub-step of a multi-step
    dispatch window selects independently (a mid-window bad step discards
    only its own update).
    """

    def select(bad, old, new):
        if jax.dtypes.issubdtype(new.dtype, jax.dtypes.prng_key):
            # typed PRNG keys carry an extended dtype jnp.where rejects;
            # select their raw key data and re-wrap
            data = jnp.where(bad, jax.random.key_data(old),
                             jax.random.key_data(new))
            return jax.random.wrap_key_data(
                data, impl=jax.random.key_impl(new))
        return jnp.where(bad, old, new)

    def guarded(state, batch):
        new_state, metrics = train_step(state, batch)
        loss = metrics.get("loss")
        if loss is None:
            # no loss metric = nothing to guard on (the pre-r19 host-side
            # check was a no-op here too); pass through with the flag down
            metrics = dict(metrics)
            metrics["bad_step"] = jnp.int32(0)
            return new_state, metrics
        bad = jnp.logical_not(jnp.all(jnp.isfinite(loss)))
        kept = jax.tree.map(
            lambda old, new: select(bad, old, new), state, new_state)
        metrics = dict(metrics)
        metrics["bad_step"] = bad.astype(jnp.int32)
        return kept, metrics

    return guarded


def mlm_gather_capacity(seq_len: int, mask_p: float = 0.15) -> int:
    """Default masked-decode capacity: 2·mask_p·L rounded up to a multiple of
    32 (sublane-friendly), capped at L. At 2× the expected masked count the
    odds of a row overflowing are negligible (>13σ at the reference config)."""
    cap = -(-int(2 * mask_p * seq_len) // 32) * 32
    return min(seq_len, max(cap, 32))


def make_mlm_steps(
    model,
    schedule: Optional[Schedule] = None,
    loss_gather_capacity: Optional[int] = None,
    fused_head: bool | str = False,
):
    """(train_step, eval_step, predict_fn) for a ``PerceiverMLM``.

    - train: masking RNG + dropout, CE over selected positions
      (reference ``lightning.py:127-139``).
    - eval: masking applied with an explicit key (val loss is measured on
      corrupted inputs, as in the reference), dropout off.
    - predict: ``masking=False`` forward returning logits — the
      ``predict_samples`` path (reference ``train_mlm.py:14-35``).

    ``loss_gather_capacity``: decode only the masked positions (up to this many
    per row) in train/eval — gradient-equivalent to the full decode but skips
    most of the dominant vocab-projection FLOPs (see ``PerceiverMLM``). The
    predict path decodes every position unless the caller passes explicit
    ``positions`` (see ``predict_fn``).

    ``fused_head``: fuse the vocab projection into the CE so the (B, K, V)
    logits never materialize in train/eval.

    - ``'pallas'``: the fused flash-CE kernel (``ops.pallas_ce``) — matmul +
      online-logsumexp + label pick inside ONE ``pallas_call``, gradients by
      blockwise recomputation. The measured WINNER at the flagship MLM head
      shapes (PERF.md round 3: the unfused head complex streams the 206 MB
      logits tensor ~5x at HBM peak, ~1.4 ms of a 10.4 ms step).
    - ``True``: the XLA chunked variant
      (``fused_linear_cross_entropy_with_ignore``) — a MEMORY lever only; on
      the flagship config it measured slower at every chunk size (PERF.md
      negative result #7: the chunk scan serializes 10-20 skinny dispatches).
      Kept for environments where the Pallas path is unavailable.

    Both are gradient-equivalent to the unfused path (tested); predict is
    unaffected.
    """
    if fused_head not in (False, True, "pallas"):
        raise ValueError(
            f"fused_head must be False, True or 'pallas', got {fused_head!r}"
        )

    def loss_fn(params, batch, rngs, deterministic):
        out, labels = model.apply(
            {"params": params},
            batch["token_ids"],
            batch["pad_mask"],
            rngs=rngs,
            deterministic=deterministic,
            loss_gather_capacity=loss_gather_capacity,
            return_features=bool(fused_head),
        )
        if fused_head:
            # the adapter owns the head layout + class-padding scheme
            kernel, bias = model.decoder.output_adapter.masked_head(
                params["decoder"]["output_adapter"]
            )
            fused_ce = (
                pallas_linear_cross_entropy_with_ignore
                if fused_head == "pallas"
                else fused_linear_cross_entropy_with_ignore
            )
            return fused_ce(out, kernel, bias, labels)
        return cross_entropy_with_ignore(out, labels)

    def train_step(state: TrainState, batch) -> Tuple[TrainState, Metrics]:
        rngs = state.step_rngs("masking", "dropout")
        loss, grads = jax.value_and_grad(loss_fn)(
            state.params, batch, rngs, False
        )
        metrics = {"loss": loss, **_lr_metric(schedule, state.step)}
        return state.apply_gradients(grads), metrics

    def eval_step(state: TrainState, batch, key: Array) -> Metrics:
        loss = loss_fn(state.params, batch, {"masking": key}, True)
        return {"loss": loss}

    def predict_fn(params, token_ids, pad_mask, positions=None):
        # positions (B, K): decode only those rows of the output-query array
        # — (B, K, vocab) logits instead of (B, L, vocab). The prediction
        # hook passes its (static) [MASK] positions so sample prediction at
        # long context never builds or fetches the full logits tensor.
        logits, _ = model.apply(
            {"params": params}, token_ids, pad_mask, masking=False,
            positions=positions,
        )
        return logits

    return train_step, eval_step, predict_fn


def make_ar_steps(model, schedule: Optional[Schedule] = None,
                  latent_offset: Optional[int] = None):
    """(train_step, eval_step, predict_fn) for a ``PerceiverARLM``.

    Next-token CE over the causal latent window: the dense forward's logits
    row i predicts the token at absolute position ``offset + i + 1``
    (``ops.masking.shift_ar_labels`` — final position and pad targets carry
    ``IGNORE_LABEL``, the same convention MLM's CE uses). No masking RNG —
    causality is structural, not sampled; dropout is the only stochastic
    stream."""

    def loss_fn(params, batch, rngs, deterministic):
        from perceiver_io_tpu.ops.masking import shift_ar_labels

        ids, pad = batch["token_ids"], batch["pad_mask"]
        logits = model.apply(
            {"params": params}, ids, pad, rngs=rngs,
            deterministic=deterministic, latent_offset=latent_offset,
        )
        o = (ids.shape[1] - logits.shape[1] if latent_offset is None
             else latent_offset)
        labels = shift_ar_labels(ids, pad, o)
        return cross_entropy_with_ignore(logits, labels)

    def train_step(state: TrainState, batch) -> Tuple[TrainState, Metrics]:
        rngs = state.step_rngs("dropout")
        loss, grads = jax.value_and_grad(loss_fn)(
            state.params, batch, rngs, False
        )
        metrics = {"loss": loss, **_lr_metric(schedule, state.step)}
        return state.apply_gradients(grads), metrics

    def eval_step(state: TrainState, batch, key: Optional[Array] = None
                  ) -> Metrics:
        # the key parameter is the Trainer's stochastic-eval slot (MLM
        # masking); AR eval is deterministic, so it is accepted and unused
        loss = loss_fn(state.params, batch, {}, True)
        return {"loss": loss}

    def predict_fn(params, token_ids, pad_mask):
        return model.apply({"params": params}, token_ids, pad_mask,
                           latent_offset=latent_offset)

    return train_step, eval_step, predict_fn


def make_classifier_steps(
    model,
    schedule: Optional[Schedule] = None,
    input_kind: str = "image",
    frozen_encoder: bool = False,
):
    """(train_step, eval_step) for a ``PerceiverIO`` classifier.

    ``input_kind``: 'image' (no pad mask, reference ``lightning.py:253-255``)
    or 'text' (pad-masked, reference ``lightning.py:209-211``).
    ``frozen_encoder=True`` runs the encoder deterministically (eval-mode
    parity with the reference's freeze+``.eval()``); combine with
    ``freeze_subtrees(tx, params, ['encoder'])`` to stop its updates.
    """
    if input_kind not in ("image", "text"):
        raise ValueError(f"input_kind must be 'image' or 'text', got {input_kind!r}")

    def forward(params, batch, rngs, deterministic):
        kwargs = {"deterministic": deterministic}
        if frozen_encoder:
            kwargs["encoder_deterministic"] = True
        if input_kind == "image":
            return model.apply({"params": params}, batch["image"], rngs=rngs, **kwargs)
        return model.apply(
            {"params": params},
            batch["token_ids"],
            pad_mask=batch["pad_mask"],
            rngs=rngs,
            **kwargs,
        )

    def loss_fn(params, batch, rngs, deterministic):
        logits = forward(params, batch, rngs, deterministic)
        loss, acc = classification_loss_and_accuracy(logits, batch["label"])
        return loss, acc

    def train_step(state: TrainState, batch) -> Tuple[TrainState, Metrics]:
        rngs = state.step_rngs("dropout")
        (loss, acc), grads = jax.value_and_grad(loss_fn, has_aux=True)(
            state.params, batch, rngs, False
        )
        metrics = {"loss": loss, "acc": acc, **_lr_metric(schedule, state.step)}
        return state.apply_gradients(grads), metrics

    def eval_step(state: TrainState, batch) -> Metrics:
        loss, acc = loss_fn(state.params, batch, {}, True)
        return {"loss": loss, "acc": acc}

    return train_step, eval_step


def make_multimodal_steps(
    model,
    schedule: Optional[Schedule] = None,
    video_weight: float = 1.0,
    audio_weight: float = 1.0,
    label_weight: float = 1.0,
):
    """(train_step, eval_step) for the multimodal autoencoder: batches
    ``{'video': (B, T, H, W, C), 'audio': (B, S, C_a), 'label': (B,) int}``,
    loss = weighted MSE(video) + MSE(audio) + CE(label).

    When the model's video head runs in patch space
    (``VideoOutputAdapter.as_patches`` — the ``video_patch_loss`` builder
    knob), the patch geometry is read off the adapter here and the TARGET is
    patchified in the loss instead of the prediction being un-patchified in
    the adapter (exact up to fp reassociation)."""
    from perceiver_io_tpu.models.multimodal import multimodal_autoencoding_loss

    video_patch_info = None
    output_adapter = getattr(
        getattr(model, "decoder", None), "output_adapter", None)
    for name, adapter in getattr(output_adapter, "adapters", ()):
        if name == "video" and getattr(adapter, "as_patches", False):
            video_patch_info = (adapter.grid_shape, adapter.patch_shape)

    def loss_fn(params, batch, rngs, deterministic):
        outputs = model.apply(
            {"params": params},
            {"video": batch["video"], "audio": batch["audio"]},
            rngs=rngs,
            deterministic=deterministic,
        )
        return multimodal_autoencoding_loss(
            outputs, batch, video_weight, audio_weight, label_weight,
            video_patch_info=video_patch_info,
        )

    def train_step(state: TrainState, batch) -> Tuple[TrainState, Metrics]:
        rngs = state.step_rngs("dropout")
        (loss, aux), grads = jax.value_and_grad(loss_fn, has_aux=True)(
            state.params, batch, rngs, False
        )
        metrics = {"loss": loss, **aux, **_lr_metric(schedule, state.step)}
        return state.apply_gradients(grads), metrics

    def eval_step(state: TrainState, batch) -> Metrics:
        loss, aux = loss_fn(state.params, batch, {}, True)
        return {"loss": loss, **aux}

    return train_step, eval_step


def make_flow_steps(model, schedule: Optional[Schedule] = None):
    """(train_step, eval_step) for an optical-flow ``PerceiverIO`` (dense
    2D-query decoder): batches ``{'frames': (B, 2, H, W, C), 'flow':
    (B, H, W, 2)}``, loss = mean end-point error."""
    from perceiver_io_tpu.models.flow import end_point_error

    def loss_fn(params, batch, rngs, deterministic):
        pred = model.apply(
            {"params": params}, batch["frames"], rngs=rngs,
            deterministic=deterministic,
        )
        return end_point_error(pred, batch["flow"])

    def train_step(state: TrainState, batch) -> Tuple[TrainState, Metrics]:
        rngs = state.step_rngs("dropout")
        loss, grads = jax.value_and_grad(loss_fn)(state.params, batch, rngs, False)
        metrics = {"loss": loss, **_lr_metric(schedule, state.step)}
        return state.apply_gradients(grads), metrics

    def eval_step(state: TrainState, batch) -> Metrics:
        return {"loss": loss_fn(state.params, batch, {}, True)}

    return train_step, eval_step
