"""Loss and metric functions.

Semantics match the reference training layer: cross-entropy with an
ignore-index of -100 for MLM (reference ``lightning.py:88,131-134`` — torch
``CrossEntropyLoss`` default mean over non-ignored elements), plain CE + top-1
accuracy for classification (reference ``lightning.py:153-160``).
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np

from perceiver_io_tpu.ops.masking import IGNORE_LABEL

Array = jax.Array


@jax.custom_vjp
def softmax_ce_integer(logits: Array, labels: Array) -> Array:
    """Per-position CE (lse − label logit), memory-lean.

    Equivalent to ``optax.softmax_cross_entropy_with_integer_labels`` on
    f32-upcast logits, but with a custom VJP so the (…, C) tensor is never
    materialized in f32: the forward keeps row statistics only (f32
    logsumexp; reductions accumulate in f32 straight off the bf16 logits),
    and the backward recomputes ``softmax − onehot`` as one fusion producing
    the logits dtype. At the MLM decode shapes ((B, 160, 10003) vocab
    logits) the f32 upcast and its multi-consumer residuals dominated HBM
    traffic in the loss.
    """
    lse = jax.nn.logsumexp(logits.astype(jnp.float32), axis=-1)
    ll = jnp.take_along_axis(logits, labels[..., None], axis=-1)[..., 0]
    return lse - ll.astype(jnp.float32)


def _ce_fwd(logits, labels):
    lse = jax.nn.logsumexp(logits.astype(jnp.float32), axis=-1)
    ll = jnp.take_along_axis(logits, labels[..., None], axis=-1)[..., 0]
    return lse - ll.astype(jnp.float32), (logits, labels, lse)


def _ce_bwd(res, g):
    logits, labels, lse = res
    p = jnp.exp(logits.astype(jnp.float32) - lse[..., None])
    onehot = (
        jax.lax.broadcasted_iota(labels.dtype, logits.shape, logits.ndim - 1)
        == labels[..., None]
    )
    d = (p - onehot) * g[..., None]
    return d.astype(logits.dtype), np.zeros(labels.shape, jax.dtypes.float0)


softmax_ce_integer.defvjp(_ce_fwd, _ce_bwd)


import functools


@functools.partial(jax.custom_vjp, nondiff_argnums=(4,))
def fused_linear_ce_integer(
    features: Array, kernel: Array, bias: Array, labels: Array, chunk: int
) -> Array:
    """Per-position CE of ``features @ kernel + bias`` vs integer ``labels``,
    WITHOUT materializing the (..., V) logits.

    The vocab axis is processed in ``chunk``-wide slices with an online
    logsumexp (flash-attention's trick applied to the classifier head), and
    the backward recomputes each chunk's logits instead of saving them. The
    (B, K, V) logits tensor of the unfused path is produced once and re-read
    ~4x (CE forward, softmax backward, and both matmul transposes) — at the
    flagship MLM decode shape (64, 160, 10003) that is ~1 GB of HBM traffic
    per step, ~25% of the step's total (measured from a device profile; see
    PERF.md). Here per-chunk logits live on-chip only.

    Numerics match the unfused path: the matmul and bias-add run in the
    features dtype (bf16 accumulates in f32 on the MXU), statistics
    accumulate in f32.
    """
    per_pos, _ = _fused_ce_fwd_impl(features, kernel, bias, labels, chunk)
    return per_pos


def _pad_vocab(kernel: Array, bias: Array, chunk: int):
    v = kernel.shape[-1]
    pad = -v % chunk
    if pad:
        kernel = jnp.pad(kernel, ((0, 0), (0, pad)))
        # large-negative (not -inf: no inf arithmetic in any dtype) so padded
        # columns contribute exp(..) == 0 to the softmax statistics
        bias = jnp.pad(bias, (0, pad), constant_values=-1e9)
    return kernel, bias, (v + pad) // chunk


def _chunk_logits(features, kernel, bias, i, chunk):
    w = jax.lax.dynamic_slice_in_dim(kernel, i * chunk, chunk, axis=1)
    b = jax.lax.dynamic_slice_in_dim(bias, i * chunk, chunk)
    logits = jnp.einsum(
        "...kc,cv->...kv", features, w.astype(features.dtype)
    ) + b.astype(features.dtype)
    return logits.astype(jnp.float32), w

def _fused_ce_fwd_impl(features, kernel, bias, labels, chunk):
    kern_p, bias_p, n = _pad_vocab(kernel, bias, chunk)
    shape = labels.shape

    def body(carry, i):
        m, s, ll = carry
        logits, _ = _chunk_logits(features, kern_p, bias_p, i, chunk)
        m_c = logits.max(axis=-1)
        m2 = jnp.maximum(m, m_c)
        s = s * jnp.exp(m - m2) + jnp.exp(
            logits - m2[..., None]
        ).sum(axis=-1)
        in_chunk = (labels >= i * chunk) & (labels < (i + 1) * chunk)
        idx = jnp.clip(labels - i * chunk, 0, chunk - 1)
        picked = jnp.take_along_axis(logits, idx[..., None], axis=-1)[..., 0]
        ll = ll + jnp.where(in_chunk, picked, 0.0)
        return (m2, s, ll), None

    init = (
        jnp.full(shape, -jnp.inf, jnp.float32),
        jnp.zeros(shape, jnp.float32),
        jnp.zeros(shape, jnp.float32),
    )
    (m, s, ll), _ = jax.lax.scan(body, init, jnp.arange(n))
    lse = m + jnp.log(s)
    return lse - ll, lse


def _fused_ce_fwd(features, kernel, bias, labels, chunk):
    per_pos, lse = _fused_ce_fwd_impl(features, kernel, bias, labels, chunk)
    return per_pos, (features, kernel, bias, labels, lse)


def _fused_ce_bwd(chunk, res, g):
    features, kernel, bias, labels, lse = res
    kern_p, bias_p, n = _pad_vocab(kernel, bias, chunk)

    def body(carry, i):
        dx, dw, db = carry
        logits, w = _chunk_logits(features, kern_p, bias_p, i, chunk)
        p = jnp.exp(logits - lse[..., None])
        in_chunk = (labels >= i * chunk) & (labels < (i + 1) * chunk)
        idx = jnp.where(in_chunk, labels - i * chunk, chunk)  # chunk = none
        onehot = (
            jax.lax.broadcasted_iota(idx.dtype, logits.shape, logits.ndim - 1)
            == idx[..., None]
        )
        d = ((p - onehot) * g[..., None]).astype(features.dtype)
        dx = dx + jnp.einsum(
            "...kv,cv->...kc", d, w.astype(features.dtype),
            preferred_element_type=jnp.float32,
        )
        dw_c = jnp.einsum(
            "...kc,...kv->cv", features, d, preferred_element_type=jnp.float32
        )
        dw = jax.lax.dynamic_update_slice_in_dim(dw, dw_c, i * chunk, axis=1)
        db_c = d.astype(jnp.float32).sum(axis=tuple(range(d.ndim - 1)))
        db = jax.lax.dynamic_update_slice_in_dim(db, db_c, i * chunk, axis=0)
        return (dx, dw, db), None

    init = (
        jnp.zeros(features.shape, jnp.float32),
        jnp.zeros(kern_p.shape, jnp.float32),
        jnp.zeros(bias_p.shape, jnp.float32),
    )
    (dx, dw, db), _ = jax.lax.scan(body, init, jnp.arange(n))
    v = kernel.shape[-1]
    return (
        dx.astype(features.dtype),
        dw[:, :v].astype(kernel.dtype),
        db[:v].astype(bias.dtype),
        np.zeros(labels.shape, jax.dtypes.float0),
    )


fused_linear_ce_integer.defvjp(_fused_ce_fwd, _fused_ce_bwd)


def fused_linear_cross_entropy_with_ignore(
    features: Array,
    kernel: Array,
    bias: Array,
    labels: Array,
    ignore_label: int = IGNORE_LABEL,
    chunk: int = 512,
) -> Array:
    """Mean CE of a linear head applied to ``features``, ignoring
    ``ignore_label`` positions — :func:`cross_entropy_with_ignore` semantics
    with the head matmul fused into the chunked loss (the (..., V) logits
    never materialize, forward or backward)."""
    valid = labels != ignore_label
    safe_labels = jnp.where(valid, labels, 0)
    per_pos = fused_linear_ce_integer(features, kernel, bias, safe_labels, chunk)
    denom = jnp.maximum(valid.sum(), 1)
    return jnp.where(valid, per_pos, 0.0).sum() / denom


def pallas_linear_cross_entropy_with_ignore(
    features: Array,
    kernel: Array,
    bias: Array,
    labels: Array,
    ignore_label: int = IGNORE_LABEL,
) -> Array:
    """:func:`fused_linear_cross_entropy_with_ignore` semantics on the fused
    Pallas flash-CE kernel (``ops.pallas_ce``): head matmul + online-logsumexp
    CE in one kernel, logits never in HBM, forward or backward. The measured
    winner at the flagship MLM head shapes (PERF.md round 3) — unlike the XLA
    chunked variant, the vocab loop is a sequential grid inside ONE kernel
    rather than a scan of dispatches."""
    from perceiver_io_tpu.ops.pallas_ce import pallas_linear_ce_integer

    valid = labels != ignore_label
    safe_labels = jnp.where(valid, labels, 0)
    per_pos = pallas_linear_ce_integer(features, kernel, bias, safe_labels)
    denom = jnp.maximum(valid.sum(), 1)
    return jnp.where(valid, per_pos, 0.0).sum() / denom


def cross_entropy_with_ignore(
    logits: Array, labels: Array, ignore_label: int = IGNORE_LABEL
) -> Array:
    """Mean CE over positions where ``labels != ignore_label``.

    logits: (..., C); labels: (...) int. Matches torch
    ``CrossEntropyLoss(ignore_index=-100)`` 'mean' reduction.
    """
    valid = labels != ignore_label
    safe_labels = jnp.where(valid, labels, 0)
    per_pos = softmax_ce_integer(logits, safe_labels)
    denom = jnp.maximum(valid.sum(), 1)
    return jnp.where(valid, per_pos, 0.0).sum() / denom


def classification_loss_and_accuracy(
    logits: Array, labels: Array
) -> Tuple[Array, Array]:
    """(mean CE, top-1 accuracy) for (B, C) logits and (B,) int labels."""
    loss = softmax_ce_integer(logits, labels).mean()
    acc = (jnp.argmax(logits, axis=-1) == labels).mean()
    return loss, acc
