"""Loss and metric functions.

Semantics match the reference training layer: cross-entropy with an
ignore-index of -100 for MLM (reference ``lightning.py:88,131-134`` — torch
``CrossEntropyLoss`` default mean over non-ignored elements), plain CE + top-1
accuracy for classification (reference ``lightning.py:153-160``).
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp
import optax

from perceiver_io_tpu.ops.masking import IGNORE_LABEL

Array = jax.Array


def cross_entropy_with_ignore(
    logits: Array, labels: Array, ignore_label: int = IGNORE_LABEL
) -> Array:
    """Mean CE over positions where ``labels != ignore_label``.

    logits: (..., C); labels: (...) int. Matches torch
    ``CrossEntropyLoss(ignore_index=-100)`` 'mean' reduction.
    """
    valid = labels != ignore_label
    safe_labels = jnp.where(valid, labels, 0)
    per_pos = optax.softmax_cross_entropy_with_integer_labels(
        logits.astype(jnp.float32), safe_labels
    )
    denom = jnp.maximum(valid.sum(), 1)
    return jnp.where(valid, per_pos, 0.0).sum() / denom


def classification_loss_and_accuracy(
    logits: Array, labels: Array
) -> Tuple[Array, Array]:
    """(mean CE, top-1 accuracy) for (B, C) logits and (B,) int labels."""
    loss = optax.softmax_cross_entropy_with_integer_labels(
        logits.astype(jnp.float32), labels
    ).mean()
    acc = (jnp.argmax(logits, axis=-1) == labels).mean()
    return loss, acc
