"""Loss and metric functions.

Semantics match the reference training layer: cross-entropy with an
ignore-index of -100 for MLM (reference ``lightning.py:88,131-134`` — torch
``CrossEntropyLoss`` default mean over non-ignored elements), plain CE + top-1
accuracy for classification (reference ``lightning.py:153-160``).
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np

from perceiver_io_tpu.ops.masking import IGNORE_LABEL

Array = jax.Array


@jax.custom_vjp
def softmax_ce_integer(logits: Array, labels: Array) -> Array:
    """Per-position CE (lse − label logit), memory-lean.

    Equivalent to ``optax.softmax_cross_entropy_with_integer_labels`` on
    f32-upcast logits, but with a custom VJP so the (…, C) tensor is never
    materialized in f32: the forward keeps row statistics only (f32
    logsumexp; reductions accumulate in f32 straight off the bf16 logits),
    and the backward recomputes ``softmax − onehot`` as one fusion producing
    the logits dtype. At the MLM decode shapes ((B, 160, 10003) vocab
    logits) the f32 upcast and its multi-consumer residuals dominated HBM
    traffic in the loss.
    """
    lse = jax.nn.logsumexp(logits.astype(jnp.float32), axis=-1)
    ll = jnp.take_along_axis(logits, labels[..., None], axis=-1)[..., 0]
    return lse - ll.astype(jnp.float32)


def _ce_fwd(logits, labels):
    lse = jax.nn.logsumexp(logits.astype(jnp.float32), axis=-1)
    ll = jnp.take_along_axis(logits, labels[..., None], axis=-1)[..., 0]
    return lse - ll.astype(jnp.float32), (logits, labels, lse)


def _ce_bwd(res, g):
    logits, labels, lse = res
    p = jnp.exp(logits.astype(jnp.float32) - lse[..., None])
    onehot = (
        jax.lax.broadcasted_iota(labels.dtype, logits.shape, logits.ndim - 1)
        == labels[..., None]
    )
    d = (p - onehot) * g[..., None]
    return d.astype(logits.dtype), np.zeros(labels.shape, jax.dtypes.float0)


softmax_ce_integer.defvjp(_ce_fwd, _ce_bwd)


def cross_entropy_with_ignore(
    logits: Array, labels: Array, ignore_label: int = IGNORE_LABEL
) -> Array:
    """Mean CE over positions where ``labels != ignore_label``.

    logits: (..., C); labels: (...) int. Matches torch
    ``CrossEntropyLoss(ignore_index=-100)`` 'mean' reduction.
    """
    valid = labels != ignore_label
    safe_labels = jnp.where(valid, labels, 0)
    per_pos = softmax_ce_integer(logits, safe_labels)
    denom = jnp.maximum(valid.sum(), 1)
    return jnp.where(valid, per_pos, 0.0).sum() / denom


def classification_loss_and_accuracy(
    logits: Array, labels: Array
) -> Tuple[Array, Array]:
    """(mean CE, top-1 accuracy) for (B, C) logits and (B,) int labels."""
    loss = softmax_ce_integer(logits, labels).mean()
    acc = (jnp.argmax(logits, axis=-1) == labels).mean()
    return loss, acc
