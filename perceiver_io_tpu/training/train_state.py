"""Train state: one pytree carrying everything a jitted step updates.

The whole state threads through ``jit``/``pjit`` as a single donated argument,
so params and optimizer state never leave the device between steps (no
host↔device traffic in the hot loop).
"""

from __future__ import annotations

from typing import Any, Callable

import jax
import optax
from flax import struct

Array = jax.Array


class TrainState(struct.PyTreeNode):
    step: Array
    params: Any
    opt_state: Any
    rng: Array
    tx: optax.GradientTransformation = struct.field(pytree_node=False)

    @classmethod
    def create(cls, params, tx: optax.GradientTransformation, rng: Array) -> "TrainState":
        import jax.numpy as jnp

        return cls(
            step=jnp.zeros((), jnp.int32),
            params=params,
            opt_state=tx.init(params),
            rng=rng,
            tx=tx,
        )

    def apply_gradients(self, grads) -> "TrainState":
        updates, new_opt_state = self.tx.update(grads, self.opt_state, self.params)
        new_params = optax.apply_updates(self.params, updates)
        return self.replace(
            step=self.step + 1, params=new_params, opt_state=new_opt_state
        )

    def step_rngs(self, *names: str) -> dict:
        """Per-step derived RNG streams: deterministic in (rng, step)."""
        base = jax.random.fold_in(self.rng, self.step)
        keys = jax.random.split(base, len(names))
        return dict(zip(names, keys))
