from perceiver_io_tpu.training.losses import (
    cross_entropy_with_ignore,
    classification_loss_and_accuracy,
)
from perceiver_io_tpu.training.optim import OptimizerConfig, make_optimizer
from perceiver_io_tpu.training.train_state import TrainState
from perceiver_io_tpu.training.steps import (
    make_ar_steps,
    make_mlm_steps,
    make_classifier_steps,
    make_flow_steps,
    make_multimodal_steps,
    freeze_subtrees,
    mlm_gather_capacity,
)
from perceiver_io_tpu.training.checkpoint import (
    CheckpointManager,
    load_hparams,
    restore_encoder_params,
    restore_params,
    restore_train_state,
)
from perceiver_io_tpu.training.metrics import MetricsLogger, next_version_dir, read_metrics
from perceiver_io_tpu.training.trainer import Trainer, TrainerConfig

__all__ = [
    "MetricsLogger",
    "next_version_dir",
    "read_metrics",
    "Trainer",
    "TrainerConfig",
    "CheckpointManager",
    "load_hparams",
    "restore_encoder_params",
    "restore_params",
    "restore_train_state",
    "cross_entropy_with_ignore",
    "classification_loss_and_accuracy",
    "OptimizerConfig",
    "make_optimizer",
    "TrainState",
    "make_ar_steps",
    "make_mlm_steps",
    "mlm_gather_capacity",
    "make_classifier_steps",
    "make_flow_steps",
    "make_multimodal_steps",
    "freeze_subtrees",
]
