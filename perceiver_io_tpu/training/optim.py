"""Optimizer and LR-schedule factory.

Mirrors the reference optimizer surface (``lightning.py:50-79``): the
reference resolves ``--optimizer`` with ``getattr(torch.optim, name)``
(``lightning.py:60``), so any torch optimizer name works from its CLI. Here
the common names — Adam, AdamW, SGD, RMSprop, Adagrad — map to optax with
torch's exact update semantics; unknown names raise the same clear error as
before (a silent near-miss optimizer is worse than a loud gap).

Semantic parity notes:

- torch ``Adam(weight_decay=w)`` is *coupled* L2: ``grad += w * param`` before
  the moment updates → ``optax.chain(add_decayed_weights, scale_by_adam, lr)``.
- torch ``AdamW(weight_decay=w)`` is decoupled, decay scaled by the lr →
  ``optax.adamw``.
- torch ``SGD(momentum=m)`` keeps ``buf = m·buf + grad`` (dampening 0) and
  steps by ``lr·buf`` → ``optax.trace(decay=m)``; weight decay is coupled L2
  applied before the momentum buffer.
- torch ``RMSprop``: ``sq = α·sq + (1−α)·g²``, step ``lr·g/(√sq + eps)`` with
  α=0.99, eps=1e-8 — the eps sits OUTSIDE the sqrt →
  ``optax.scale_by_rms(decay=0.99, eps=1e-8, eps_in_sqrt=False)``.
- torch ``Adagrad``: ``sum += g²``, step ``lr·g/(√sum + eps)`` with eps=1e-10
  and zero initial accumulator. optax's ``scale_by_rss`` puts eps inside the
  sqrt and special-cases sum==0, so ``_scale_by_adagrad_torch`` below
  reproduces the torch update directly.
- torch ``OneCycleLR(max_lr, pct_start, total_steps, cycle_momentum=False)``
  uses cosine annealing with ``div_factor=25``, ``final_div_factor=1e4``, a
  peak at step ``pct_start*total_steps - 1`` and the minimum at step
  ``total_steps - 1`` (one-shifted vs. ``optax.cosine_onecycle_schedule``) —
  reproduced exactly by ``torch_one_cycle_schedule`` below.

The schedule callable is returned alongside the transformation so steps can
log the current LR (the reference's per-step ``LearningRateMonitor``,
``train/utils.py:16-17``).
"""

from __future__ import annotations

import dataclasses
from typing import Callable, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import optax


def torch_one_cycle_schedule(
    total_steps: int,
    max_lr: float,
    pct_start: float = 0.1,
    div_factor: float = 25.0,
    final_div_factor: float = 1e4,
) -> Callable:
    """Cosine OneCycle with torch's exact phase boundaries.

    initial = max_lr/div_factor; min = initial/final_div_factor; cosine-anneal
    initial→max over steps [0, pct_start*total-1], then max→min over
    [pct_start*total-1, total-1]. jit-friendly (pure jnp on the step counter).
    """
    initial_lr = max_lr / div_factor
    min_lr = initial_lr / final_div_factor
    peak_step = max(pct_start * total_steps - 1.0, 1e-8)
    down_steps = max(total_steps - 1.0 - peak_step, 1e-8)

    def cos_anneal(start, end, frac):
        return end + (start - end) * (1.0 + jnp.cos(jnp.pi * frac)) / 2.0

    def schedule(step):
        s = jnp.asarray(step, jnp.float32)
        up = cos_anneal(initial_lr, max_lr, jnp.clip(s / peak_step, 0.0, 1.0))
        down = cos_anneal(max_lr, min_lr, jnp.clip((s - peak_step) / down_steps, 0.0, 1.0))
        return jnp.where(s <= peak_step, up, down)

    return schedule


@dataclasses.dataclass(frozen=True)
class OptimizerConfig:
    """Reference optimizer argparse group (``lightning.py:50-57``)."""

    optimizer: str = "Adam"  # 'Adam' | 'AdamW' | 'SGD' | 'RMSprop' | 'Adagrad'
    learning_rate: float = 1e-3
    weight_decay: float = 0.0
    one_cycle_lr: bool = False
    one_cycle_pct_start: float = 0.1
    max_steps: Optional[int] = None
    # torch SGD momentum (the reference never sets it — its getattr call
    # passes only lr/weight_decay — but torch's default surface has it)
    momentum: float = 0.0
    # TPU-framework extensions beyond the reference surface:
    grad_clip_norm: Optional[float] = None  # global-norm clipping before moments
    accumulate_steps: int = 1  # micro-batches averaged per optimizer update


class _AdagradState(NamedTuple):
    sum_of_squares: object


def _scale_by_adagrad_torch(
    eps: float = 1e-10, initial_accumulator_value: float = 0.0
) -> optax.GradientTransformation:
    """torch ``Adagrad``'s exact scaling: ``sum += g²; g / (sqrt(sum) + eps)``.

    optax's ``scale_by_rss`` differs in two observable ways (eps inside the
    sqrt; a where() that zeroes updates while the accumulator is zero), so the
    torch update is implemented directly. State mirrors the param-tree paths
    like Adam's moments, so the ZeRO sharding rules apply unchanged.
    """

    def init_fn(params):
        return _AdagradState(
            sum_of_squares=jax.tree.map(
                lambda p: jnp.full_like(p, initial_accumulator_value), params
            )
        )

    def update_fn(updates, state, params=None):
        del params
        sums = jax.tree.map(
            lambda g, s: s + jnp.square(g), updates, state.sum_of_squares
        )
        updates = jax.tree.map(
            lambda g, s: g / (jnp.sqrt(s) + eps), updates, sums
        )
        return updates, _AdagradState(sum_of_squares=sums)

    return optax.GradientTransformation(init_fn, update_fn)


def make_optimizer(
    config: OptimizerConfig,
) -> Tuple[optax.GradientTransformation, Callable[[int], float]]:
    """Build (transformation, lr_schedule) from the config.

    Raises ValueError when OneCycle is requested without ``max_steps``
    (reference ``lightning.py:65-67``).
    """
    k = config.accumulate_steps
    if k < 1:
        raise ValueError(f"accumulate_steps must be >= 1, got {k}")

    if config.one_cycle_lr:
        if config.max_steps is None:
            raise ValueError("OneCycleLR requires a max_steps value")
        # max_steps counts trainer (micro) steps; the schedule advances once
        # per optimizer update, i.e. every k micro steps
        schedule = torch_one_cycle_schedule(
            total_steps=max(config.max_steps // k, 1),
            max_lr=config.learning_rate,
            pct_start=config.one_cycle_pct_start,
        )
    else:
        schedule = optax.constant_schedule(config.learning_rate)

    name = config.optimizer
    # coupled L2 (torch's default weight_decay semantics for everything but
    # AdamW): grad += wd * param BEFORE any moment/accumulator update
    coupled_wd = (
        [optax.add_decayed_weights(config.weight_decay)]
        if config.weight_decay
        else []
    )
    if name == "Adam":
        tx = optax.chain(
            *coupled_wd,
            optax.scale_by_adam(),
            optax.scale_by_learning_rate(schedule),
        )
    elif name == "AdamW":
        tx = optax.adamw(schedule, weight_decay=config.weight_decay)
    elif name == "SGD":
        momentum = (
            [optax.trace(decay=config.momentum)] if config.momentum else []
        )
        tx = optax.chain(
            *coupled_wd, *momentum, optax.scale_by_learning_rate(schedule)
        )
    elif name == "RMSprop":
        # torch defaults: alpha=0.99, eps=1e-8, eps OUTSIDE the sqrt
        tx = optax.chain(
            *coupled_wd,
            optax.scale_by_rms(decay=0.99, eps=1e-8, eps_in_sqrt=False),
            optax.scale_by_learning_rate(schedule),
        )
    elif name == "Adagrad":
        tx = optax.chain(
            *coupled_wd,
            _scale_by_adagrad_torch(),
            optax.scale_by_learning_rate(schedule),
        )
    else:
        raise ValueError(
            f"unknown optimizer {name!r} (expected one of 'Adam', 'AdamW', "
            f"'SGD', 'RMSprop', 'Adagrad' — the torch.optim names the "
            f"reference CLI accepts)"
        )

    if config.grad_clip_norm is not None:
        if config.grad_clip_norm <= 0:
            raise ValueError(f"grad_clip_norm must be > 0, got {config.grad_clip_norm}")
        tx = optax.chain(optax.clip_by_global_norm(config.grad_clip_norm), tx)

    if k > 1:
        ms = optax.MultiSteps(tx, every_k_schedule=k)
        # plain GradientTransformation view, so downstream wrappers
        # (freeze_subtrees' multi_transform) compose with it
        tx = optax.GradientTransformation(ms.init, ms.update)
        micro_schedule = schedule
        schedule = lambda step: micro_schedule(jnp.asarray(step) // k)

    return tx, schedule
