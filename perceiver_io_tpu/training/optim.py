"""Optimizer and LR-schedule factory.

Mirrors the reference optimizer surface (``lightning.py:50-79``): Adam or
AdamW selected by name, optional OneCycle LR stepped per optimizer step and
requiring ``max_steps``.

Semantic parity notes:

- torch ``Adam(weight_decay=w)`` is *coupled* L2: ``grad += w * param`` before
  the moment updates → ``optax.chain(add_decayed_weights, scale_by_adam, lr)``.
- torch ``AdamW(weight_decay=w)`` is decoupled, decay scaled by the lr →
  ``optax.adamw``.
- torch ``OneCycleLR(max_lr, pct_start, total_steps, cycle_momentum=False)``
  uses cosine annealing with ``div_factor=25``, ``final_div_factor=1e4``, a
  peak at step ``pct_start*total_steps - 1`` and the minimum at step
  ``total_steps - 1`` (one-shifted vs. ``optax.cosine_onecycle_schedule``) —
  reproduced exactly by ``torch_one_cycle_schedule`` below.

The schedule callable is returned alongside the transformation so steps can
log the current LR (the reference's per-step ``LearningRateMonitor``,
``train/utils.py:16-17``).
"""

from __future__ import annotations

import dataclasses
from typing import Callable, Optional, Tuple

import jax.numpy as jnp
import optax


def torch_one_cycle_schedule(
    total_steps: int,
    max_lr: float,
    pct_start: float = 0.1,
    div_factor: float = 25.0,
    final_div_factor: float = 1e4,
) -> Callable:
    """Cosine OneCycle with torch's exact phase boundaries.

    initial = max_lr/div_factor; min = initial/final_div_factor; cosine-anneal
    initial→max over steps [0, pct_start*total-1], then max→min over
    [pct_start*total-1, total-1]. jit-friendly (pure jnp on the step counter).
    """
    initial_lr = max_lr / div_factor
    min_lr = initial_lr / final_div_factor
    peak_step = max(pct_start * total_steps - 1.0, 1e-8)
    down_steps = max(total_steps - 1.0 - peak_step, 1e-8)

    def cos_anneal(start, end, frac):
        return end + (start - end) * (1.0 + jnp.cos(jnp.pi * frac)) / 2.0

    def schedule(step):
        s = jnp.asarray(step, jnp.float32)
        up = cos_anneal(initial_lr, max_lr, jnp.clip(s / peak_step, 0.0, 1.0))
        down = cos_anneal(max_lr, min_lr, jnp.clip((s - peak_step) / down_steps, 0.0, 1.0))
        return jnp.where(s <= peak_step, up, down)

    return schedule


@dataclasses.dataclass(frozen=True)
class OptimizerConfig:
    """Reference optimizer argparse group (``lightning.py:50-57``)."""

    optimizer: str = "Adam"  # 'Adam' | 'AdamW'
    learning_rate: float = 1e-3
    weight_decay: float = 0.0
    one_cycle_lr: bool = False
    one_cycle_pct_start: float = 0.1
    max_steps: Optional[int] = None
    # TPU-framework extensions beyond the reference surface:
    grad_clip_norm: Optional[float] = None  # global-norm clipping before moments
    accumulate_steps: int = 1  # micro-batches averaged per optimizer update


def make_optimizer(
    config: OptimizerConfig,
) -> Tuple[optax.GradientTransformation, Callable[[int], float]]:
    """Build (transformation, lr_schedule) from the config.

    Raises ValueError when OneCycle is requested without ``max_steps``
    (reference ``lightning.py:65-67``).
    """
    k = config.accumulate_steps
    if k < 1:
        raise ValueError(f"accumulate_steps must be >= 1, got {k}")

    if config.one_cycle_lr:
        if config.max_steps is None:
            raise ValueError("OneCycleLR requires a max_steps value")
        # max_steps counts trainer (micro) steps; the schedule advances once
        # per optimizer update, i.e. every k micro steps
        schedule = torch_one_cycle_schedule(
            total_steps=max(config.max_steps // k, 1),
            max_lr=config.learning_rate,
            pct_start=config.one_cycle_pct_start,
        )
    else:
        schedule = optax.constant_schedule(config.learning_rate)

    name = config.optimizer
    if name == "Adam":
        chain = []
        if config.weight_decay:
            chain.append(optax.add_decayed_weights(config.weight_decay))
        chain += [
            optax.scale_by_adam(),
            optax.scale_by_learning_rate(schedule),
        ]
        tx = optax.chain(*chain)
    elif name == "AdamW":
        tx = optax.adamw(schedule, weight_decay=config.weight_decay)
    else:
        raise ValueError(f"unknown optimizer {name!r} (expected 'Adam' or 'AdamW')")

    if config.grad_clip_norm is not None:
        if config.grad_clip_norm <= 0:
            raise ValueError(f"grad_clip_norm must be > 0, got {config.grad_clip_norm}")
        tx = optax.chain(optax.clip_by_global_norm(config.grad_clip_norm), tx)

    if k > 1:
        ms = optax.MultiSteps(tx, every_k_schedule=k)
        # plain GradientTransformation view, so downstream wrappers
        # (freeze_subtrees' multi_transform) compose with it
        tx = optax.GradientTransformation(ms.init, ms.update)
        micro_schedule = schedule
        schedule = lambda step: micro_schedule(jnp.asarray(step) // k)

    return tx, schedule
