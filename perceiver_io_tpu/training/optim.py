"""Optimizer and LR-schedule factory.

Mirrors the reference optimizer surface (``lightning.py:50-79``): the
reference resolves ``--optimizer`` with ``getattr(torch.optim, name)``
(``lightning.py:60``), so any torch optimizer name works from its CLI. Here
the common names — Adam, AdamW, SGD, RMSprop, Adagrad, Adamax, NAdam,
RAdam — map to optax with torch's exact update semantics; unknown names
raise a loud error listing the supported set (a silent near-miss optimizer
is worse than a loud gap).

Semantic parity notes:

- torch ``Adam(weight_decay=w)`` is *coupled* L2: ``grad += w * param`` before
  the moment updates → ``optax.chain(add_decayed_weights, scale_by_adam, lr)``.
- torch ``AdamW(weight_decay=w)`` is decoupled, decay scaled by the lr →
  ``optax.adamw``.
- torch ``SGD(momentum=m)`` keeps ``buf = m·buf + grad`` (dampening 0) and
  steps by ``lr·buf`` → ``optax.trace(decay=m)``; weight decay is coupled L2
  applied before the momentum buffer.
- torch ``RMSprop``: ``sq = α·sq + (1−α)·g²``, step ``lr·g/(√sq + eps)`` with
  α=0.99, eps=1e-8 — the eps sits OUTSIDE the sqrt →
  ``optax.scale_by_rms(decay=0.99, eps=1e-8, eps_in_sqrt=False)``.
- torch ``Adagrad``: ``sum += g²``, step ``lr·g/(√sum + eps)`` with eps=1e-10
  and zero initial accumulator. optax's ``scale_by_rss`` puts eps inside the
  sqrt and special-cases sum==0, so ``_scale_by_adagrad_torch`` below
  reproduces the torch update directly.
- torch ``OneCycleLR(max_lr, pct_start, total_steps, cycle_momentum=False)``
  uses cosine annealing with ``div_factor=25``, ``final_div_factor=1e4``, a
  peak at step ``pct_start*total_steps - 1`` and the minimum at step
  ``total_steps - 1`` (one-shifted vs. ``optax.cosine_onecycle_schedule``) —
  reproduced exactly by ``torch_one_cycle_schedule`` below.

The schedule callable is returned alongside the transformation so steps can
log the current LR (the reference's per-step ``LearningRateMonitor``,
``train/utils.py:16-17``).
"""

from __future__ import annotations

import dataclasses
from typing import Callable, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import optax


def torch_one_cycle_schedule(
    total_steps: int,
    max_lr: float,
    pct_start: float = 0.1,
    div_factor: float = 25.0,
    final_div_factor: float = 1e4,
) -> Callable:
    """Cosine OneCycle with torch's exact phase boundaries.

    initial = max_lr/div_factor; min = initial/final_div_factor; cosine-anneal
    initial→max over steps [0, pct_start*total-1], then max→min over
    [pct_start*total-1, total-1]. jit-friendly (pure jnp on the step counter).
    """
    initial_lr = max_lr / div_factor
    min_lr = initial_lr / final_div_factor
    peak_step = max(pct_start * total_steps - 1.0, 1e-8)
    down_steps = max(total_steps - 1.0 - peak_step, 1e-8)

    def cos_anneal(start, end, frac):
        return end + (start - end) * (1.0 + jnp.cos(jnp.pi * frac)) / 2.0

    def schedule(step):
        s = jnp.asarray(step, jnp.float32)
        up = cos_anneal(initial_lr, max_lr, jnp.clip(s / peak_step, 0.0, 1.0))
        down = cos_anneal(max_lr, min_lr, jnp.clip((s - peak_step) / down_steps, 0.0, 1.0))
        return jnp.where(s <= peak_step, up, down)

    return schedule


@dataclasses.dataclass(frozen=True)
class OptimizerConfig:
    """Reference optimizer argparse group (``lightning.py:50-57``)."""

    optimizer: str = "Adam"  # any name make_optimizer maps (Adam, AdamW, SGD,
    # RMSprop, Adagrad, Adamax, NAdam, RAdam — torch-exact semantics each)
    learning_rate: float = 1e-3
    weight_decay: float = 0.0
    one_cycle_lr: bool = False
    one_cycle_pct_start: float = 0.1
    max_steps: Optional[int] = None
    # torch SGD momentum (the reference never sets it — its getattr call
    # passes only lr/weight_decay — but torch's default surface has it)
    momentum: float = 0.0
    # TPU-framework extensions beyond the reference surface:
    grad_clip_norm: Optional[float] = None  # global-norm clipping before moments
    accumulate_steps: int = 1  # micro-batches averaged per optimizer update


class _AdagradState(NamedTuple):
    sum_of_squares: object


def _scale_by_adagrad_torch(
    eps: float = 1e-10, initial_accumulator_value: float = 0.0
) -> optax.GradientTransformation:
    """torch ``Adagrad``'s exact scaling: ``sum += g²; g / (sqrt(sum) + eps)``.

    optax's ``scale_by_rss`` differs in two observable ways (eps inside the
    sqrt; a where() that zeroes updates while the accumulator is zero), so the
    torch update is implemented directly. State mirrors the param-tree paths
    like Adam's moments, so the ZeRO sharding rules apply unchanged.
    """

    def init_fn(params):
        return _AdagradState(
            sum_of_squares=jax.tree.map(
                lambda p: jnp.full_like(p, initial_accumulator_value), params
            )
        )

    def update_fn(updates, state, params=None):
        del params
        sums = jax.tree.map(
            lambda g, s: s + jnp.square(g), updates, state.sum_of_squares
        )
        updates = jax.tree.map(
            lambda g, s: g / (jnp.sqrt(s) + eps), updates, sums
        )
        return updates, _AdagradState(sum_of_squares=sums)

    return optax.GradientTransformation(init_fn, update_fn)


def _scale_by_rms_torch(
    decay: float = 0.99, eps: float = 1e-8
) -> optax.GradientTransformation:
    """torch ``RMSprop``'s exact scaling: ``nu = α·nu + (1-α)·g²;
    g / (sqrt(nu) + eps)`` — eps OUTSIDE the sqrt.

    The optax spelling is ``scale_by_rms(..., eps_in_sqrt=False)``, but the
    optax build this runs under predates that kwarg, so the torch update is
    implemented directly. State reuses ``optax.ScaleByRmsState`` (same
    ``nu`` param-tree mirror), so checkpoints and the ZeRO sharding rules
    are unchanged.
    """

    def init_fn(params):
        return optax.ScaleByRmsState(
            nu=jax.tree.map(jnp.zeros_like, params)
        )

    def update_fn(updates, state, params=None):
        del params
        nu = jax.tree.map(
            lambda g, n: decay * n + (1.0 - decay) * jnp.square(g),
            updates, state.nu,
        )
        updates = jax.tree.map(
            lambda g, n: g / (jnp.sqrt(n) + eps), updates, nu
        )
        return updates, optax.ScaleByRmsState(nu=nu)

    return optax.GradientTransformation(init_fn, update_fn)


class _MomentState(NamedTuple):
    count: object
    mu: object
    nu: object


def _scale_by_adamax_torch(
    b1: float = 0.9, b2: float = 0.999, eps: float = 1e-8
) -> optax.GradientTransformation:
    """torch ``Adamax``'s exact scaling (``torch/optim/adamax.py``):
    ``mu = b1*mu + (1-b1)*g``; ``nu = max(b2*nu, |g| + eps)`` (eps inside the
    max, so nu is never zero); step ``mu / ((1 - b1^t) * nu)``."""

    def init_fn(params):
        zeros = jax.tree.map(jnp.zeros_like, params)
        return _MomentState(count=jnp.zeros([], jnp.int32), mu=zeros,
                            nu=jax.tree.map(jnp.zeros_like, params))

    def update_fn(updates, state, params=None):
        del params
        count = state.count + 1
        mu = jax.tree.map(lambda g, m: b1 * m + (1 - b1) * g,
                          updates, state.mu)
        nu = jax.tree.map(
            lambda g, n: jnp.maximum(b2 * n, jnp.abs(g) + eps),
            updates, state.nu,
        )
        bc = 1 - b1 ** count.astype(jnp.float32)
        updates = jax.tree.map(lambda m, n: m / (bc * n), mu, nu)
        return updates, _MomentState(count=count, mu=mu, nu=nu)

    return optax.GradientTransformation(init_fn, update_fn)


class _NAdamState(NamedTuple):
    count: object
    mu_product: object
    mu: object
    nu: object


def _scale_by_nadam_torch(
    b1: float = 0.9,
    b2: float = 0.999,
    eps: float = 1e-8,
    momentum_decay: float = 4e-3,
) -> optax.GradientTransformation:
    """torch ``NAdam``'s exact scaling (``torch/optim/nadam.py``) — Nesterov
    momentum with the 0.96^(t·ψ) momentum-decay schedule torch adds on top of
    Dozat's formulation (optax's ``nesterov=True`` Adam lacks it):
    ``µ_t = b1·(1 − ½·0.96^(t·ψ))``, running ``µ_product``, and the step
    mixes the raw gradient and the first moment, each with its own
    bias-correction, over ``sqrt(nu/(1−b2^t)) + eps``."""

    def init_fn(params):
        return _NAdamState(
            count=jnp.zeros([], jnp.int32),
            mu_product=jnp.ones([], jnp.float32),
            mu=jax.tree.map(jnp.zeros_like, params),
            nu=jax.tree.map(jnp.zeros_like, params),
        )

    def update_fn(updates, state, params=None):
        del params
        count = state.count + 1
        t = count.astype(jnp.float32)
        mu_t = b1 * (1 - 0.5 * 0.96 ** (t * momentum_decay))
        mu_next = b1 * (1 - 0.5 * 0.96 ** ((t + 1) * momentum_decay))
        mu_product = state.mu_product * mu_t
        mu = jax.tree.map(lambda g, m: b1 * m + (1 - b1) * g,
                          updates, state.mu)
        nu = jax.tree.map(lambda g, n: b2 * n + (1 - b2) * jnp.square(g),
                          updates, state.nu)
        bc2 = 1 - b2 ** t
        g_scale = (1 - mu_t) / (1 - mu_product)
        m_scale = mu_next / (1 - mu_product * mu_next)
        updates = jax.tree.map(
            lambda g, m, n: (g_scale * g + m_scale * m)
            / (jnp.sqrt(n / bc2) + eps),
            updates, mu, nu,
        )
        return updates, _NAdamState(count=count, mu_product=mu_product,
                                    mu=mu, nu=nu)

    return optax.GradientTransformation(init_fn, update_fn)


def _scale_by_radam_torch(
    b1: float = 0.9, b2: float = 0.999, eps: float = 1e-8
) -> optax.GradientTransformation:
    """torch ``RAdam``'s exact scaling (``torch/optim/radam.py``): Adam
    moments, and while the variance-rectification term ``rho_t <= 5`` the
    step is the bias-corrected first moment ALONE (no second-moment
    denominator); afterwards the rectified adaptive step divides by
    ``sqrt(nu) + eps`` scaled by ``sqrt(1 - b2^t)`` (eps OUTSIDE the
    bias-corrected sqrt — a visible difference from optax's radam)."""
    rho_inf = 2.0 / (1.0 - b2) - 1.0

    def init_fn(params):
        return _MomentState(
            count=jnp.zeros([], jnp.int32),
            mu=jax.tree.map(jnp.zeros_like, params),
            nu=jax.tree.map(jnp.zeros_like, params),
        )

    def update_fn(updates, state, params=None):
        del params
        count = state.count + 1
        t = count.astype(jnp.float32)
        mu = jax.tree.map(lambda g, m: b1 * m + (1 - b1) * g,
                          updates, state.mu)
        nu = jax.tree.map(lambda g, n: b2 * n + (1 - b2) * jnp.square(g),
                          updates, state.nu)
        # -expm1(t·log b2) keeps 1 - b2^t fully precise in f32 at small t
        # (the naive form loses ~half the mantissa exactly where the
        # rectification boundary sits; torch does this math in python f64)
        bc1 = -jnp.expm1(t * jnp.log(jnp.float32(b1)))
        bc2 = -jnp.expm1(t * jnp.log(jnp.float32(b2)))
        rho_t = rho_inf - 2 * t * (b2 ** t) / bc2
        rect = jnp.sqrt(
            jnp.clip(
                (rho_t - 4) * (rho_t - 2) * rho_inf
                / ((rho_inf - 4) * (rho_inf - 2) * rho_t),
                0.0,
            )
        )
        rectified = rho_t > 5.0

        def leaf(m, n):
            m_hat = m / bc1
            adaptive = m_hat * rect * jnp.sqrt(bc2) / (jnp.sqrt(n) + eps)
            return jnp.where(rectified, adaptive, m_hat)

        updates = jax.tree.map(leaf, mu, nu)
        return updates, _MomentState(count=count, mu=mu, nu=nu)

    return optax.GradientTransformation(init_fn, update_fn)


def make_optimizer(
    config: OptimizerConfig,
) -> Tuple[optax.GradientTransformation, Callable[[int], float]]:
    """Build (transformation, lr_schedule) from the config.

    Raises ValueError when OneCycle is requested without ``max_steps``
    (reference ``lightning.py:65-67``).
    """
    k = config.accumulate_steps
    if k < 1:
        raise ValueError(f"accumulate_steps must be >= 1, got {k}")

    if config.one_cycle_lr:
        if config.max_steps is None:
            raise ValueError("OneCycleLR requires a max_steps value")
        # max_steps counts trainer (micro) steps; the schedule advances once
        # per optimizer update, i.e. every k micro steps
        schedule = torch_one_cycle_schedule(
            total_steps=max(config.max_steps // k, 1),
            max_lr=config.learning_rate,
            pct_start=config.one_cycle_pct_start,
        )
    else:
        schedule = optax.constant_schedule(config.learning_rate)

    name = config.optimizer
    # coupled L2 (torch's default weight_decay semantics for everything but
    # AdamW): grad += wd * param BEFORE any moment/accumulator update
    coupled_wd = (
        [optax.add_decayed_weights(config.weight_decay)]
        if config.weight_decay
        else []
    )
    if name == "Adam":
        tx = optax.chain(
            *coupled_wd,
            optax.scale_by_adam(),
            optax.scale_by_learning_rate(schedule),
        )
    elif name == "AdamW":
        tx = optax.adamw(schedule, weight_decay=config.weight_decay)
    elif name == "SGD":
        momentum = (
            [optax.trace(decay=config.momentum)] if config.momentum else []
        )
        tx = optax.chain(
            *coupled_wd, *momentum, optax.scale_by_learning_rate(schedule)
        )
    elif name == "RMSprop":
        # torch defaults: alpha=0.99, eps=1e-8, eps OUTSIDE the sqrt
        tx = optax.chain(
            *coupled_wd,
            _scale_by_rms_torch(decay=0.99, eps=1e-8),
            optax.scale_by_learning_rate(schedule),
        )
    elif name == "Adagrad":
        tx = optax.chain(
            *coupled_wd,
            _scale_by_adagrad_torch(),
            optax.scale_by_learning_rate(schedule),
        )
    elif name == "Adamax":
        # torch default weight_decay semantics: coupled L2
        tx = optax.chain(
            *coupled_wd,
            _scale_by_adamax_torch(),
            optax.scale_by_learning_rate(schedule),
        )
    elif name == "NAdam":
        # torch NAdam(decoupled_weight_decay=False) default: coupled L2
        tx = optax.chain(
            *coupled_wd,
            _scale_by_nadam_torch(),
            optax.scale_by_learning_rate(schedule),
        )
    elif name == "RAdam":
        # torch RAdam(decoupled_weight_decay=False) default: coupled L2
        tx = optax.chain(
            *coupled_wd,
            _scale_by_radam_torch(),
            optax.scale_by_learning_rate(schedule),
        )
    else:
        raise ValueError(
            f"unknown optimizer {name!r}: this maps torch.optim names to "
            f"optax with torch-exact update semantics, and supports 'Adam', "
            f"'AdamW', 'SGD', 'RMSprop', 'Adagrad', 'Adamax', 'NAdam', "
            f"'RAdam' (the reference resolves ANY torch.optim name via "
            f"getattr, lightning.py:60 — for another name, add a mapping in "
            f"training/optim.py; see docs/MIGRATION.md)"
        )

    if config.grad_clip_norm is not None:
        if config.grad_clip_norm <= 0:
            raise ValueError(f"grad_clip_norm must be > 0, got {config.grad_clip_norm}")
        tx = optax.chain(optax.clip_by_global_norm(config.grad_clip_norm), tx)

    if k > 1:
        ms = optax.MultiSteps(tx, every_k_schedule=k)
        # plain GradientTransformation view, so downstream wrappers
        # (freeze_subtrees' multi_transform) compose with it
        tx = optax.GradientTransformation(ms.init, ms.update)
        micro_schedule = schedule
        schedule = lambda step: micro_schedule(jnp.asarray(step) // k)

    return tx, schedule
