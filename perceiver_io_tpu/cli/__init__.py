"""CLI entry points (reference ``train/train_*.py`` equivalents)."""
