"""IMDB sequence-classification entry point (reference ``train/train_seq_clf.py``).

Three init modes, mirroring ``train_seq_clf.py:18-28``:

- ``--mlm_checkpoint <run_dir/checkpoints>``: rebuild the encoder from the
  checkpoint's embedded hparams, graft its pretrained params subtree into a
  fresh classifier (the reference's checkpoint surgery as a pure pytree swap),
  optionally ``--freeze_encoder`` (no updates + encoder runs in eval mode —
  ``freeze()`` parity, reference ``train/utils.py:5-8``);
- ``--clf_checkpoint <run_dir/checkpoints>``: resume a classifier run;
- neither: train from scratch.

Reference per-task defaults (``train_seq_clf.py:56-68``): batch 128,
weight_decay 1e-3, dropout 0.1.
"""

from __future__ import annotations

import argparse
from typing import Optional, Sequence

import jax

from perceiver_io_tpu.cli import common
from perceiver_io_tpu.data.imdb import IMDBDataModule
from perceiver_io_tpu.training import TrainState, make_classifier_steps
from perceiver_io_tpu.training.checkpoint import (
    load_hparams,
    restore_encoder_params,
    restore_train_state,
)
from perceiver_io_tpu.training.steps import freeze_subtrees
from perceiver_io_tpu.training.trainer import Trainer


def build_parser() -> argparse.ArgumentParser:
    parser = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    common.add_trainer_args(parser)
    common.add_mesh_args(parser)
    common.add_compute_args(parser)
    common.add_model_args(parser)
    common.add_optimizer_args(parser)
    common.add_imdb_args(parser)
    g = parser.add_argument_group("task (sequence classification)")
    g.add_argument("--mlm_checkpoint", default=None,
                   help="checkpoints dir of a train_mlm run: transfer its encoder")
    g.add_argument("--clf_checkpoint", default=None,
                   help="checkpoints dir of a train_seq_clf run: resume")
    g.add_argument("--freeze_encoder", action="store_true")
    # reference per-task defaults (train_seq_clf.py:56-68)
    parser.set_defaults(experiment="seq_clf", batch_size=128, weight_decay=1e-3,
                        dropout=0.1, num_latents=64, num_latent_channels=64,
                        num_encoder_layers=3)
    return parser


def main(argv: Optional[Sequence[str]] = None):
    args = common.parse_with_resume(build_parser(), argv)
    common.maybe_initialize_distributed(args)
    if args.mlm_checkpoint and args.clf_checkpoint:
        raise SystemExit("--mlm_checkpoint and --clf_checkpoint are exclusive")
    if args.resume and (args.mlm_checkpoint or args.clf_checkpoint):
        # conflicting init modes: --resume continues one run in place, the
        # checkpoint flags start a NEW run from another run's weights
        raise SystemExit(
            "--resume is exclusive with --mlm_checkpoint/--clf_checkpoint"
        )

    # a restored encoder must be rebuilt with the shapes it was trained with
    source_ckpt = args.mlm_checkpoint or args.clf_checkpoint
    if source_ckpt:
        common.override_model_args(args, load_hparams(source_ckpt))
    if args.clf_checkpoint:
        # resume also restores the training setup: the optimizer-state pytree
        # structure depends on these (load_from_checkpoint parity,
        # reference lightning.py:46 + train_seq_clf.py:26)
        hparams = load_hparams(args.clf_checkpoint)
        for key in ("optimizer", "weight_decay", "one_cycle_lr", "freeze_encoder"):
            if key in hparams:
                setattr(args, key, hparams[key])

    data = IMDBDataModule(
        root=args.root,
        max_seq_len=args.max_seq_len,
        vocab_size=args.vocab_size,
        batch_size=args.batch_size,
        synthetic=args.synthetic,
        synthetic_size=args.synthetic_size,
        seed=args.seed,
        shard_id=jax.process_index(),
        num_shards=jax.process_count(),
    )
    data.prepare_data()
    data.setup()
    vocab_size = data.tokenizer.get_vocab_size()

    model = common.build_text_classifier(args, vocab_size, args.max_seq_len)
    example = next(iter(data.val_dataloader()))
    variables = model.init(
        {"params": jax.random.key(args.seed)},
        example["token_ids"][:1], pad_mask=example["pad_mask"][:1],
    )
    params = variables["params"]

    if args.mlm_checkpoint:
        params = dict(params)
        params["encoder"] = restore_encoder_params(
            args.mlm_checkpoint, params["encoder"]
        )

    tx, schedule = common.optimizer_from_args(args)
    if args.freeze_encoder:
        tx = freeze_subtrees(tx, params, ["encoder"])
    state = TrainState.create(params, tx, jax.random.key(args.seed + 2))
    state, resume_dir = common.resume_state(args, state)

    if args.clf_checkpoint:
        state = restore_train_state(args.clf_checkpoint, state)

    train_step, eval_step = make_classifier_steps(
        model, schedule, input_kind="text", frozen_encoder=args.freeze_encoder
    )
    mesh = common.mesh_from_args(args)

    trainer = Trainer(
        train_step,
        lambda s, b, k: eval_step(s, b),
        state,
        common.trainer_config(args),
        example_batch={k: example[k] for k in ("token_ids", "pad_mask", "label")},
        mesh=mesh,
        shard_seq=args.shard_seq,
        zero_opt=args.zero_opt,
        hparams=vars(args),
        run_dir=resume_dir,
        tokens_per_example=args.max_seq_len,
    )
    with trainer:
        trainer.fit(data.train_dataloader(), data.val_dataloader())
    return trainer.run_dir


if __name__ == "__main__":
    main()
