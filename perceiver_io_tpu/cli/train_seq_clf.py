"""IMDB sequence-classification entry point (reference ``train/train_seq_clf.py``).

Three init modes, mirroring ``train_seq_clf.py:18-28``:

- ``--mlm_checkpoint <run_dir/checkpoints>``: rebuild the encoder from the
  checkpoint's embedded hparams, graft its pretrained params subtree into a
  fresh classifier (the reference's checkpoint surgery as a pure pytree swap),
  optionally ``--freeze_encoder`` (no updates + encoder runs in eval mode —
  ``freeze()`` parity, reference ``train/utils.py:5-8``);
- ``--clf_checkpoint <run_dir/checkpoints>``: resume a classifier run;
- neither: train from scratch.

Both checkpoint flags also accept a reference PyTorch-Lightning ``.ckpt``
FILE (the artifacts the reference publishes, ``README.md:46-48``) — the torch
state_dict is converted on the fly (``perceiver_io_tpu/interop.py``), so the
reference's pretrained-weights workflow transfers unchanged. A ``.ckpt``
carries no compatible optimizer state, so ``--clf_checkpoint model.ckpt``
restores weights and starts a fresh optimizer (the reference's
``load_from_checkpoint`` does the same, ``train_seq_clf.py:26``).

Reference per-task defaults (``train_seq_clf.py:56-68``): batch 128,
weight_decay 1e-3, dropout 0.1.
"""

from __future__ import annotations

import argparse
from typing import Optional, Sequence

import jax

from perceiver_io_tpu.cli import common
from perceiver_io_tpu.data.imdb import IMDBDataModule
from perceiver_io_tpu.training import TrainState, make_classifier_steps
from perceiver_io_tpu.training.checkpoint import (
    load_hparams,
    restore_encoder_params,
    restore_train_state,
)
from perceiver_io_tpu.training.steps import freeze_subtrees
from perceiver_io_tpu.training.trainer import Trainer


def _is_torch_ckpt(path: str) -> bool:
    import os

    return os.path.isfile(path) and path.endswith(".ckpt")


def _check_tree(imported, like, source: str):
    """Imported params must exactly match the fresh model's tree — a mismatch
    means the .ckpt was trained with different shapes/hparams."""
    import jax

    imported_paths = {
        jax.tree_util.keystr(p): leaf.shape
        for p, leaf in jax.tree_util.tree_leaves_with_path(imported)
    }
    like_paths = {
        jax.tree_util.keystr(p): leaf.shape
        for p, leaf in jax.tree_util.tree_leaves_with_path(like)
    }
    if imported_paths != like_paths:
        missing = sorted(set(like_paths) - set(imported_paths))
        extra = sorted(set(imported_paths) - set(like_paths))
        mismatched = sorted(
            k for k in set(like_paths) & set(imported_paths)
            if like_paths[k] != imported_paths[k]
        )
        raise SystemExit(
            f"imported checkpoint {source} does not fit the model: "
            f"missing={missing[:4]} extra={extra[:4]} shape-mismatch={mismatched[:4]}"
        )
    return imported


def _warn_if_vocab_mismatch(tokenizer_path: str, ckpt: str) -> None:
    """A reference .ckpt's embedding rows are indexed by the reference's
    exact vocab. A locally-trained WordPiece of the same size passes every
    shape check while assigning different ids — warn loudly so the silent
    quality degradation is visible. (The reference's cached HF tokenizer
    JSON drops in at ``<root>/imdb-tokenizer-10003.json``.)"""
    import json
    import warnings

    try:
        with open(tokenizer_path, encoding="utf-8") as f:
            native = json.load(f).get("format", "").startswith("perceiver_io_tpu")
    except (OSError, ValueError):
        native = False
    if native:
        warnings.warn(
            f"importing {ckpt} while using a locally-trained tokenizer "
            f"({tokenizer_path}): token ids almost certainly differ from the "
            f"vocab the checkpoint was trained with, so pretrained embeddings "
            f"will be misaligned. Drop the reference's tokenizer JSON at that "
            f"path (tools/import_reference.py tokenizer) for exact ids.",
            stacklevel=2,
        )


def build_parser() -> argparse.ArgumentParser:
    parser = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    common.add_trainer_args(parser)
    common.add_mesh_args(parser)
    common.add_compute_args(parser)
    common.add_model_args(parser)
    common.add_optimizer_args(parser)
    common.add_imdb_args(parser)
    g = parser.add_argument_group("task (sequence classification)")
    g.add_argument("--mlm_checkpoint", default=None,
                   help="checkpoints dir of a train_mlm run: transfer its encoder")
    g.add_argument("--clf_checkpoint", default=None,
                   help="checkpoints dir of a train_seq_clf run: resume")
    g.add_argument("--freeze_encoder", action="store_true")
    g.add_argument("--unsafe_load", action="store_true",
                   help="when a checkpoint flag points at a torch .ckpt that "
                        "the safe weights-only loader rejects, fall back to "
                        "the unrestricted pickle loader (executes code "
                        "embedded in the file — only for trusted artifacts)")
    # reference per-task defaults (train_seq_clf.py:56-68)
    parser.set_defaults(experiment="seq_clf", batch_size=128, weight_decay=1e-3,
                        dropout=0.1, num_latents=64, num_latent_channels=64,
                        num_encoder_layers=3)
    return parser


def main(argv: Optional[Sequence[str]] = None):
    args = common.parse_with_resume(build_parser(), argv)
    if common.maybe_spawn_hosts(args, argv):
        return None  # training ran in the spawned processes
    common.maybe_initialize_distributed(args)
    if args.mlm_checkpoint and args.clf_checkpoint:
        raise SystemExit("--mlm_checkpoint and --clf_checkpoint are exclusive")
    if args.resume and (args.mlm_checkpoint or args.clf_checkpoint):
        # conflicting init modes: --resume continues one run in place, the
        # checkpoint flags start a NEW run from another run's weights
        raise SystemExit(
            "--resume is exclusive with --mlm_checkpoint/--clf_checkpoint"
        )

    # a restored encoder must be rebuilt with the shapes it was trained with
    source_ckpt = args.mlm_checkpoint or args.clf_checkpoint
    imported_params = None  # set when the source is a reference .ckpt file
    if source_ckpt and _is_torch_ckpt(source_ckpt):
        from perceiver_io_tpu.interop import import_lightning_checkpoint

        imported_params, source_hparams = import_lightning_checkpoint(
            source_ckpt, allow_unsafe_pickle=args.unsafe_load
        )
        common.override_model_args(args, source_hparams)
    elif source_ckpt:
        source_hparams = load_hparams(source_ckpt)
        common.override_model_args(args, source_hparams)
    if args.clf_checkpoint and imported_params is None:
        # resume also restores the training setup: the optimizer-state pytree
        # structure depends on these (load_from_checkpoint parity,
        # reference lightning.py:46 + train_seq_clf.py:26)
        hparams = load_hparams(args.clf_checkpoint)
        for key in ("optimizer", "weight_decay", "one_cycle_lr", "freeze_encoder"):
            if key in hparams:
                setattr(args, key, hparams[key])

    common.validate_bucket_args(args)
    data = IMDBDataModule(
        root=args.root,
        max_seq_len=args.max_seq_len,
        vocab_size=args.vocab_size,
        batch_size=args.batch_size,
        synthetic=args.synthetic,
        synthetic_size=args.synthetic_size,
        seed=args.seed,
        shard_id=jax.process_index(),
        num_shards=jax.process_count(),
        download=not args.no_download,
        bucket_widths=args.bucket_widths,
        length_sort_window=args.length_sort_window,
        dispatch_group=args.steps_per_dispatch,
    )
    data.prepare_data()
    data.setup()
    vocab_size = data.tokenizer.get_vocab_size()

    if imported_params is not None:
        _warn_if_vocab_mismatch(data.tokenizer_path, source_ckpt)

    model = common.build_text_classifier(args, vocab_size, args.max_seq_len)
    example = next(iter(data.val_dataloader()))
    variables = model.init(
        {"params": jax.random.key(args.seed)},
        example["token_ids"][:1], pad_mask=example["pad_mask"][:1],
    )
    params = variables["params"]

    if args.mlm_checkpoint:
        params = dict(params)
        if imported_params is not None:
            params["encoder"] = _check_tree(
                imported_params["encoder"], params["encoder"], args.mlm_checkpoint
            )
        else:
            params["encoder"] = restore_encoder_params(
                args.mlm_checkpoint, params["encoder"]
            )
    if args.clf_checkpoint and imported_params is not None:
        params = _check_tree(imported_params, params, args.clf_checkpoint)

    tx, schedule = common.optimizer_from_args(args)
    if args.freeze_encoder:
        tx = freeze_subtrees(tx, params, ["encoder"])
    state = TrainState.create(params, tx, jax.random.key(args.seed + 2))
    state, resume_dir = common.resume_state(args, state)

    if args.clf_checkpoint and imported_params is None:
        state = restore_train_state(args.clf_checkpoint, state)

    train_step, eval_step = make_classifier_steps(
        model, schedule, input_kind="text", frozen_encoder=args.freeze_encoder
    )
    mesh = common.mesh_from_args(args)

    trainer = Trainer(
        train_step,
        lambda s, b, k: eval_step(s, b),
        state,
        common.trainer_config(args),
        example_batch={k: example[k] for k in ("token_ids", "pad_mask", "label")},
        mesh=mesh,
        shard_seq=args.shard_seq,
        zero_opt=args.zero_opt,
        hparams=vars(args),
        run_dir=resume_dir,
        tokens_per_example=args.max_seq_len,
    )
    with trainer:
        common.run_fit(trainer, data.train_dataloader(), data.val_dataloader())
    return trainer.run_dir


if __name__ == "__main__":
    main()
