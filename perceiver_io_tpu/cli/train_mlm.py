"""MLM pretraining entry point (reference ``train/train_mlm.py``).

Reproduces the reference CLI surface and per-task defaults
(``train_mlm.py:93-106``: 64 latents × 64 channels, 3 encoder layers,
512-token sequences, batch 64) plus the per-validation-epoch masked-token
top-k sample predictions logged as text (``train_mlm.py:38-56``), on the
TPU-native stack: SPMD mesh instead of DDP, Orbax checkpoints, bf16 compute.

Usage (mirroring the reference README):

    python train/train_mlm.py --dataset=imdb --experiment=mlm \
        --one_cycle_lr --learning_rate=3e-3 --max_steps=50000
"""

from __future__ import annotations

import argparse
from typing import Optional, Sequence

import jax
import numpy as np

from perceiver_io_tpu.cli import common
from perceiver_io_tpu.data.imdb import IMDBDataModule
from perceiver_io_tpu.data.tokenizer import MASK_TOKEN
from perceiver_io_tpu.training import TrainState, make_mlm_steps, mlm_gather_capacity
from perceiver_io_tpu.training.trainer import Trainer

DEFAULT_PREDICT_SAMPLES = (
    "i have watched this [MASK] and it was awesome",
    "this movie was [MASK] from start to finish",
)


# Width/compute DEFAULTS per --preset, applied post-parse by apply_preset:
# the parser defaults the affected args to None (a sentinel), so explicit
# flags, resume's hparams-as-defaults layering, and the preset compose
# without any dependence on global sys.argv. attn_impl 'xla' under
# flagship_tpu is the measured-best at TPU widths (models/presets.py
# flagship_tpu_mlm).
PRESET_DEFAULTS = {
    "reference": {"num_latents": 64, "num_latent_channels": 64,
                  "attn_impl": "auto"},
    "flagship_tpu": {"num_latents": 256, "num_latent_channels": 512,
                     "attn_impl": "xla"},
}


def apply_preset(args: argparse.Namespace) -> argparse.Namespace:
    """Fill any still-None width/compute args from the chosen preset."""
    for key, value in PRESET_DEFAULTS[args.preset].items():
        if getattr(args, key) is None:
            setattr(args, key, value)
    return args


def build_parser() -> argparse.ArgumentParser:
    parser = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    common.add_trainer_args(parser)
    common.add_mesh_args(parser)
    common.add_compute_args(parser)
    common.add_model_args(parser)
    common.add_optimizer_args(parser)
    common.add_imdb_args(parser)
    g = parser.add_argument_group("task (MLM)")
    g.add_argument("--preset", choices=["reference", "flagship_tpu"],
                   default="reference",
                   help="model-width preset: 'reference' = the GPU-sized "
                        "train_mlm defaults (64 latents x 64 channels, head "
                        "depth 16); 'flagship_tpu' = the same recipe at "
                        "TPU-native widths (256 latents x 512 channels, head "
                        "depth 128 — models/presets.py flagship_tpu_mlm). "
                        "Explicit --num_latents/--num_latent_channels still "
                        "override the preset")
    g.add_argument("--num_predictions", "--predict_k", type=int, default=5,
                   help="top-k predictions logged per [MASK] position "
                        "(--predict_k is the reference's spelling)")
    g.add_argument("--predict_samples", nargs="*", default=list(DEFAULT_PREDICT_SAMPLES))
    g.add_argument("--loss_gather_capacity", type=int, default=-1,
                   help="decode only the masked positions, up to this many per "
                        "row (gradient-equivalent, skips most vocab-projection "
                        "FLOPs). -1 = auto (2·mask_p·seq_len), 0 = full decode")
    g.add_argument("--fused_head", choices=["auto", "pallas", "xla", "off"],
                   default="auto",
                   help="fuse the vocab projection into the CE so the "
                        "(B, K, V) logits never materialize: 'pallas' = the "
                        "flash-CE kernel (the measured winner on TPU, "
                        "PERF.md r3), 'xla' = chunked-scan variant, 'off' = "
                        "unfused. auto = pallas only on a single-device TPU "
                        "mesh (off under ANY multi-chip sharding — dp/sp/tp "
                        "— and on other backends)")
    # reference per-task defaults (train_mlm.py:93-106); the preset-affected
    # args default to the None sentinel apply_preset resolves
    parser.set_defaults(experiment="mlm", batch_size=64, num_latents=None,
                        num_latent_channels=None, attn_impl=None,
                        num_encoder_layers=3)
    return parser


def encode_masked_samples(collator, samples: Sequence[str]):
    """Encode raw strings containing the ``[MASK]`` literal
    (see :func:`perceiver_io_tpu.inference.encode_masked_texts`)."""
    from perceiver_io_tpu.inference import encode_masked_texts

    return encode_masked_texts(collator.tokenizer, samples, collator.max_seq_len)


def make_predict_hook(predict_fn, collator, samples: Sequence[str], k: int):
    """Sample-prediction channel (reference ``train_mlm.py:14-35,44-56``):
    no-masking forward, top-k over the ``[MASK]`` positions, decoded text."""
    if not samples:
        return None
    tokenizer = collator.tokenizer
    mask_id = tokenizer.token_to_id(MASK_TOKEN)
    token_ids, pad_mask = encode_masked_samples(collator, samples)
    jit_predict = jax.jit(predict_fn)
    # The hook logs top-k at the FIRST mask position per sample (reference
    # semantics), and the sample token ids are fixed for the whole run — so
    # decode exactly those positions instead of all max_seq_len: at long
    # context the full (B, L, vocab) logits would be a GB-scale fetch per
    # evaluation. Rows without a mask decode position 0 and are skipped.
    has_mask = (token_ids == mask_id).any(axis=1)
    first_mask = np.where(
        has_mask, (token_ids == mask_id).argmax(axis=1), 0
    ).astype(np.int32)[:, None]

    def hook(state, logger, step):
        logits = np.asarray(jax.device_get(
            jit_predict(state.params, token_ids, pad_mask, first_mask)
        ))
        lines = []
        for row in range(len(samples)):
            if not has_mask[row]:
                continue
            # top-k over the first mask position, as the reference logs
            top = np.argsort(-logits[row, 0])[:k]
            filled = [
                samples[row].replace(MASK_TOKEN, f"**{tokenizer.id_to_token(int(t))}**", 1)
                for t in top
            ]
            lines.append(samples[row] + "\n\n" + "\n".join(f"- {s}" for s in filled))
        if lines:
            logger.log_text("predictions", step, "\n\n---\n\n".join(lines))

    return hook


def main(argv: Optional[Sequence[str]] = None):
    args = apply_preset(common.parse_with_resume(build_parser(), argv))
    if common.maybe_spawn_hosts(args, argv):
        return None  # training ran in the spawned processes
    common.maybe_initialize_distributed(args)
    # after distributed init: the multi-host guard reads jax.process_count()
    common.validate_bucket_args(args)

    data = IMDBDataModule(
        root=args.root,
        max_seq_len=args.max_seq_len,
        vocab_size=args.vocab_size,
        batch_size=args.batch_size,
        synthetic=args.synthetic,
        synthetic_size=args.synthetic_size,
        seed=args.seed,
        shard_id=jax.process_index(),
        num_shards=jax.process_count(),
        download=not args.no_download,
        bucket_widths=args.bucket_widths,
        length_sort_window=args.length_sort_window,
        dispatch_group=args.steps_per_dispatch,
    )
    data.prepare_data()
    data.setup()
    vocab_size = data.tokenizer.get_vocab_size()

    model = common.build_mlm(args, vocab_size, args.max_seq_len)
    example = next(iter(data.val_dataloader()))
    variables = model.init(
        {"params": jax.random.key(args.seed), "masking": jax.random.key(args.seed + 1)},
        example["token_ids"][:1], example["pad_mask"][:1],
    )
    tx, schedule = common.optimizer_from_args(args)
    state = TrainState.create(variables["params"], tx, jax.random.key(args.seed + 2))
    state, resume_dir = common.resume_state(args, state)

    capacity = args.loss_gather_capacity
    if capacity < 0:
        capacity = mlm_gather_capacity(args.max_seq_len)
    mesh = common.mesh_from_args(args)
    fused = args.fused_head
    if fused == "auto":
        # the flash-CE kernel is a single-device op (ops/pallas_ce.py):
        # auto enables it only on a single-device TPU mesh — under ANY
        # multi-chip sharding GSPMD cannot partition the pallas_call (it
        # would all-gather the gathered-decode features on every chip),
        # so sharded meshes keep the unfused head whose collectives GSPMD
        # manages. Explicit 'pallas' overrides for dp/sp (correct, possibly
        # slower); tp is rejected below (vocab sharding conflicts). The
        # width gate is measured: at C=64 the kernel is +6.1% (PERF.md r3),
        # at C=512 it's -2% (the K=512-deep head matmuls are MXU-efficient,
        # so skipping the logits traffic no longer pays — r4 roofline A/B).
        fused = ("pallas" if jax.default_backend() == "tpu"
                 and mesh.size == 1
                 and args.num_latent_channels <= 128 else "off")
    elif fused == "pallas" and mesh.shape["model"] > 1:
        raise SystemExit(
            "--fused_head pallas is a single-device head; with --tp > 1 the "
            "vocab projection shards over the model axis — use auto or off"
        )
    train_step, eval_step, predict_fn = make_mlm_steps(
        model, schedule, loss_gather_capacity=capacity or None,
        fused_head={"pallas": "pallas", "xla": True, "off": False}[fused],
    )

    trainer = Trainer(
        train_step,
        eval_step,
        state,
        common.trainer_config(args),
        example_batch={k: example[k] for k in ("token_ids", "pad_mask")},
        mesh=mesh,
        shard_seq=args.shard_seq,
        zero_opt=args.zero_opt,
        hparams=vars(args),
        run_dir=resume_dir,
        predict_hook=make_predict_hook(
            predict_fn, data.collator, args.predict_samples, args.num_predictions
        ),
        tokens_per_example=args.max_seq_len,
    )
    with trainer:
        state = common.run_fit(
            trainer, data.train_dataloader(), data.val_dataloader()
        )
    return trainer.run_dir


if __name__ == "__main__":
    main()
