"""Optical-flow training entry point (framework extension — the reference has
no flow task; this exercises BASELINE.md's Sintel config end-to-end: frame-pair
input adapter, dense per-pixel query decoder, end-point-error loss).

Usage:

    python train/train_flow.py --synthetic --experiment=flow \
        --image_height 64 --image_width 64 --max_epochs 10
"""

from __future__ import annotations

import argparse
from typing import Optional, Sequence

import jax

from perceiver_io_tpu.cli import common
from perceiver_io_tpu.data.flow import FlowDataModule
from perceiver_io_tpu.models.flow import build_optical_flow_model
from perceiver_io_tpu.training import TrainState, make_flow_steps
from perceiver_io_tpu.training.trainer import Trainer


def build_parser() -> argparse.ArgumentParser:
    parser = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    common.add_trainer_args(parser)
    common.add_mesh_args(parser)
    common.add_compute_args(parser)
    common.add_model_args(parser)
    common.add_optimizer_args(parser)
    g = parser.add_argument_group("data (optical flow)")
    g.add_argument("--root", default=".cache")
    g.add_argument("--batch_size", type=int, default=8)
    g.add_argument("--image_height", type=int, default=368)
    g.add_argument("--image_width", type=int, default=496)
    g.add_argument("--image_channels", type=int, default=3)
    g.add_argument("--synthetic", action="store_true")
    g.add_argument("--synthetic_size", type=int, default=512)
    t = parser.add_argument_group("task (optical flow)")
    t.add_argument("--patch_size", type=int, default=3)
    t.add_argument("--num_frequency_bands", type=int, default=64)
    # flow-scale defaults (Perceiver IO paper config, scaled by CLI flags)
    parser.set_defaults(experiment="flow", num_latents=2048,
                        num_latent_channels=512, num_encoder_layers=1,
                        num_self_attention_layers_per_block=24,
                        num_cross_attention_heads=1,
                        num_self_attention_heads=8)
    return parser


def main(argv: Optional[Sequence[str]] = None):
    args = common.parse_with_resume(build_parser(), argv)
    if common.maybe_spawn_hosts(args, argv):
        return None  # training ran in the spawned processes
    common.maybe_initialize_distributed(args)
    image_shape = (args.image_height, args.image_width, args.image_channels)

    data = FlowDataModule(
        root=args.root,
        image_shape=image_shape,
        batch_size=args.batch_size,
        synthetic=args.synthetic,
        synthetic_size=args.synthetic_size,
        seed=args.seed,
        shard_id=jax.process_index(),
        num_shards=jax.process_count(),
    )
    data.prepare_data()
    data.setup()

    model = build_optical_flow_model(
        image_shape=image_shape,
        latent_shape=(args.num_latents, args.num_latent_channels),
        num_layers=args.num_encoder_layers,
        num_self_attention_layers_per_block=args.num_self_attention_layers_per_block,
        num_cross_attention_heads=args.num_cross_attention_heads,
        num_self_attention_heads=args.num_self_attention_heads,
        patch_size=args.patch_size,
        num_frequency_bands=args.num_frequency_bands,
        dropout=args.dropout,
        dtype=common.DTYPES[args.dtype],
        attn_impl=args.attn_impl,
        remat=args.remat,
        reuse_kv=not getattr(args, "no_reuse_kv", False),
    )
    example = next(iter(data.val_dataloader()))
    variables = model.init(
        {"params": jax.random.key(args.seed)}, example["frames"][:1]
    )
    tx, schedule = common.optimizer_from_args(args)
    state = TrainState.create(variables["params"], tx, jax.random.key(args.seed + 2))
    state, resume_dir = common.resume_state(args, state)

    train_step, eval_step = make_flow_steps(model, schedule)
    mesh = common.mesh_from_args(args)

    trainer = Trainer(
        train_step,
        lambda s, b, k: eval_step(s, b),
        state,
        common.trainer_config(args),
        example_batch={k: example[k] for k in ("frames", "flow")},
        mesh=mesh,
        shard_seq=args.shard_seq,
        zero_opt=args.zero_opt,
        hparams=vars(args),
        run_dir=resume_dir,
    )
    with trainer:
        common.run_fit(trainer, data.train_dataloader(), data.val_dataloader())
    return trainer.run_dir


if __name__ == "__main__":
    main()
