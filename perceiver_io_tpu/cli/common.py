"""Shared CLI plumbing: layered argparse groups + model/optimizer builders.

Mirrors the reference's composed-parser pattern (each layer contributes an
argument group: model ``lightning.py:26-40``, optimizer ``lightning.py:50-57``,
data ``imdb.py:103-112`` / ``mnist.py:53-61``, Trainer flags, per-task
``set_defaults`` — reference ``train_mlm.py:80-106``), with TPU-specific
groups the reference has no analogue for: mesh construction (dp/tp/sp — the
DDP-flags replacement) and compute (dtype / attention impl / remat).
"""

from __future__ import annotations

import argparse
import os
from typing import Optional, Tuple

import jax.numpy as jnp

import perceiver_io_tpu as pit
from perceiver_io_tpu.ops.masking import TextMasking
from perceiver_io_tpu.parallel.mesh import make_mesh
from perceiver_io_tpu.training.optim import OptimizerConfig, make_optimizer
from perceiver_io_tpu.training.trainer import TrainerConfig

DTYPES = {"float32": jnp.float32, "bfloat16": jnp.bfloat16}


# -- argument groups ---------------------------------------------------------


def add_model_args(parser: argparse.ArgumentParser) -> None:
    g = parser.add_argument_group("model")
    g.add_argument("--num_latents", type=int, default=64)
    g.add_argument("--num_latent_channels", type=int, default=64)
    g.add_argument("--num_encoder_layers", type=int, default=3)
    g.add_argument("--num_self_attention_layers_per_block", type=int, default=6)
    g.add_argument("--num_cross_attention_heads", type=int, default=4)
    g.add_argument("--num_self_attention_heads", type=int, default=4)
    g.add_argument("--dropout", type=float, default=0.0)


def add_optimizer_args(parser: argparse.ArgumentParser) -> None:
    g = parser.add_argument_group("optimizer")
    g.add_argument("--optimizer",
                   choices=("Adam", "AdamW", "SGD", "RMSprop", "Adagrad",
                            "Adamax", "NAdam", "RAdam"),
                   default="Adam",
                   help="torch.optim name (the reference resolves any name "
                        "via getattr; these are mapped to optax with torch's "
                        "exact update semantics)")
    g.add_argument("--learning_rate", type=float, default=1e-3)
    g.add_argument("--weight_decay", type=float, default=0.0)
    g.add_argument("--momentum", type=float, default=0.0,
                   help="SGD momentum (torch trace semantics; ignored by "
                        "other optimizers)")
    g.add_argument("--one_cycle_lr", action="store_true")
    g.add_argument("--one_cycle_pct_start", type=float, default=0.1)
    g.add_argument("--grad_clip_norm", type=float, default=None,
                   help="clip gradients to this global norm before the update")
    g.add_argument("--accumulate_steps", type=int, default=1,
                   help="average gradients over N micro-batches per optimizer "
                        "update (effective batch = N * batch_size)")


def add_trainer_args(parser: argparse.ArgumentParser) -> None:
    g = parser.add_argument_group("trainer")
    g.add_argument("--max_epochs", type=int, default=None)
    g.add_argument("--max_steps", type=int, default=None)
    g.add_argument("--log_every_n_steps", type=int, default=50)
    g.add_argument("--eval_every_n_steps", type=int, default=None,
                   help="validate every N steps (default: once per epoch)")
    g.add_argument("--logdir", default="logs")
    g.add_argument("--experiment", default="default")
    g.add_argument("--max_to_keep", type=int, default=1)
    g.add_argument("--no_tensorboard", action="store_true")
    g.add_argument("--profile_steps", type=int, default=0,
                   help="capture a profiler trace of N OPTIMIZER steps after "
                        "warmup (a K-step dispatch advances it by K; keep "
                        "the window under a few seconds of device time — "
                        "longer windows can overflow the xplane export, "
                        "which the trainer now warns about)")
    g.add_argument("--steps_per_dispatch", type=int, default=1,
                   help="lax.scan N optimizer steps per device dispatch — "
                        "amortizes per-call latency on remote/tunneled "
                        "accelerators (PERF.md)")
    g.add_argument("--selfprofile_every_n_steps", type=int, default=0,
                   help="in-loop device-trace watchdog: every N optimizer "
                        "steps capture a short jax.profiler trace, analyze "
                        "it in-process (utils/xplane.py lower quartile), and "
                        "log device/host step time + MFU + compile count as "
                        "registry gauges and metrics.jsonl rows (PERF.md "
                        "§Observability). 0 disables")
    g.add_argument("--selfprofile_steps", type=int, default=4,
                   help="dispatches per watchdog capture window")
    g.add_argument("--debug_nans", action="store_true",
                   help="NaN localization (sanitizer): enable jax_debug_nans "
                        "so the first dispatch producing NaN/Inf re-runs "
                        "de-optimized and raises at the originating op. "
                        "Slow (per-dispatch host sync, no state donation) — "
                        "for post-mortems; halt_on_nonfinite already detects "
                        "divergence in production")
    g.add_argument("--resume", default=None, metavar="RUN_DIR",
                   help="continue a previous run in place: restore the newest "
                        "checkpoint (the preemption last/ slot if present), "
                        "override model args from its hparams, and keep "
                        "logging into the same run directory")
    g.add_argument("--skip_nonfinite_steps", action="store_true",
                   help="self-healing: check the loss after EVERY dispatch "
                        "and SKIP a non-finite step (keep the pre-step state) "
                        "instead of letting NaN poison the moments; after "
                        "--rollback_after_bad_steps consecutive bad steps, "
                        "roll back to the newest checkpoint. Costs one host "
                        "sync per dispatch and disables state donation "
                        "(PERF.md §Reliability)")
    g.add_argument("--rollback_after_bad_steps", type=int, default=3,
                   help="with --skip_nonfinite_steps: consecutive bad steps "
                        "before rolling back to the newest checkpoint "
                        "(0 = skip only, never roll back)")
    g.add_argument("--dispatch_error_retries", type=int, default=0,
                   help="self-healing: retry a train dispatch that fails "
                        "with a TRANSIENT error (tunnel drop, PJRT "
                        "UNAVAILABLE — never divergence or shape bugs) with "
                        "exponential backoff, up to N times per step. "
                        "Implies the per-dispatch host sync. 0 disables")
    g.add_argument("--fit_attempts", type=int, default=1,
                   help="self-healing: total fit attempts — on a transient "
                        "failure that escapes the per-step retries, "
                        "auto-resume from the newest checkpoint "
                        "(fit_with_recovery supervisor). 1 = no supervisor")
    g.add_argument("--step_timeout_s", type=float, default=None,
                   help="bounded-exit deadline on the train dispatch cycle: "
                        "if no step completion is observed within this many "
                        "seconds (a dead peer wedging a collective), dump "
                        "thread stacks and exit with the transient code 75 "
                        "so --spawn_attempts supervision restarts the world "
                        "(resilience/multihost.py). Default: off")
    g.add_argument("--peer_heartbeat_s", type=float, default=0.0,
                   help="multi-host peer-liveness heartbeat cadence over the "
                        "jax.distributed KV store; a peer that stops beating "
                        "for 5 intervals is declared dead and this host "
                        "exits transient (75) instead of hanging in its "
                        "next collective. 0 = off")
    g.add_argument("--compile_cache", default=None, metavar="DIR",
                   help="cold start: persist XLA compilations here (jax's "
                        "persistent compilation cache, min compile time 0) "
                        "so restarts/resumes skip the remote compile of an "
                        "unchanged step. Fail-soft: an unusable dir warns "
                        "and trains uncached (PERF.md §Cold start)")
    g.add_argument("--publish_dir", default=None, metavar="DIR",
                   help="continuous deployment (perceiver_io_tpu.deploy): "
                        "atomically publish the current params here every "
                        "--publish_every_n_steps steps, with a manifest "
                        "(step, val metrics, content digest) — the feed "
                        "serve.py --watch_checkpoints admission-gates and "
                        "hot-swaps into live serving. Fail-soft: a failed "
                        "publish warns, training continues")
    g.add_argument("--publish_every_n_steps", type=int, default=0,
                   help="publication cadence in optimizer steps (required "
                        "with --publish_dir)")


def add_mesh_args(parser: argparse.ArgumentParser) -> None:
    g = parser.add_argument_group("mesh (the DDP-flags replacement)")
    g.add_argument("--dp", type=int, default=None,
                   help="data-parallel size (default: n_devices / (tp*sp))")
    g.add_argument("--tp", type=int, default=1, help="tensor-parallel size")
    g.add_argument("--sp", type=int, default=1,
                   help="sequence-parallel size (shards the input axis M)")
    g.add_argument("--dcn_dp", type=int, default=1,
                   help="outer data-parallel factor placed across slice/host "
                        "(DCN) boundaries; must divide dp. The inner data "
                        "factor and tp/sp stay on each slice's ICI")
    g.add_argument("--shard_seq", action="store_true",
                   help="shard batches over the seq mesh axis: token axis for "
                        "text, first spatial axis for image/frames (must be "
                        "divisible by sp)")
    # mutually exclusive: both share dest zero_opt, and letting argparse's
    # last-flag-wins silently downgrade '--zero3 --zero' to opt-state-only
    # sharding would be a surprise (--zero3 already implies --zero)
    zg = g.add_mutually_exclusive_group()
    zg.add_argument("--zero", dest="zero_opt", action="store_true",
                    help="ZeRO-style optimizer-state sharding over the data "
                         "axis (per-chip Adam mu/nu footprint / dp)")
    zg.add_argument("--zero3", dest="zero_opt", action="store_const",
                    const="params",
                    help="ZeRO-3/FSDP flavor: PARAMS shard over the data axis "
                         "too (all-gather-on-use + reduce-scatter inserted by "
                         "GSPMD); implies --zero (mutually exclusive with it)")
    g.add_argument("--spawn_hosts", type=int, default=None, metavar="N",
                   help="one-command multi-process launch (the reference's "
                        "'--accelerator=ddp --gpus=-1' UX): fork N copies of "
                        "this exact command with the coordinator flags set "
                        "(localhost coordinator, CPU backend per child — a "
                        "dev/simulation helper; real TPU pods auto-detect "
                        "via --multihost with one launch per host)")
    g.add_argument("--spawn_attempts", type=int, default=1, metavar="K",
                   help="restart-the-world supervision for --spawn_hosts: "
                        "on ANY child death the launcher kills the whole "
                        "world, re-resolves a fresh coordinator port, and "
                        "relaunches all N hosts with --resume from the "
                        "newest digest-verified checkpoint, up to K total "
                        "world launches (capped backoff between restarts; a "
                        "crash loop of consecutive fast failures detaches "
                        "early). 1 = today's fail-fast behavior")
    g.add_argument("--elastic", action="store_true",
                   help="elastic supervision for --spawn_hosts (r23): a "
                        "child death no longer restarts the world — the "
                        "supervisor waits for the survivors to resize "
                        "in-process (resilience.elastic) and resume, only "
                        "falling back to restart-the-world when the live "
                        "count drops below --elastic_quorum or the elastic "
                        "progress file stops advancing. Worlds that made "
                        "step progress reset the --spawn_attempts budget")
    g.add_argument("--elastic_quorum", type=int, default=1, metavar="Q",
                   help="minimum live hosts for in-process resize under "
                        "--elastic; below it the supervisor restarts the "
                        "world (r19 behavior)")
    g.add_argument("--multihost", action="store_true",
                   help="call jax.distributed.initialize() before touching "
                        "devices (TPU pods auto-detect the coordinator); "
                        "without it every host trains independently")
    g.add_argument("--coordinator_address", default=None,
                   help="host:port of process 0, for clusters JAX cannot "
                        "auto-detect (implies --multihost)")
    g.add_argument("--num_processes", type=int, default=None)
    g.add_argument("--process_id", type=int, default=None)


def add_compute_args(parser: argparse.ArgumentParser) -> None:
    g = parser.add_argument_group("compute")
    g.add_argument("--dtype", choices=sorted(DTYPES), default="bfloat16")
    g.add_argument("--attn_impl",
                   choices=("auto", "xla", "pallas", "pallas_sp", "packed"),
                   default="auto",
                   help="attention inner-product impl; auto picks the fused "
                        "Pallas kernel for long KV streams, XLA otherwise "
                        "(and routes the encoder cross-attention through the "
                        "sequence-parallel kernel when --sp > 1 and "
                        "--shard_seq are active); pallas_sp forces the kernel "
                        "path with that sp routing; packed = experimental "
                        "small-latent kernel (PERF.md)")
    g.add_argument("--remat", action="store_true",
                   help="rematerialize encoder layers (HBM for FLOPs)")
    g.add_argument("--no_reuse_kv", action="store_true",
                   help="recompute the shared layer_n cross-attention K/V "
                        "projections per recurrent application instead of "
                        "caching them (the cache is exact and measured "
                        "faster — PERF.md r5; this is the off switch for "
                        "A/Bs and minimal-live-memory remat runs)")
    g.add_argument("--pad_vocab_multiple", type=int, default=None,
                   help="round the vocab/class projection width up to this "
                        "multiple (padded logits pinned to -1e30) so it "
                        "divides the model mesh axis and tensor-shards under "
                        "--tp; applies to MLM and classifier heads")
    g.add_argument("--seed", type=int, default=0)


def add_imdb_args(parser: argparse.ArgumentParser) -> None:
    g = parser.add_argument_group("data (IMDB)")
    # accepted for drop-in compatibility with the reference recipes
    # (README.md:33-38); imdb is the only text dataset either repo ships
    g.add_argument("--dataset", choices=("imdb",), default="imdb")
    g.add_argument("--root", default=".cache")
    g.add_argument("--max_seq_len", type=int, default=512)
    g.add_argument("--vocab_size", type=int, default=10003)
    g.add_argument("--batch_size", type=int, default=64)
    g.add_argument("--synthetic", action="store_true",
                   help="deterministic generated corpus (no downloads)")
    g.add_argument("--synthetic_size", type=int, default=2048)
    g.add_argument("--no_download", action="store_true",
                   help="fail fast if data is absent instead of fetching it")
    g.add_argument("--bucket_widths", type=int, nargs="+", default=None,
                   help="pad each batch to the smallest of these sequence "
                        "widths that fits it (SPMD-safe bucketed padding — "
                        "the reference's pad-to-longest without dynamic "
                        "shapes; one cached compile per width). Combine with "
                        "--length_sort_window. Composes with "
                        "--steps_per_dispatch (same-width batches are "
                        "grouped into K-runs so stacked windows never mix "
                        "widths) and with multi-host runs (the loader "
                        "decides each global batch's width from shared "
                        "token lengths, so hosts always agree); under "
                        "--shard_seq every width must divide --sp")
    g.add_argument("--length_sort_window", type=int, default=8,
                   help="with --bucket_widths: sort examples by length within "
                        "windows of this many batches so batches are "
                        "length-homogeneous (batch order re-shuffled inside "
                        "the window; 0 = off)")


def validate_bucket_args(args) -> None:
    """Cross-flag constraints for bucketed-width batches."""
    widths = getattr(args, "bucket_widths", None)
    if not widths:
        return
    # Multi-host and steps_per_dispatch now COMPOSE with buckets (r4,
    # VERDICT r3 item 2): the loader decides each global batch's width from
    # the shared token-length table (host-consistent by construction) and
    # arranges same-width batches in K-runs so stacked dispatch windows
    # never mix widths (data/pipeline.py group_widths/group_size).
    if getattr(args, "shard_seq", False):
        sp = getattr(args, "sp", 1)
        bad = [w for w in widths if w % sp]
        if bad:
            raise SystemExit(
                f"--bucket_widths {bad} not divisible by --sp {sp} "
                f"(seq-sharded batches need width % sp == 0)"
            )


def add_mnist_args(parser: argparse.ArgumentParser) -> None:
    g = parser.add_argument_group("data (MNIST)")
    # accepted for drop-in compatibility with the reference recipes
    # (README.md:77-79)
    g.add_argument("--dataset", choices=("mnist",), default="mnist")
    g.add_argument("--root", default=".cache")
    g.add_argument("--batch_size", type=int, default=128)
    g.add_argument("--random_crop", type=int, default=None)
    g.add_argument("--synthetic", action="store_true")
    g.add_argument("--synthetic_size", type=int, default=4096)
    g.add_argument("--no_download", action="store_true",
                   help="fail fast if data is absent instead of fetching it")


# -- builders ----------------------------------------------------------------


def trainer_config(args) -> TrainerConfig:
    return TrainerConfig(
        max_epochs=args.max_epochs,
        max_steps=args.max_steps,
        log_every_n_steps=args.log_every_n_steps,
        eval_every_n_steps=args.eval_every_n_steps,
        logdir=args.logdir,
        experiment=args.experiment,
        max_to_keep=args.max_to_keep,
        use_tensorboard=not args.no_tensorboard,
        profile_steps=args.profile_steps,
        steps_per_dispatch=getattr(args, "steps_per_dispatch", 1),
        debug_nans=getattr(args, "debug_nans", False),
        selfprofile_every_n_steps=getattr(
            args, "selfprofile_every_n_steps", 0),
        selfprofile_steps=getattr(args, "selfprofile_steps", 4),
        skip_nonfinite_steps=getattr(args, "skip_nonfinite_steps", False),
        rollback_after_bad_steps=getattr(args, "rollback_after_bad_steps", 3),
        dispatch_error_retries=getattr(args, "dispatch_error_retries", 0),
        fit_attempts=getattr(args, "fit_attempts", 1),
        step_timeout_s=getattr(args, "step_timeout_s", None),
        peer_heartbeat_s=getattr(args, "peer_heartbeat_s", 0.0),
        compile_cache=getattr(args, "compile_cache", None),
        publish_dir=getattr(args, "publish_dir", None),
        publish_every_n_steps=getattr(args, "publish_every_n_steps", 0),
    )


def run_fit(trainer, train_loader, val_loader=None):
    """Drive ``trainer.fit`` — through the ``fit_with_recovery`` supervisor
    whenever the config asks for more than one attempt (``--fit_attempts``),
    so every train CLI gets the auto-resume story from one switch."""
    if trainer.config.fit_attempts > 1:
        return trainer.fit_with_recovery(train_loader, val_loader)
    return trainer.fit(train_loader, val_loader)


def optimizer_from_args(args):
    return make_optimizer(
        OptimizerConfig(
            optimizer=args.optimizer,
            learning_rate=args.learning_rate,
            weight_decay=args.weight_decay,
            one_cycle_lr=args.one_cycle_lr,
            one_cycle_pct_start=args.one_cycle_pct_start,
            max_steps=args.max_steps,
            momentum=getattr(args, "momentum", 0.0),
            grad_clip_norm=getattr(args, "grad_clip_norm", None),
            accumulate_steps=getattr(args, "accumulate_steps", 1),
        )
    )


def mesh_from_args(args):
    mesh = make_mesh(dp=args.dp, tp=args.tp, sp=args.sp,
                     dcn_dp=getattr(args, "dcn_dp", 1))
    dp = mesh.shape["data"]
    if args.batch_size % dp != 0:
        raise SystemExit(
            f"batch_size {args.batch_size} must be divisible by the data-"
            f"parallel mesh axis ({dp}); pass --batch_size or --dp/--tp/--sp"
        )
    return mesh


def build_text_encoder(args, vocab_size: int, max_seq_len: int) -> pit.PerceiverEncoder:
    """TextInputAdapter + encoder (reference ``lightning.py:108-116``; the
    embedding width equals the latent channel count, as in the reference's
    north-star config)."""
    dtype = DTYPES[args.dtype]
    return pit.PerceiverEncoder(
        input_adapter=pit.TextInputAdapter(
            vocab_size=vocab_size,
            max_seq_len=max_seq_len,
            num_channels=args.num_latent_channels,
            dtype=dtype,
        ),
        latent_shape=(args.num_latents, args.num_latent_channels),
        num_layers=args.num_encoder_layers,
        num_cross_attention_heads=args.num_cross_attention_heads,
        num_self_attention_heads=args.num_self_attention_heads,
        num_self_attention_layers_per_block=args.num_self_attention_layers_per_block,
        dropout=args.dropout,
        dtype=dtype,
        attn_impl=args.attn_impl,
        remat=args.remat,
        reuse_kv=not getattr(args, "no_reuse_kv", False),
    )


def build_mlm(args, vocab_size: int, max_seq_len: int) -> pit.PerceiverMLM:
    """MLM model (reference ``lightning.py:108-120``)."""
    dtype = DTYPES[args.dtype]
    return pit.PerceiverMLM(
        encoder=build_text_encoder(args, vocab_size, max_seq_len),
        decoder=pit.PerceiverDecoder(
            output_adapter=pit.TextOutputAdapter(
                vocab_size=vocab_size,
                max_seq_len=max_seq_len,
                num_output_channels=args.num_latent_channels,
                dtype=dtype,
                pad_classes_to=getattr(args, "pad_vocab_multiple", None),
            ),
            latent_shape=(args.num_latents, args.num_latent_channels),
            num_cross_attention_heads=args.num_cross_attention_heads,
            dropout=args.dropout,
            dtype=dtype,
            attn_impl=args.attn_impl,
        ),
        masking=TextMasking(
            vocab_size=vocab_size, unk_token_id=1, mask_token_id=2,
            num_special_tokens=3,
        ),
    )


def build_ar(args, vocab_size: int, max_seq_len: int):
    """Perceiver-AR causal LM (the generative task preset surface —
    mirrors :func:`build_mlm`'s width knobs over ``models.presets``)."""
    dtype = DTYPES[args.dtype]
    return pit.PerceiverARLM(
        input_adapter=pit.TextInputAdapter(
            vocab_size=vocab_size,
            max_seq_len=max_seq_len,
            num_channels=args.num_latent_channels,
            dtype=dtype,
        ),
        output_adapter=pit.TextOutputAdapter(
            vocab_size=vocab_size,
            max_seq_len=max_seq_len,
            num_output_channels=args.num_latent_channels,
            dtype=dtype,
            pad_classes_to=getattr(args, "pad_vocab_multiple", None),
        ),
        num_latents=args.num_latents,
        num_layers=args.num_encoder_layers,
        num_self_attention_layers_per_block=args.num_self_attention_layers_per_block,
        num_cross_attention_heads=args.num_cross_attention_heads,
        num_self_attention_heads=args.num_self_attention_heads,
        dropout=args.dropout,
        dtype=dtype,
        attn_impl=args.attn_impl,
    )


def build_text_classifier(args, vocab_size: int, max_seq_len: int,
                          num_classes: int = 2) -> pit.PerceiverIO:
    """Sequence classifier (reference ``lightning.py:186-200``)."""
    dtype = DTYPES[args.dtype]
    return pit.PerceiverIO(
        encoder=build_text_encoder(args, vocab_size, max_seq_len),
        decoder=pit.PerceiverDecoder(
            output_adapter=pit.ClassificationOutputAdapter(
                num_classes=num_classes,
                num_output_channels=args.num_latent_channels,
                dtype=dtype,
                pad_classes_to=getattr(args, "pad_vocab_multiple", None),
            ),
            latent_shape=(args.num_latents, args.num_latent_channels),
            num_cross_attention_heads=args.num_cross_attention_heads,
            dropout=args.dropout,
            dtype=dtype,
            attn_impl=args.attn_impl,
        ),
    )


def build_image_classifier(
    args, image_shape: Tuple[int, ...], num_classes: int,
    num_frequency_bands: int = 32,
) -> pit.PerceiverIO:
    """Image classifier (reference ``lightning.py:222-244``)."""
    dtype = DTYPES[args.dtype]
    return pit.PerceiverIO(
        encoder=pit.PerceiverEncoder(
            input_adapter=pit.ImageInputAdapter(
                image_shape=tuple(image_shape),
                num_frequency_bands=num_frequency_bands,
                dtype=dtype,
            ),
            latent_shape=(args.num_latents, args.num_latent_channels),
            num_layers=args.num_encoder_layers,
            num_cross_attention_heads=args.num_cross_attention_heads,
            num_self_attention_heads=args.num_self_attention_heads,
            num_self_attention_layers_per_block=args.num_self_attention_layers_per_block,
            dropout=args.dropout,
            dtype=dtype,
            attn_impl=args.attn_impl,
            remat=args.remat,
            reuse_kv=not getattr(args, "no_reuse_kv", False),
        ),
        decoder=pit.PerceiverDecoder(
            output_adapter=pit.ClassificationOutputAdapter(
                num_classes=num_classes,
                num_output_channels=args.num_latent_channels,
                dtype=dtype,
                pad_classes_to=getattr(args, "pad_vocab_multiple", None),
            ),
            latent_shape=(args.num_latents, args.num_latent_channels),
            num_cross_attention_heads=args.num_cross_attention_heads,
            dropout=args.dropout,
            dtype=dtype,
            attn_impl=args.attn_impl,
        ),
    )


MODEL_HPARAM_KEYS = (
    "num_latents", "num_latent_channels", "num_encoder_layers",
    "num_self_attention_layers_per_block", "num_cross_attention_heads",
    "num_self_attention_heads", "vocab_size", "max_seq_len",
)


def override_model_args(args, hparams: dict) -> None:
    """Overwrite shape-determining model args from a checkpoint's embedded
    hparams so a restored encoder fits (reference ``load_from_checkpoint``
    rebuilds the model from saved hyperparameters, ``lightning.py:46``)."""
    for key in MODEL_HPARAM_KEYS:
        if key in hparams:
            setattr(args, key, hparams[key])


def maybe_spawn_hosts(args, argv=None) -> bool:
    """Reference-style one-command multi-process launch (``--spawn_hosts N``).

    Lightning's ``--accelerator=ddp --gpus=-1`` spawns per-device processes
    from a single invocation (reference ``train_mlm.py:102-103``); the JAX
    equivalent normally needs one launch per process with coordinator flags
    (CLAUDE.md multi-host recipe). This dev helper closes the UX gap: it
    re-executes this command N times with
    ``--coordinator_address localhost:PORT --num_processes N --process_id R``
    appended and ``JAX_PLATFORMS=cpu`` in each child's env (a simulation
    harness — real TPU pods auto-detect the coordinator via ``--multihost``,
    one launch per host). Returns True when this process acted as the
    launcher (training ran in the children; the caller should return), False
    when training should proceed in-process. Child failure raises
    ``SystemExit`` with the first non-zero return code.

    The child command: for CLI invocations (``argv is None``) the children
    re-run ``sys.executable sys.argv[0]``. For PROGRAMMATIC calls —
    ``main(explicit_argv)`` from a library/REPL/pytest, where ``sys.argv[0]``
    is whatever binary happens to be running and must NOT be re-executed with
    training flags — the children run ``python -m <calling cli module>``
    instead (the module is read from the caller's frame).

    The coordinator port is picked bind-then-close, which leaves a TOCTOU
    window where another process can grab it before rank 0's
    ``jax.distributed`` service binds. A stolen port makes the children fail
    during init, well before training starts — so a launch whose first
    failure lands within ``_SPAWN_RETRY_WINDOW_S`` is retried (fresh port,
    same command) up to two more times before the failure is reported.

    Supervision (``--spawn_attempts K``, r19): the launch runs under a
    :class:`WorldSupervisor` — any child death kills the surviving world,
    the supervisor re-resolves a fresh coordinator port, and relaunches all
    N hosts with ``--resume`` pointing at the newest resumable run (the one
    whose restore will be digest-verified by ``restore_train_state``), with
    capped backoff between restarts and a crash-loop detach after
    consecutive fast failures. ``K=1`` (the default) keeps the historical
    fail-fast behavior.
    """
    import sys

    n = getattr(args, "spawn_hosts", None)
    if not n or n <= 1 or getattr(args, "process_id", None) is not None:
        return False
    base = list(sys.argv[1:] if argv is None else argv)
    child_argv, skip = [], False
    for a in base:
        if skip:
            skip = False
            continue
        if a in ("--spawn_hosts", "--spawn_attempts"):
            skip = True  # drop the launcher-only flag and its value
        elif a.startswith(("--spawn_hosts=", "--spawn_attempts=")):
            pass
        else:
            child_argv.append(a)
    if argv is None:
        target = [sys.executable, sys.argv[0]]
    else:
        caller_mod = sys._getframe(1).f_globals.get("__name__")
        if caller_mod and caller_mod != "__main__":
            target = [sys.executable, "-m", caller_mod]
        else:
            # a script's own main(argv) — its file path is still the command
            target = [sys.executable, sys.argv[0]]
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    if len(target) == 3:
        # `-m` children must resolve the package even when the parent
        # imported it from a path not on the default sys.path
        import perceiver_io_tpu

        pkg_root = os.path.dirname(os.path.dirname(
            os.path.abspath(perceiver_io_tpu.__file__)))
        env["PYTHONPATH"] = pkg_root + os.pathsep + env.get("PYTHONPATH", "")

    progress_probe = None
    if getattr(args, "elastic", False):
        from perceiver_io_tpu.resilience.elastic import (
            progress_path, read_progress)

        proot = getattr(args, "logdir", None) or "."
        progress_probe = lambda: read_progress(progress_path(proot))  # noqa: E731
    supervisor = WorldSupervisor(
        launch=lambda resume_dir: _launch_world(
            target, child_argv, env, n, resume_dir),
        n=n,
        attempts=getattr(args, "spawn_attempts", 1) or 1,
        find_resume=lambda: _newest_resumable_run(
            getattr(args, "logdir", None), getattr(args, "experiment", None)),
        elastic=getattr(args, "elastic", False),
        quorum=getattr(args, "elastic_quorum", 1) or 1,
        progress_probe=progress_probe,
    )
    supervisor.run()
    return True


def _pick_coordinator_port() -> int:
    import socket

    with socket.socket() as s:
        s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        s.bind(("localhost", 0))
        return s.getsockname()[1]


def _launch_world(target, child_argv, env, n, resume_dir=None):
    """Start all N ranks of one world on a fresh coordinator port. Returns
    ``(procs, logs)`` — rank 0 inherits stdout/stderr (it owns
    logging/checkpoints); the others write to temp files — NEVER undrained
    pipes, which fill the OS buffer once a child emits ~64KB and deadlock
    the whole cluster — replayed only on failure.

    ``resume_dir`` (world restarts) appends ``--resume`` AFTER the user's
    argv, so argparse's last-wins gives the supervisor's choice precedence
    over any ``--resume`` the original command carried.
    """
    import subprocess
    import sys
    import tempfile

    port = _pick_coordinator_port()
    extra = ["--resume", str(resume_dir)] if resume_dir else []
    procs, logs = [], []
    for rank in range(n):
        cmd = [*target, *child_argv, *extra,
               "--coordinator_address", f"localhost:{port}",
               "--num_processes", str(n), "--process_id", str(rank)]
        if rank == 0:
            out, log = None, None
        else:
            log = tempfile.NamedTemporaryFile(
                mode="w+", prefix=f"spawn_hosts_rank{rank}_", suffix=".log",
                delete=False,
            )
            out = log
        logs.append(log)
        procs.append(subprocess.Popen(
            cmd, env=env, stdout=out,
            stderr=subprocess.STDOUT if rank else None, text=True,
        ))
    print(f"--spawn_hosts: launched {n} processes "
          f"(coordinator localhost:{port})"
          + (f", resuming {resume_dir}" if resume_dir else ""),
          file=sys.stderr)
    return procs, logs


def _newest_resumable_run(logdir, experiment):
    """The newest ``version_N`` run dir under ``logdir/experiment`` holding
    both embedded hparams and at least one committed checkpoint step (main
    slot or the preemption ``last/`` slot) — i.e. a dir ``--resume`` will
    accept and ``restore_train_state`` will digest-verify. None when the
    world died before its first checkpoint (restart fresh instead)."""
    import re

    if not logdir or not experiment:
        return None
    base = os.path.join(logdir, experiment)
    try:
        names = os.listdir(base)
    except OSError:
        return None
    versions = []
    for name in names:
        m = re.fullmatch(r"version_(\d+)", name)
        if m:
            versions.append((int(m.group(1)), name))
    for _, name in sorted(versions, reverse=True):
        run = os.path.join(base, name)
        ckpt = os.path.join(run, "checkpoints")
        if not os.path.isfile(os.path.join(ckpt, "hparams.json")):
            continue
        for slot in (ckpt, os.path.join(ckpt, "last")):
            try:
                entries = os.listdir(slot)
            except OSError:
                continue
            for entry in entries:
                if entry.isdigit() and os.path.exists(
                    os.path.join(slot, entry, "_CHECKPOINT_METADATA")
                ):
                    return run
    return None


class WorldSupervisor:
    """Elastic restart-the-world supervision over one ``--spawn_hosts`` job.

    One ``run()`` call owns the whole job lifetime: launch a world, watch
    every child, and on ANY child death kill the survivors and relaunch all
    N ranks from the newest resumable checkpoint — the process-level twin of
    the serving tier's ``ReplicaSupervisor`` (r12), except that multi-host
    training cannot restart one rank (its peers' collectives reference the
    dead one's program), so the restart unit is the WORLD.

    Injectable collaborators keep the policy tier-1-testable with fake
    children: ``launch(resume_dir) -> (procs, logs)`` where each proc
    exposes ``poll/terminate/kill/wait``; ``find_resume() -> run_dir|None``;
    ``sleep`` for the backoff. Three failure disciplines compose:

    - **port-race retry** (pre-existing): a fast failure with connect/bind
      evidence in a child log relaunches on a fresh port WITHOUT consuming
      a supervision attempt (bounded by ``_SPAWN_PORT_RETRIES`` per world);
    - **world restart**: up to ``attempts`` total world launches, capped
      exponential backoff between them, ``spawn_world_restarts_total``
      counting actuations;
    - **crash-loop detach**: ``_CRASHLOOP_LIMIT`` consecutive worlds dying
      within ``_CRASHLOOP_WINDOW_S`` of launch abandon the job early with
      the last exit code — a deterministic failure must not burn the whole
      attempt budget at backoff cadence.

    The chaos hook ``spawn.child_exit`` fires once per watch poll; an
    injected raise is treated as an observed child death (simulated-failure
    drills restart real worlds without killing real processes).
    """

    def __init__(self, launch, n, attempts=1, find_resume=None,
                 poll_s=0.2, backoff=None, sleep=None, reap_wait_s=10.0,
                 elastic=False, quorum=1, progress_probe=None,
                 elastic_grace_s=30.0):
        import time as _time

        import perceiver_io_tpu.obs as obs
        from perceiver_io_tpu.resilience import RetryPolicy

        self._launch = launch
        self.n = int(n)
        self.attempts = max(1, int(attempts))
        self._find_resume = find_resume or (lambda: None)
        self._poll_s = poll_s
        self._backoff = backoff or RetryPolicy(
            max_retries=self.attempts, base_s=1.0, multiplier=2.0, max_s=30.0)
        self._sleep = sleep or _time.sleep
        self._reap_wait_s = reap_wait_s
        # r23 elastic supervision: a child death is first offered to the
        # in-process resize path (resilience.elastic) — the supervisor only
        # restarts the world below the quorum floor or when the elastic
        # progress file stops advancing within the grace window.
        self.elastic = bool(elastic)
        self.quorum = max(1, int(quorum))
        self._progress_probe = progress_probe or (lambda: None)
        self._elastic_grace_s = elastic_grace_s
        self._m_restarts = obs.get_registry().counter(
            "spawn_world_restarts_total",
            "whole-world relaunches after a child death under "
            "--spawn_attempts supervision")
        self._m_absorbed = obs.get_registry().counter(
            "spawn_elastic_absorbed_total",
            "child deaths absorbed by an in-process elastic resize "
            "instead of a world restart (--elastic)")
        self.procs = []  # the CURRENT world, for the signal handlers

    # -- plumbing ------------------------------------------------------------

    def _reap(self) -> None:
        import subprocess

        live = [p for p in self.procs if p.poll() is None]
        for p in live:
            p.terminate()
        for p in live:
            try:
                p.wait(timeout=self._reap_wait_s)
            except subprocess.TimeoutExpired:
                p.kill()
                # wait out the SIGKILL too: the NEXT world must never
                # overlap a dying one (zombie reaping, port/file handles,
                # and CPU contention during its successor's compile)
                try:
                    p.wait(timeout=self._reap_wait_s)
                except subprocess.TimeoutExpired:
                    pass

    def _watch(self):
        """Poll until the world succeeds (-> None) or any child dies
        (-> (rank|None, rc)); rank None marks an injected simulated death."""
        import time as _time

        from perceiver_io_tpu.resilience import faults

        live = list(range(self.n))
        while live:
            try:
                # chaos hook: a raise simulates an observed child death
                faults.inject("spawn.child_exit")
            except Exception as e:
                import sys

                print(f"--spawn_hosts: injected child death "
                      f"({type(e).__name__})", file=sys.stderr)
                return None, 1
            for r in list(live):
                rc = self.procs[r].poll()
                if rc is not None:
                    live.remove(r)
                    if rc != 0:
                        if not self.elastic:
                            return r, rc
                        if len(live) < self.quorum:
                            import sys

                            print(f"--spawn_hosts: rank {r} died (rc={rc}) "
                                  f"and {len(live)} live < quorum "
                                  f"{self.quorum} — restarting the world",
                                  file=sys.stderr)
                            return r, rc
                        if not self._await_elastic_resume(r, rc):
                            return r, rc
            if live:
                _time.sleep(self._poll_s)
        return None

    # -- elastic absorption (r23) --------------------------------------------

    @staticmethod
    def _progress_key(progress):
        """Orderable identity of an elastic progress record (None = none)."""
        if not progress:
            return None
        return (progress.get("generation", -1), progress.get("step", -1),
                progress.get("wall", 0.0))

    def _await_elastic_resume(self, rank, rc) -> bool:
        """Give the survivors the grace window to resize in-process and
        advance the elastic progress file past its pre-death value. True =
        the death was absorbed (keep watching); False = restart the world."""
        import sys
        import time as _time

        import perceiver_io_tpu.obs as obs

        before = self._progress_key(self._progress_probe())
        print(f"--spawn_hosts --elastic: rank {rank} died (rc={rc}); "
              f"waiting up to {self._elastic_grace_s:.0f}s for the "
              "survivors to resize in-process", file=sys.stderr)
        deadline = _time.monotonic() + self._elastic_grace_s
        while _time.monotonic() < deadline:
            now = self._progress_key(self._progress_probe())
            if now is not None and now != before and (
                    before is None or now > before):
                self._m_absorbed.inc()
                obs.event("spawn_elastic_absorbed", rank=rank, rc=rc,
                          generation=now[0], step=now[1])
                print(f"--spawn_hosts --elastic: survivors resumed at "
                      f"generation {now[0]} step {now[1]} — death absorbed, "
                      "no world restart", file=sys.stderr)
                return True
            self._sleep(self._poll_s)
        print("--spawn_hosts --elastic: no elastic progress within the "
              "grace window — falling back to restart-the-world",
              file=sys.stderr)
        return False

    def _replay_log(self, logs, rank, label="") -> bool:
        """Dump a failed rank's captured output tail to stderr; returns
        whether there was a log to replay (rank 0 streams directly)."""
        import sys

        if rank is None or rank >= len(logs) or logs[rank] is None:
            return False
        logs[rank].flush()
        logs[rank].seek(0)
        print(f"--- rank {rank} output{label} ---\n"
              f"{logs[rank].read()[-4000:]}", file=sys.stderr)
        return True

    def _close_logs(self, logs, keep=None) -> None:
        """Close every log handle; delete all but ``keep``'s (kept for
        post-mortem) so repeated dev runs don't litter /tmp."""
        for rank, log in enumerate(logs):
            if log is None:
                continue
            log.close()
            if rank != keep:
                try:
                    os.unlink(log.name)
                except OSError:
                    pass

    # -- the supervision loop ------------------------------------------------

    def run(self) -> None:
        """Supervise to completion; raises SystemExit on final failure."""
        import signal

        # the launcher must never outlive-orphan its children:
        # SIGTERM/SIGINT (Ctrl-C, `timeout`, a scheduler preemption) reaps
        # the current world before exiting
        prev_handlers = {}

        def _on_signal(signum, frame):
            self._reap()
            raise SystemExit(128 + signum)

        for sig in (signal.SIGTERM, signal.SIGINT):
            try:
                prev_handlers[sig] = signal.signal(sig, _on_signal)
            except ValueError:
                pass  # non-main thread (programmatic use) — skip handlers
        try:
            self._run_supervised()
        finally:
            for sig, h in prev_handlers.items():
                signal.signal(sig, h)

    def _run_supervised(self) -> None:
        import sys
        import time as _time

        launches = 0          # FAILED worlds counted against the budget
        port_retries = 0      # per-world coordinator-port retries
        fast_failures = 0     # consecutive crash-loop candidates
        resume_dir = None
        while True:
            self.procs, logs = self._launch(resume_dir)
            started = _time.monotonic()
            progress_at_launch = self._progress_key(self._progress_probe())
            failed = self._watch()
            if failed is None:
                self._close_logs(logs)
                return
            rank, rc = failed
            self._reap()
            elapsed = _time.monotonic() - started
            # A world that demonstrably made step progress (elastic rejoins
            # reaching a clean boundary, or plain long productive training)
            # earns back the FULL attempt budget: this failure is
            # independent of the ones that consumed earlier attempts.
            progress_now = self._progress_key(self._progress_probe())
            if (progress_now is not None
                    and progress_now != progress_at_launch
                    and (progress_at_launch is None
                         or progress_now > progress_at_launch)
                    and (launches or fast_failures)):
                print(f"--spawn_hosts: world made step progress "
                      f"(generation {progress_now[0]} step {progress_now[1]})"
                      " — resetting the supervision attempt budget",
                      file=sys.stderr)
                launches = 0
                fast_failures = 0
            # Port-race retry ONLY with evidence of a coordinator bring-up
            # problem in some child's log — a deterministic fast failure
            # (bad flag, import error) must surface immediately, not be
            # retried with a misleading race diagnostic. Doesn't consume a
            # supervision attempt (hence counted before `launches` moves).
            if (elapsed < _SPAWN_RETRY_WINDOW_S
                    and port_retries < _SPAWN_PORT_RETRIES
                    and _logs_show_coordination_failure(logs)):
                port_retries += 1
                print(
                    f"--spawn_hosts: rank {rank} failed (rc={rc}) within "
                    f"{_SPAWN_RETRY_WINDOW_S:.0f}s with connect/bind "
                    "errors in the child logs — likely a coordinator-port "
                    "race; retrying with a fresh port",
                    file=sys.stderr,
                )
                # show the evidence on EVERY retry (ADVICE r5): if this is
                # actually a deterministic failure that happens to match a
                # connect/bind marker, the user sees the real error now
                self._replay_log(logs, rank, f" (retry {port_retries})")
                self._close_logs(logs)
                continue
            port_retries = 0
            launches += 1
            out_of_attempts = launches >= self.attempts
            crash_loop = False
            if elapsed < _CRASHLOOP_WINDOW_S:
                fast_failures += 1
                crash_loop = fast_failures >= _CRASHLOOP_LIMIT
            else:
                fast_failures = 0
            if out_of_attempts or crash_loop:
                replayed = self._replay_log(logs, rank)
                if replayed:
                    print(f"(full rank-{rank} log kept at "
                          f"{logs[rank].name})", file=sys.stderr)
                self._close_logs(logs, keep=rank)
                if crash_loop and not out_of_attempts:
                    print(
                        f"--spawn_hosts: {fast_failures} consecutive worlds "
                        f"died within {_CRASHLOOP_WINDOW_S:.0f}s of launch — "
                        f"crash loop, detaching with "
                        f"{self.attempts - launches} attempt(s) unused",
                        file=sys.stderr,
                    )
                raise SystemExit(rc)
            self._replay_log(logs, rank)
            self._close_logs(logs)
            self._m_restarts.inc()
            resume_dir = self._find_resume()
            pause = self._backoff.backoff_s(launches)
            print(
                f"--spawn_hosts: world attempt {launches}/{self.attempts} "
                f"failed ({'injected' if rank is None else f'rank {rank}'} "
                f"rc={rc}); restarting all {self.n} hosts in {pause:.1f}s"
                + (f" with --resume {resume_dir}" if resume_dir
                   else " fresh (no checkpoint yet)"),
                file=sys.stderr,
            )
            import perceiver_io_tpu.obs as obs

            obs.event("spawn_world_restart", attempt=launches, rc=rc,
                      rank=rank, resume_dir=resume_dir,
                      backoff_s=round(pause, 3))
            if pause > 0:
                self._sleep(pause)


# Children that die this quickly never started training — a candidate for
# the coordinator bring-up retry (e.g. the picked port got stolen), taken
# only when the child logs actually show coordination/bind errors.
_SPAWN_RETRY_WINDOW_S = 20.0
_SPAWN_PORT_RETRIES = 2

# Crash-loop detach (--spawn_attempts supervision): this many CONSECUTIVE
# worlds dying within the window of their launch abandon the job early — a
# deterministic failure (shape bug, poisoned checkpoint) must not burn the
# whole attempt budget at backoff cadence while looking like recovery.
_CRASHLOOP_WINDOW_S = 15.0
_CRASHLOOP_LIMIT = 3

# Signatures of a failed jax.distributed bring-up in a child's output —
# CONNECT/BIND-specific only (ADVICE r5): broad markers like
# 'jax.distributed.initialize' or bare 'unavailable:' also appear in
# deterministic init-failure tracebacks (bad --num_processes arithmetic,
# plugin errors), which must surface immediately rather than be retried
# twice under a misleading port-race diagnostic.
_COORDINATION_ERROR_MARKERS = (
    "address already in use",
    "failed to connect",
    "connection refused",
    "bind address",
)


def _logs_show_coordination_failure(logs) -> bool:
    """True when any child's captured output tail matches a distributed-
    bring-up failure signature (case-insensitive)."""
    for log in logs:
        if log is None:
            continue
        try:
            log.flush()
            log.seek(0, os.SEEK_END)
            size = log.tell()
            log.seek(max(0, size - 8000))
            tail = log.read().lower()
        except (OSError, ValueError):
            continue
        if any(m in tail for m in _COORDINATION_ERROR_MARKERS):
            return True
    return False


def maybe_initialize_distributed(args) -> None:
    """Multi-host bring-up, gated on ``--multihost``. MUST run before any
    device access (first use initializes the local-only backend)."""
    wants_distributed = (
        getattr(args, "multihost", False)
        or getattr(args, "coordinator_address", None) is not None
        or getattr(args, "num_processes", None) is not None
        or getattr(args, "process_id", None) is not None
    )
    if wants_distributed:
        from perceiver_io_tpu.parallel import initialize_distributed
        from perceiver_io_tpu.utils.platform import (
            drop_unselected_plugin_backends,
        )

        # a registered-but-unselected PJRT plugin can initialize backends
        # mid-initialize, detaching the distributed client (process_count
        # silently stays 1 and every rank trains alone)
        drop_unselected_plugin_backends()
        try:
            initialize_distributed(
                coordinator_address=getattr(args, "coordinator_address", None),
                num_processes=getattr(args, "num_processes", None),
                process_id=getattr(args, "process_id", None),
            )
        except (ValueError, RuntimeError) as e:
            raise SystemExit(
                f"--multihost: jax.distributed.initialize failed ({e}). On a "
                "TPU pod the coordinator is auto-detected; elsewhere pass "
                "--coordinator_address host:port --num_processes N "
                "--process_id I on every process, or drop the flag for "
                "single-host runs."
            ) from e
        import sys

        import jax

        print(
            f"[distributed] process {jax.process_index()}/"
            f"{jax.process_count()}, {jax.local_device_count()} local "
            f"device(s)", file=sys.stderr,
        )


def parse_with_resume(parser: argparse.ArgumentParser, argv):
    """Parse, and when ``--resume RUN_DIR`` is set, re-parse with the resumed
    run's embedded hparams installed as the parser's defaults.

    Every arg of the original run — model shapes, data shapes, optimizer
    structure (``accumulate_steps`` changes the opt_state pytree!) — comes
    back automatically, while flags given explicitly on THIS command line
    still win (so ``--resume RUN --max_steps 100000`` extends the schedule).
    ``--resume`` itself is never taken from hparams."""
    args = parser.parse_args(argv)
    if not getattr(args, "resume", None):
        return args
    from perceiver_io_tpu.training.checkpoint import load_hparams

    try:
        hparams = load_hparams(os.path.join(args.resume, "checkpoints"))
    except (FileNotFoundError, NotADirectoryError):
        raise SystemExit(_nothing_to_resume(args.resume)) from None
    known = vars(args)
    # environment/bring-up flags describe where THIS invocation runs, not the
    # training recipe — never inherit them from the original run (store_true
    # flags have no --no_* spelling to override with)
    env_flags = {"resume", "multihost", "coordinator_address", "num_processes",
                 "process_id", "dp", "tp", "sp", "shard_seq", "zero_opt",
                 # launcher topology/supervision describe THIS invocation
                 "spawn_hosts", "spawn_attempts", "elastic", "elastic_quorum",
                 # local paths: never inherit across hosts/invocations
                 "compile_cache", "publish_dir", "publish_every_n_steps"}
    defaults = {
        k: v for k, v in hparams.items() if k in known and k not in env_flags
    }
    parser.set_defaults(**defaults)
    args = parser.parse_args(argv)
    args.resume = os.path.abspath(known["resume"])
    return args


def _nothing_to_resume(path: str) -> str:
    return (
        f"--resume {path}: no usable checkpoint under {path}/checkpoints — "
        f"the run was probably interrupted before its first checkpoint "
        f"(nothing to resume from; start fresh without --resume), or the "
        f"path is not a run directory (expected the version_N dir "
        f"containing checkpoints/)."
    )


def resume_state(args, state):
    """After building the fresh TrainState: restore the newest checkpoint of
    the ``--resume`` run (preferring the preemption ``last/`` slot). Returns
    ``(state, run_dir)`` — ``run_dir`` is the resumed directory (so logging
    and checkpoints continue in place) or None for a fresh run."""
    if not getattr(args, "resume", None):
        return state, None
    from perceiver_io_tpu.training.checkpoint import restore_train_state

    try:
        state = restore_train_state(
            os.path.join(args.resume, "checkpoints"), state, prefer_latest=True
        )
    except (FileNotFoundError, NotADirectoryError):
        # hparams.json is written at Trainer CONSTRUCTION, so a run killed
        # between construction and its first checkpoint save passes the
        # parse_with_resume guard but has no checkpoint steps to restore
        raise SystemExit(_nothing_to_resume(args.resume)) from None
    return state, args.resume
