"""Fill-mask serving entry point over the micro-batching engine.

The serving-side sibling of the ``train_*`` CLIs: load an MLM checkpoint
(hparams-embedded, ``MLMPredictor.from_checkpoint`` semantics) plus its
tokenizer, warm every (width, batch, query) bucket program ahead of time, and
serve fill-mask requests through ``inference/engine.py``'s continuous
micro-batcher — one JSON line per text on stdout.

Usage::

    python -m perceiver_io_tpu.cli.serve \
        --checkpoint logs/mlm/version_0/checkpoints \
        --tokenizer .cache/imdb-tokenizer-10003.json \
        --texts "this movie was [MASK]" "a [MASK] ending"

    # a stream on stdin (one text per line), width-bucketed, bf16 serving
    ... --stdin --bucket_widths 128 256 --dtype bfloat16

``--cached`` serves through the encode-once/decode-many latent-cache path
instead of the fused forward — same results (parity-tested), useful to smoke
the split pipeline a multi-query deployment would run.

``--quantize int8`` is the weight-only int8 serving path: matmul kernels are
quantized once at load (per-channel symmetric int8, f32 scales —
``perceiver_io_tpu.quant``) and dequantized inside the compiled programs, so
each micro-batch streams int8 weight bytes from HBM. The checkpoint stays
f32 on disk; parity error vs the f32 oracle is bounded and measured
(`tools/quant_bench.py`, PERF.md §Quantization).

``--compile_cache DIR`` is the zero-recompile cold start
(``perceiver_io_tpu.aot``, PERF.md §Cold start): every compiled bucket
program is serialized to DIR keyed by a content fingerprint, and a warm
restart deserializes the family instead of recompiling it — warmup then
performs zero XLA compiles. (The serving process runs the AOT tier alone;
jax's persistent compilation cache is the TRAINER/tools tier — running both
on the same compile double-serializes the executable and destabilizes this
jaxlib, a measured negative recorded in PERF.md §Cold start.)
Warmup itself runs in the BACKGROUND by default
(priority-ordered, smallest buckets first): the first request is answered as
soon as its program is ready, not after the whole family is warm
(``--blocking_warmup`` restores the old wait). A missing/unusable cache dir
warns and serves uncached — a cache problem never refuses traffic.

``--slo_p99_ms`` declares a serving SLO (``perceiver_io_tpu.obs.slo``):
every answered/shed request classifies against the latency target, the
windowed error-budget burn rate rides ``/metrics``+``/statz`` as ``slo_*``
gauges, and ``/healthz`` degrades when the burn rate crosses
``--slo_burn_alert``. Per-request phase tracing
(``serving_phase_seconds{phase=...}``) attributes tail latency to
admission/queue/assembly/dispatch/device/complete; sweep offered load and
fit the capacity model with ``tools/load_bench.py`` (PERF.md §SLO).

``--replicas N`` serves through the multi-replica fabric
(``perceiver_io_tpu.serving``, PERF.md §Fabric): a supervisor spawns N
replica processes (each loads the checkpoint and warms its own AOT pool;
crashes restart with backoff and rejoin only once ``engine_ready``), and a
router does least-loaded health-aware dispatch with transparent failover —
``kill -9`` on a replica re-routes its in-flight requests instead of failing
them. ``--cached`` composes: sessions pin to the replica holding their
latents, and a dead pin surfaces as a re-encode. ``--rolling_swap_step``
rolls the fleet to another checkpoint step one replica at a time with
auto-rollback on post-swap SLO burn/breaker regression.

``--autoscale`` (fleet mode) closes the serving control loop
(``perceiver_io_tpu.serving.autoscale``, PERF.md §Autoscale): an
``Autoscaler`` grows/shrinks the supervised fleet between
``--min_replicas`` and ``--max_replicas`` from the windowed SLO-burn and
queue series the router's scrape loop maintains, seeded by the measured
``--autoscale_rps_per_replica`` capacity fit — hold-down + hysteresis so a
bursty minute never flaps the fleet, scale-down only via graceful
drain-then-retire (``lost_accepted`` stays 0), capped exponential backoff
on failed spawns. ``--priority_classes``/``--client_quota_rps`` add
admission control at the router's front door: weighted-fair dispatch
across service classes and per-client token buckets, so one bursting
client degrades its own SLO class while other classes' p99 stays flat.

``--watch_checkpoints DIR`` closes the train→serve loop
(``perceiver_io_tpu.deploy``, PERF.md §Deployment): the process polls DIR
(a trainer's ``publish_dir``) for atomically-published checkpoints, runs
each through the admission gate — manifest digest verification, all-finite
param scan, a golden-batch forward within ``--gate_quality_tol`` of the
incumbent — and hot-swaps only passing trees into live serving (rolling
one replica at a time under ``--replicas``, each replica re-verifying the
digest at load; re-quantized on the fly under ``--quantize int8``). A
failing publication is quarantined in place (sticky, never re-attempted);
a post-swap SLO-burn/breaker regression rolls back to the incumbent tree.

Graceful drain: SIGTERM/SIGINT stop admission, finish every accepted
request, flush the event log, and exit 0 (``--drain_timeout_s`` bounds the
wait) — in both single-process and fleet modes, so a supervisor rotation
never drops the queue. An in-progress gated swap completes (or rolls back)
before exit — never a half-swapped fleet.

``--metrics_port`` starts the localhost observability sidecar
(``/metrics`` Prometheus text, ``/healthz``, ``/statz`` JSON snapshot, now
including process self-metrics RSS/uptime/threads/GC at every scrape);
``--heartbeat_deadline_s`` arms the wedged-tunnel dispatch heartbeat;
``--selfprofile_every`` turns on the in-loop device-trace watchdog. All
telemetry output rides stderr/HTTP — stdout stays one JSON line per text.

``--series`` adds the historical half (``perceiver_io_tpu.obs.timeseries``,
PERF.md §Timeseries): every registry instrument sampled into a bounded
ring-buffer store each ``--series_interval_s``, served live as
``/seriesz`` and optionally persisted as rotating JSONL
(``--series_jsonl``). ``--alert_rules FILE`` evaluates declarative alert
rules (threshold / rate-of-change / absence over a window, with hold-down
and hysteresis) over those series: transitions land in the event log
(exemplar trace-linked), ``alert_state{rule=}`` rides ``/metrics``, and a
firing page-class alert degrades ``/healthz`` through the same aggregation
as stalls, breakers, and SLO burn.

Self-healing (``perceiver_io_tpu.resilience``, PERF.md §Reliability):
``--request_deadline_s`` sheds requests whose deadline expires before
dispatch, ``--queue_limit`` bounds the queue with fast-fail load shedding,
``--dispatch_retries`` re-dispatches transiently-failed micro-batches with
backoff, and ``--breaker_failures``/``--breaker_cooldown_s`` arm the circuit
breaker (consecutive failures or a heartbeat stall open it; submissions
fast-fail until a half-open probe succeeds; state rides /metrics + /healthz).
"""

from __future__ import annotations

import argparse
import json
import signal
import sys
from typing import Optional, Sequence


class _DrainRequested(BaseException):
    """Raised (once) by the SIGTERM/SIGINT handler to unwind the admission
    loop. A BaseException so no library except-Exception swallows it."""


def _install_drain_handlers():
    """Graceful-drain signal handling: the FIRST SIGTERM/SIGINT raises
    :class:`_DrainRequested` in the main thread (stops admission — even out
    of a blocked stdin read, since a raising handler interrupts the retry
    loop PEP 475 would otherwise continue); later signals are ignored so the
    finish-in-flight phase cannot be aborted into dropping the queue.
    Returns ``(state, restore)`` — call ``restore()`` when done (serve.main
    also runs in-process under pytest; a leaked handler would break the
    host's Ctrl-C)."""
    state = {"draining": False}

    def handler(signum, frame):
        if state["draining"]:
            print(f"serve: signal {signum} during drain — still finishing "
                  "in-flight work", file=sys.stderr, flush=True)
            return
        state["draining"] = True
        raise _DrainRequested()

    previous = {}
    for sig in (signal.SIGTERM, signal.SIGINT):
        try:
            previous[sig] = signal.signal(sig, handler)
        except ValueError:  # not the main thread (programmatic use)
            pass

    def restore():
        for sig, h in previous.items():
            signal.signal(sig, h)

    return state, restore


def build_parser() -> argparse.ArgumentParser:
    parser = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    g = parser.add_argument_group("serving")
    g.add_argument("--task", choices=("mlm", "generate"), default="mlm",
                   help="workload class: 'mlm' fills [MASK] positions; "
                        "'generate' streams Perceiver-AR continuations of "
                        "each input line (checkpoint from cli/train_ar.py; "
                        "single-process — fleet generation serves through "
                        "`python -m perceiver_io_tpu.serving.replica "
                        "--task generate` behind a Router)")
    gen = parser.add_argument_group("generation (--task generate)")
    gen.add_argument("--max_new_tokens", type=int, default=32,
                     help="continuation length per prompt")
    gen.add_argument("--temperature", type=float, default=0.0,
                     help="0 = greedy; otherwise categorical at this "
                          "temperature")
    gen.add_argument("--top_k", type=int, default=0,
                     help="truncate sampling to the k most likely tokens "
                          "(0 = full softmax)")
    gen.add_argument("--gen_seed", type=int, default=0,
                     help="sampling seed (position-folded: deterministic "
                          "per absolute position, reproducible across "
                          "re-encodes)")
    gen.add_argument("--generate_chunk", type=int, default=8,
                     help="decode steps per chunked dispatch (= streaming "
                          "granularity)")
    gen.add_argument("--decode_batching", action="store_true",
                     help="continuous batching: pool session caches into a "
                          "slotted arena, ONE batched step dispatch for all "
                          "active streams (identical token streams; pays "
                          "off at concurrency — prompts here run "
                          "sequentially, so this mostly exercises the path)")
    gen.add_argument("--decode_slots", type=int, default=8,
                     help="decode batching: initial arena slots per prefill "
                          "width (power-of-two-bucketed)")
    g.add_argument("--checkpoint", required=True,
                   help="checkpoint directory of a train_mlm run "
                        "(the version_N/checkpoints dir; hparams embedded)")
    g.add_argument("--tokenizer", required=True,
                   help="tokenizer json (the train run caches one under "
                        "--root, e.g. imdb-tokenizer-10003.json)")
    g.add_argument("--texts", nargs="*", default=None,
                   help="texts containing the [MASK] literal")
    g.add_argument("--stdin", action="store_true",
                   help="read one text per line from stdin instead")
    g.add_argument("--k", "--num_predictions", type=int, default=5,
                   help="top-k tokens per [MASK] position")
    g.add_argument("--step", type=int, default=None,
                   help="checkpoint step (default: best by val_loss)")
    g.add_argument("--max_batch", type=int, default=64,
                   help="micro-batch cap (power-of-two buckets below it)")
    g.add_argument("--max_delay_ms", type=float, default=0.0,
                   help="hold the first request of a batch this long for "
                        "stragglers (0 = pure continuous batching)")
    g.add_argument("--bucket_widths", type=int, nargs="+", default=None,
                   help="sequence-width serving buckets (the training "
                        "collator's rule): each request pads to the smallest "
                        "width holding it instead of max_seq_len")
    g.add_argument("--dtype", choices=("float32", "bfloat16"), default="float32",
                   help="serving compute dtype: float32 is the golden-parity "
                        "path; bfloat16 rebuilds the model at bf16 compute "
                        "and casts params once (the bf16 serving path)")
    g.add_argument("--quantize", choices=("none", "int8", "int4"),
                   default="none",
                   help="weight-only quantization: int8 stores the matmul "
                        "kernels as per-channel symmetric int8 (f32 scales), "
                        "int4 as grouped symmetric int4 (one scale per "
                        "--group_size rows of each column), dequantized "
                        "inside the compiled program — 0.5x/0.25x the weight "
                        "bytes streamed from HBM per micro-batch vs bf16 "
                        "(the measured serving bottleneck); on TPU the fused "
                        "dequant-matmul kernel streams the int tiles "
                        "directly. Params are quantized once at load; the "
                        "checkpoint stays f32 on disk. Composes with "
                        "--dtype: compute runs at --dtype, only weight "
                        "STORAGE is int8/int4")
    g.add_argument("--group_size", type=int, default=None,
                   help="rows per int4 scale group (default 128); int8 "
                        "stays per-channel unless set")
    g.add_argument("--cached", action="store_true",
                   help="serve via the latent-cache split (encode once, "
                        "decode the [MASK] queries) instead of the fused "
                        "forward")
    g.add_argument("--no_warmup", action="store_true",
                   help="skip ahead-of-time bucket compilation (first "
                        "requests then pay the compiles)")
    g.add_argument("--compile_cache", default=None, metavar="DIR",
                   help="zero-recompile cold start: persist every compiled "
                        "bucket program here (serialized executables, "
                        "perceiver_io_tpu.aot) — a warm restart deserializes "
                        "instead of recompiling, and warmup performs zero "
                        "XLA compiles. Fail-soft: a missing/unusable dir "
                        "warns and serves uncached — never refuses traffic")
    g.add_argument("--blocking_warmup", action="store_true",
                   help="wait for the FULL bucket-program family before "
                        "serving (the pre-r10 behavior). Default: warmup "
                        "runs in the background, priority-ordered, and "
                        "serving starts immediately — a request is answered "
                        "as soon as its program is ready")
    g.add_argument("--stats", action="store_true",
                   help="print engine stats to stderr on exit")
    f = parser.add_argument_group(
        "multi-replica fabric (perceiver_io_tpu.serving; PERF.md §Fabric)")
    f.add_argument("--replicas", type=int, default=0, metavar="N",
                   help="serve through a router tier over N replica "
                        "PROCESSES (each loads the checkpoint, warms its "
                        "own AOT pool, and is babysat by a supervisor that "
                        "restarts crashes with backoff): least-loaded "
                        "health-aware dispatch, transparent failover when a "
                        "replica dies, latent-cache affinity under --cached. "
                        "0 (default) = the single-process engine")
    f.add_argument("--transport", choices=("http", "uds", "shmem"),
                   default="http",
                   help="with --replicas: the router→replica data plane for "
                        "array RPCs — 'http' (portable default), 'uds' "
                        "(pipelined unix-socket frames), 'shmem' (shared-"
                        "memory slot slab + uds control channel). Admin "
                        "verbs and streamed generate always ride HTTP")
    f.add_argument("--drain_timeout_s", type=float, default=60.0,
                   help="graceful-drain bound: on SIGTERM/SIGINT (and fleet "
                        "shutdown) stop admission and wait up to this long "
                        "for accepted work to finish before exiting 0")
    f.add_argument("--rolling_swap_step", type=int, default=None,
                   metavar="STEP",
                   help="with --replicas: after serving, roll the fleet to "
                        "this checkpoint step ONE REPLICA AT A TIME "
                        "(update_params hot-swap; warm pools carry over), "
                        "baking each swap against its SLO burn / breaker "
                        "and auto-rolling the whole fleet back on "
                        "regression; the report prints to stderr")
    f.add_argument("--rolling_bake_s", type=float, default=2.0,
                   help="post-swap observation window per replica")
    f.add_argument("--rolling_burn_threshold", type=float, default=2.0,
                   help="post-swap SLO burn rate above which the rollout "
                        "rolls back")
    a = parser.add_argument_group(
        "elastic autoscaling + admission control (fleet mode; "
        "perceiver_io_tpu.serving.autoscale / .admission)")
    a.add_argument("--autoscale", action="store_true",
                   help="with --replicas: run the serving control loop — "
                        "an Autoscaler spawns/retires supervised replica "
                        "processes from the windowed fleet SLO-burn and "
                        "queue series (hold-down + hysteresis, scale-down "
                        "only via graceful drain-then-retire, capped "
                        "exponential backoff on failed spawns). Requires "
                        "--autoscale_rps_per_replica — seed it from a "
                        "measured tools/load_bench.py capacity fit, never "
                        "a guess")
    a.add_argument("--autoscale_rps_per_replica", type=float, default=None,
                   metavar="RPS",
                   help="measured requests/s one replica sustains at the "
                        "SLO (fit_capacity's slo_sustainable_rps over the "
                        "sweep's replica count)")
    a.add_argument("--min_replicas", type=int, default=1,
                   help="autoscale floor")
    a.add_argument("--max_replicas", type=int, default=None,
                   help="autoscale ceiling (default: 2x --replicas)")
    a.add_argument("--autoscale_interval_s", type=float, default=1.0,
                   help="control-loop tick cadence")
    a.add_argument("--priority_classes", default=None, metavar="SPEC",
                   help="admission control: comma-separated "
                        "'name:weight' service classes (e.g. "
                        "'gold:8,silver:4,bronze:1' — first is the "
                        "default class). Admitted requests dispatch in "
                        "weighted-fair order; each class owns a weight-"
                        "proportional share of --admission_queue_limit, "
                        "so one bursting class sheds in ITS share while "
                        "other classes' tail stays flat")
    a.add_argument("--client_quota_rps", type=float, default=None,
                   help="per-client token-bucket rate (each distinct "
                        "client id draws from its own bucket; over-quota "
                        "requests shed with a reasoned RejectedError that "
                        "burns the CLIENT'S class SLO only)")
    a.add_argument("--client_quota_burst", type=float, default=None,
                   help="token-bucket burst ceiling (default: 2x the "
                        "rate)")
    a.add_argument("--admission_queue_limit", type=int, default=256,
                   help="total WFQ queue slots split weight-"
                        "proportionally across the priority classes")
    a.add_argument("--request_client", default=None, metavar="ID",
                   help="client id THIS process's requests present at the "
                        "admission gate (they draw that client's token "
                        "bucket; omitted = quota-exempt operator traffic). "
                        "Several serve processes with different ids "
                        "compose into a multi-tenant front")
    a.add_argument("--request_priority", default=None, metavar="CLASS",
                   help="priority class this process's requests ride in "
                        "(default: the admission controller's default "
                        "class)")
    d = parser.add_argument_group(
        "continuous deployment (perceiver_io_tpu.deploy; PERF.md "
        "§Deployment)")
    d.add_argument("--watch_checkpoints", default=None, metavar="DIR",
                   help="watch this publish directory (TrainerConfig."
                        "publish_dir) for new checkpoint publications and "
                        "hot-swap each one into live serving AFTER it "
                        "passes the admission gate (digest verification, "
                        "all-finite scan, golden-batch forward within "
                        "--gate_quality_tol of the incumbent). A failing "
                        "publication is quarantined in place and never "
                        "re-attempted; a post-swap SLO-burn/breaker "
                        "regression rolls back to the incumbent tree. "
                        "Works in both single-process and --replicas mode "
                        "(fleet swaps roll one replica at a time)")
    d.add_argument("--gate_quality_tol", type=float, default=0.5,
                   help="admission-gate quality bound: maximum relative "
                        "deviation of the candidate's golden-batch outputs "
                        "from the incumbent's (an online-refresh checkpoint "
                        "continues the same run — garbage trees deviate by "
                        "orders of magnitude)")
    d.add_argument("--publish_poll_s", type=float, default=2.0,
                   help="seconds between publish-directory polls")
    r = parser.add_argument_group(
        "resilience (PERF.md §Reliability: retry/shed/breaker semantics)")
    r.add_argument("--request_deadline_s", type=float, default=None,
                   help="per-request deadline: a request still waiting for "
                        "dispatch past this is SHED with DeadlineExceeded "
                        "(at admission and batch assembly) instead of "
                        "occupying the queue as dead work. Default: none")
    r.add_argument("--queue_limit", type=int, default=None,
                   help="bounded queue: admission fast-fails with "
                        "RejectedError once this many micro-batch parts are "
                        "backlogged (explicit load shedding instead of "
                        "unbounded growth). Default: unbounded")
    r.add_argument("--dispatch_retries", type=int, default=2,
                   help="transient dispatch/completion failures re-dispatch "
                        "the micro-batch with exponential backoff up to this "
                        "many times before failing its requests (the error "
                        "taxonomy never retries fatal errors). 0 disables")
    r.add_argument("--breaker_failures", type=int, default=0,
                   help="circuit breaker: open after this many CONSECUTIVE "
                        "dispatch failures (or a heartbeat stall) and "
                        "fast-fail submissions until a cooldown probe "
                        "succeeds; state exported to /metrics and /healthz. "
                        "0 disables (default)")
    r.add_argument("--breaker_cooldown_s", type=float, default=5.0,
                   help="seconds an open breaker fast-fails before admitting "
                        "a half-open probe")
    o = parser.add_argument_group("observability")
    o.add_argument("--metrics_port", type=int, default=None,
                   help="start the localhost observability sidecar on this "
                        "port (/metrics Prometheus text, /healthz, /statz "
                        "JSON); 0 picks an ephemeral port — the bound port "
                        "is printed to stderr. Default: off")
    o.add_argument("--heartbeat_deadline_s", type=float, default=None,
                   help="dispatch heartbeat deadline: if no dispatch "
                        "completes within this many seconds while work is in "
                        "flight (wedged tunnel), /healthz flips unhealthy and "
                        "a thread-stack diagnostic is dumped to stderr. "
                        "Default: off")
    o.add_argument("--selfprofile_every", type=int, default=0,
                   help="in-loop device-trace watchdog: every N micro-batches "
                        "capture a short jax.profiler trace, analyze it "
                        "in-process, and publish device-clock step time "
                        "gauges. Default: off")
    o.add_argument("--events_jsonl", default=None,
                   help="append runtime events (compiles, warmups, stalls, "
                        "per-request phase spans) as JSON lines to this file "
                        "(size-capped rotation: see --events_max_mb)")
    o.add_argument("--events_max_mb", type=float, default=64.0,
                   help="rotate the events file past this size, keeping 3 "
                        "numbered segments (a week of serving cannot grow "
                        "it unboundedly); 0 disables rotation")
    o.add_argument("--span_every", type=int, default=1,
                   help="emit a request_phases span for every Nth completed "
                        "request part (each span is a synchronous JSONL "
                        "write — sample at high request rates; the "
                        "serving_phase_seconds histograms keep the "
                        "full-rate view regardless)")
    o.add_argument("--trace_sample", type=float, default=1.0,
                   help="distributed request tracing head-sampling rate: "
                        "the fraction of requests that mint a TraceContext "
                        "(router/engine submit) and record spans at every "
                        "hop into --events_jsonl. In --replicas mode each "
                        "replica process writes its own "
                        "<events_jsonl>.<replica> log; assemble "
                        "per-request trace trees with "
                        "tools/trace_assemble.py. 0 disables; tail-based "
                        "retention happens at assembly")
    o.add_argument("--series", action="store_true",
                   help="sample every registry instrument into a bounded "
                        "in-memory time-series store at --series_interval_s "
                        "(counters as cumulative values, gauges as values, "
                        "histograms as windowed p50/p95/p99+count) and "
                        "serve it live as /seriesz on the --metrics_port "
                        "sidecar (?window_s=60 bounds the returned points). "
                        "Implied by --series_jsonl / --alert_rules")
    o.add_argument("--series_interval_s", type=float, default=1.0,
                   help="sampling cadence (PERF.md §Timeseries: overhead "
                        "at the 1 s default is below the CPU noise floor)")
    o.add_argument("--series_jsonl", default=None, metavar="PATH",
                   help="persist one series_sample JSON line per sweep "
                        "here (size-capped rotation like --events_jsonl) — "
                        "the on-disk history next to the event log")
    o.add_argument("--alert_rules", default=None, metavar="FILE",
                   help="JSON alert rules (a list of AlertRule objects: "
                        "name/metric/kind=threshold|rate|absence/op/"
                        "threshold/window_s/for_s/resolve_threshold/"
                        "severity) evaluated over the sampled series every "
                        "--series_interval_s: transitions emit alert_firing/"
                        "alert_resolved events into --events_jsonl, "
                        "alert_state{rule=} rides /metrics, and a firing "
                        "page-severity rule degrades /healthz")
    o.add_argument("--slo_p99_ms", type=float, default=None,
                   help="serving SLO latency target: a request answered "
                        "within this many ms counts good, sheds/errors and "
                        "slower answers burn the error budget. Enables the "
                        "slo_* burn-rate gauges on /metrics and /statz and "
                        "wires the burn alert into /healthz "
                        "(obs/slo.py; sweep with tools/load_bench.py)")
    o.add_argument("--slo_availability", type=float, default=0.999,
                   help="fraction of requests that must meet the SLO "
                        "(error budget = 1 - this)")
    o.add_argument("--slo_ttft_ms", type=float, default=None,
                   help="generate task, --replicas mode: per-stream time-"
                        "to-first-token target forwarded to every replica "
                        "— streams over it burn the stream SLO "
                        "(stream_burn on /statz; the router degrades and "
                        "the autoscaler scales on it)")
    o.add_argument("--slo_itl_ms", type=float, default=None,
                   help="generate task, --replicas mode: per-stream mean "
                        "inter-token-latency target (same wire as "
                        "--slo_ttft_ms)")
    o.add_argument("--slo_burn_alert", type=float, default=2.0,
                   help="/healthz degrades when the windowed error-budget "
                        "burn rate exceeds this (1.0 = spending the budget "
                        "exactly as it accrues); 0 disables the health wire")
    parser.add_argument("--cpu", action="store_true",
                        help="pin to the CPU backend (ensure_cpu_only before "
                             "jax initializes) — the offline/tier-1 mode")
    return parser


def main(argv: Optional[Sequence[str]] = None):
    args = build_parser().parse_args(argv)
    if not args.texts and not args.stdin:  # catches omitted AND empty --texts
        raise SystemExit("nothing to serve: pass --texts ... or --stdin")
    if args.autoscale:
        if args.replicas <= 0:
            raise SystemExit("--autoscale needs --replicas N (the control "
                             "loop lives at the router tier)")
        if not args.autoscale_rps_per_replica:
            raise SystemExit(
                "--autoscale needs --autoscale_rps_per_replica — seed it "
                "from a measured tools/load_bench.py capacity fit "
                "(slo_sustainable_rps / replicas), never a guess")
    if (args.priority_classes or args.client_quota_rps) \
            and args.replicas <= 0:
        raise SystemExit("--priority_classes/--client_quota_rps need "
                         "--replicas N (admission lives at the router)")

    # drain handlers go in FIRST: a SIGTERM during the checkpoint load /
    # warmup must already mean "graceful exit 0", not the default kill
    drain_state, restore_handlers = _install_drain_handlers()

    if args.cpu:
        from perceiver_io_tpu.utils.platform import ensure_cpu_only

        ensure_cpu_only()

    import perceiver_io_tpu.obs as obs
    from perceiver_io_tpu.data.tokenizer import load_tokenizer
    from perceiver_io_tpu.inference import MLMServer, load_mlm_checkpoint

    if args.events_jsonl:
        obs.configure_event_log(
            args.events_jsonl,
            max_bytes=(int(args.events_max_mb * 1024 * 1024)
                       if args.events_max_mb > 0 else None),
        )
    obs_server = None
    sampler = None
    alert_engine = None
    if args.metrics_port is not None:
        # started BEFORE the checkpoint load / warmup so probes can watch a
        # slow bring-up; counters stay zero until requests arrive. stdout is
        # the result stream — the sidecar address goes to stderr. Process
        # self-metrics (RSS/uptime/threads/GC) refresh at every scrape so
        # saturation correlates with host pressure.
        obs.install_process_metrics()
        obs_server = obs.ObsServer(port=args.metrics_port)
        url = obs_server.start()
        if url is not None:
            print(f"serve: metrics on {url}/metrics (also /healthz /statz"
                  + ("/seriesz" if (args.series or args.series_jsonl
                                    or args.alert_rules) else "") + ")",
                  file=sys.stderr, flush=True)

    if args.series or args.series_jsonl or args.alert_rules:
        # the historical half: a bounded store sampled on a cadence,
        # installed as the process default so /seriesz serves it live;
        # optional JSONL persistence rides the same rotation contract as
        # the event log. Alert rules evaluate over the same store.
        store = obs.SeriesStore()
        obs.install_series_store(store)
        sampler = obs.Sampler(
            store=store, interval_s=args.series_interval_s,
            jsonl_path=args.series_jsonl, name="serve").start()
        print(f"serve: sampling series every {args.series_interval_s:g}s"
              + (f" -> {args.series_jsonl}" if args.series_jsonl else ""),
              file=sys.stderr, flush=True)
        if args.alert_rules:
            rules = obs.load_alert_rules(args.alert_rules)
            alert_engine = obs.AlertEngine(
                store, rules, interval_s=args.series_interval_s,
                name="serve").start()
            print(f"serve: {len(rules)} alert rule(s) active "
                  f"({', '.join(r.name for r in rules)}) — firing "
                  "page-class alerts degrade /healthz", file=sys.stderr,
                  flush=True)

    try:
        if args.task == "generate":
            if args.replicas > 0:
                raise SystemExit(
                    "--task generate serves single-process here; a "
                    "generation FLEET runs `python -m "
                    "perceiver_io_tpu.serving.replica --task generate` "
                    "replicas behind a serving.Router")
            return _serve_generate(args, load_tokenizer, drain_state)
        if args.replicas > 0:
            return _serve_fleet(args, drain_state)
        return _serve(args, MLMServer, load_tokenizer, load_mlm_checkpoint,
                      drain_state)
    except _DrainRequested:
        # the signal landed during startup (load/warmup), before any request
        # was admitted: nothing is in flight, exit 0 with nothing served
        print("serve: drain requested during startup — exiting with no "
              "requests admitted", file=sys.stderr, flush=True)
        return []
    finally:
        # an exception mid-serve must not leak the sidecar thread, the
        # drain signal handlers, or leave the process-global event log
        # bound to this run's file (serve.main is also called in-process by
        # tests/other tools). configure_event_log(None) FLUSHES and closes
        # the JSONL stream — the drain contract's "flush the event log".
        restore_handlers()
        if alert_engine is not None:
            # one last evaluation so an episode that ended during drain
            # still resolves into the event log before it closes
            try:
                alert_engine.evaluate()
            except Exception:
                pass
            print(f"serve: alerts {json.dumps(alert_engine.stats())}",
                  file=sys.stderr, flush=True)
            alert_engine.close()
        if sampler is not None:
            sampler.close()  # drains --series_jsonl to disk
            obs.install_series_store(None)
        if obs_server is not None:
            obs_server.close()
        if args.events_jsonl:
            obs.configure_event_log(None)


def _start_deployer(args, model, params, max_seq_len, target):
    """The serving half of the train→serve loop (``--watch_checkpoints``):
    poll the publish dir, admission-gate every publication (digest /
    finite / golden-forward-vs-incumbent quality), and hot-swap passing
    trees into ``target``. The gate is handed over as a FACTORY, so its
    golden-program compile happens lazily on the deployer thread — serve
    startup stays non-blocking (the r10 background-warmup property) even
    when no publication ever arrives. Publications at or below the booted
    checkpoint's step are ignored (a restart must not replay — or
    quarantine — the historical backlog). Runs on a daemon thread; the
    caller's drain path stops it via :func:`_stop_deployer`."""
    import numpy as np

    from perceiver_io_tpu.deploy import AdmissionGate, ModelDeployer
    from perceiver_io_tpu.inference.engine import mlm_apply_fns
    from perceiver_io_tpu.training.checkpoint import resolve_checkpoint_step

    golden = (np.zeros((1, max_seq_len), np.int32),
              np.zeros((1, max_seq_len), bool),
              np.zeros((1, 2), np.int32))

    def make_gate():
        return AdmissionGate(
            mlm_apply_fns(model)["infer"], golden, params,
            quality_tol=args.gate_quality_tol, name="serve",
        )

    try:
        min_step = resolve_checkpoint_step(args.checkpoint, args.step)
    except Exception:  # unranked/odd checkpoint dir: accept every step
        min_step = -1
    deployer = ModelDeployer(
        args.watch_checkpoints, make_gate, target,
        poll_s=args.publish_poll_s, name="serve", min_step=min_step,
    ).start()
    print(f"serve: watching {args.watch_checkpoints} for checkpoint "
          f"publications newer than step {min_step} (poll "
          f"{args.publish_poll_s:g}s, quality tol "
          f"{args.gate_quality_tol:g})", file=sys.stderr, flush=True)
    return deployer


def _stop_deployer(deployer, timeout_s: float) -> None:
    if deployer is None:
        return
    if not deployer.stop(timeout_s):
        print("serve: WARNING — deployment loop did not stop within "
              f"{timeout_s:g}s (a swap may still be in flight)",
              file=sys.stderr, flush=True)
    else:
        print(f"serve: deployment loop stopped "
              f"({json.dumps(deployer.stats())})", file=sys.stderr,
              flush=True)


def _serve(args, MLMServer, load_tokenizer, load_mlm_checkpoint,
           drain_state=None):
    # Deliberately tier 1 ONLY in the serve process: the AOT executable
    # cache covers every compile serving performs (the bucket programs), and
    # enabling jax's persistent compilation cache IN ADDITION measurably
    # destabilizes this jaxlib — both tiers serialize the same executable,
    # and the double serialization intermittently corrupts the CPU runtime
    # (PERF.md §Cold start records the negative result). Trainers/tools,
    # which have no AOT tier, use tier 2 via --compile_cache there.
    tokenizer = load_tokenizer(args.tokenizer)
    model, params, max_seq_len = load_mlm_checkpoint(
        args.checkpoint, tokenizer, step=args.step,
        dtype="bfloat16" if args.dtype == "bfloat16" else None,
    )

    import perceiver_io_tpu.obs as obs

    slo = None
    if args.slo_p99_ms is not None:
        slo = obs.SLO(
            latency_target_s=args.slo_p99_ms / 1e3,
            availability_target=args.slo_availability,
            burn_alert=args.slo_burn_alert if args.slo_burn_alert > 0 else None,
        )

    results = []
    with MLMServer(
        model, params, tokenizer, max_seq_len,
        bucket_widths=args.bucket_widths,
        max_batch=args.max_batch,
        max_delay_ms=args.max_delay_ms,
        compute_dtype="bfloat16" if args.dtype == "bfloat16" else None,
        quantize=None if args.quantize == "none" else args.quantize,
        group_size=args.group_size,
        heartbeat_deadline_s=args.heartbeat_deadline_s,
        selfprofile_every=args.selfprofile_every,
        request_deadline_s=args.request_deadline_s,
        queue_limit=args.queue_limit,
        dispatch_retries=args.dispatch_retries,
        breaker_failures=args.breaker_failures,
        breaker_cooldown_s=args.breaker_cooldown_s,
        compile_cache=args.compile_cache,
        slo=slo,
        span_every=args.span_every,
        trace_sample=args.trace_sample,
    ) as server:
        warmup_handle = None
        if not args.no_warmup:
            if args.blocking_warmup:
                n = server.warmup()
                print(f"serve: warmed {n} bucket programs", file=sys.stderr)
            else:
                warmup_handle = server.warmup(background=True)
                print("serve: warming bucket programs in the background; "
                      "serving immediately (--blocking_warmup restores the "
                      "wait)", file=sys.stderr)

        deployer = None
        if args.watch_checkpoints:
            from perceiver_io_tpu.deploy import EngineSwapTarget

            deployer = _start_deployer(
                args, model, params, max_seq_len,
                EngineSwapTarget(server, params,
                                 bake_s=args.rolling_bake_s,
                                 burn_threshold=args.rolling_burn_threshold),
            )

        def emit(text: str, fills) -> None:
            line = {"text": text, "fills": fills}
            results.append(line)
            print(json.dumps(line))

        # pending futures in emission order — tracked OUTSIDE the admission
        # loops so a drain signal that unwinds them still finds (and
        # finishes) every accepted request
        pending = []
        try:
            try:
                if args.texts:
                    if args.cached:
                        cached = server.encode(args.texts)
                        for text, f in zip(args.texts, server.fill_masks_cached(
                                cached, k=args.k)):
                            emit(text, f)
                    else:
                        for text in args.texts:
                            pending.append((text, server.submit(text, k=args.k)))
                if args.stdin:
                    if args.cached:
                        # cached mode batches the whole pipe: one encode sweep,
                        # one decode sweep — per-line sync round-trips would
                        # serialize into exactly the naive dispatch the engine
                        # exists to beat
                        lines = [l.rstrip("\n") for l in sys.stdin]
                        lines = [l for l in lines if l]
                        cached = server.encode(lines)
                        for text, f in zip(lines, server.fill_masks_cached(
                                cached, k=args.k)):
                            emit(text, f)
                    else:
                        # a line-per-request stream: submit as lines arrive,
                        # resolve in order — arrivals batch up behind the
                        # in-flight dispatch. The marker line tells a supervisor
                        # (and the drain test) admission is live.
                        print("serve: admitting stdin", file=sys.stderr,
                              flush=True)
                        for line in sys.stdin:
                            text = line.rstrip("\n")
                            if text:
                                pending.append(
                                    (text, server.submit(text, k=args.k)))
            except _DrainRequested:
                # graceful drain: admission stopped (the raise unwound the
                # loops); everything already accepted below still finishes and
                # the process exits 0 — a supervisor rotation never drops the
                # queue. Later signals are absorbed by the handler.
                print("serve: drain requested (signal) — admission stopped, "
                      f"finishing {len(pending)} in-flight request(s)",
                      file=sys.stderr, flush=True)
            # admission is over either way: mark draining so a FIRST signal
            # landing during the resolve loop below is absorbed by the handler
            # (printed, not raised) — finish-in-flight can never be unwound
            # into dropping accepted results
            signaled = drain_state is not None and drain_state.get("draining")
            if drain_state is not None:
                drain_state["draining"] = True
            for text, fut in pending:
                emit(text, fut.result())
            if signaled:
                server.drain(args.drain_timeout_s)
        finally:
            # the drain contract extends to the deployment loop: an
            # in-progress gated swap COMPLETES (or rolls back) before exit —
            # never a half-swapped server
            _stop_deployer(deployer, args.drain_timeout_s)
        if warmup_handle is not None and warmup_handle.done():
            try:
                n = warmup_handle.wait(0)
                print(f"serve: warmed {n} bucket programs (background)",
                      file=sys.stderr)
            except Exception as e:  # warmup failed; requests self-compiled
                print(f"serve: background warmup failed "
                      f"({type(e).__name__}: {e}) — programs were built "
                      "on demand", file=sys.stderr)
        if args.stats:
            print(f"serve: stats {json.dumps(server.stats())}", file=sys.stderr)
    return results


def _serve_generate(args, load_tokenizer, drain_state=None):
    """``--task generate``: stream Perceiver-AR continuations of each input
    line. One JSON result line per prompt on stdout ({"text",
    "continuation_ids", "continuation"}); chunk-by-chunk progress rides
    stderr. A drain signal stops admission; the tokens already streamed for
    an interrupted prompt still emit (accepted work is never dropped)."""
    from perceiver_io_tpu.inference.generate import (
        ARGenerator,
        SamplingConfig,
        load_ar_checkpoint,
    )

    tokenizer = load_tokenizer(args.tokenizer)
    model, params, max_seq_len = load_ar_checkpoint(
        args.checkpoint, tokenizer, step=args.step,
        dtype="bfloat16" if args.dtype == "bfloat16" else None,
    )
    if args.decode_batching:
        from perceiver_io_tpu.inference.batching import ContinuousBatcher

        gen = ContinuousBatcher(
            model, params, max_seq_len=max_seq_len,
            chunk=args.generate_chunk, slots=args.decode_slots,
            compute_dtype="bfloat16" if args.dtype == "bfloat16" else None,
            compile_cache=args.compile_cache,
            heartbeat_deadline_s=args.heartbeat_deadline_s,
        )
    else:
        gen = ARGenerator(
            model, params, max_seq_len=max_seq_len,
            chunk=args.generate_chunk,
            compute_dtype="bfloat16" if args.dtype == "bfloat16" else None,
        )
    sampling = SamplingConfig(temperature=args.temperature,
                              top_k=args.top_k, seed=args.gen_seed)
    if not args.no_warmup:
        # warm the CONFIGURED sampling shape: greedy and top-k are distinct
        # compiled decode programs, and an unwarmed shape is a mid-stream
        # compile stall on the first prompt
        n = gen.warmup(sampling=sampling)
        print(f"serve: warmed {n} generation programs", file=sys.stderr)
    results = []

    def emit(text: str, tokens) -> None:
        line = {
            "text": text,
            "continuation_ids": list(tokens),
            "continuation": " ".join(
                tokenizer.id_to_token(int(t)) for t in tokens),
        }
        results.append(line)
        print(json.dumps(line))

    def run_one(text: str) -> None:
        prefix = tokenizer.encode_ids(text)
        if not prefix:
            emit(text, [])
            return
        streamed = []

        def on_chunk(tokens, info):
            streamed.extend(tokens)
            print(f"serve: +{len(tokens)} tokens @pos {info['pos']} "
                  f"({info['chunk_ms']:.1f} ms)", file=sys.stderr,
                  flush=True)

        try:
            tokens, _ = gen.generate(prefix, args.max_new_tokens, sampling,
                                     on_chunk=on_chunk)
        except _DrainRequested:
            emit(text, streamed)  # what was accepted still emits
            raise
        emit(text, tokens)

    try:
        if args.texts:
            for text in args.texts:
                run_one(text)
        else:
            for line in sys.stdin:
                line = line.strip()
                if line:
                    run_one(line)
    except _DrainRequested:
        print("serve: drain requested — admission stopped", file=sys.stderr,
              flush=True)
    if args.stats:
        print(json.dumps({"prompts": len(results)}), file=sys.stderr)
    return results


def _serve_fleet(args, drain_state):
    """``--replicas N``: the router-tier serving path. N replica processes
    each load the checkpoint and warm their own pools; the router does the
    tokenize/top-k host work and least-loaded dispatch; ``--cached`` runs
    encode-once/decode-many with session affinity (the latents stay on the
    replica that encoded them)."""
    import numpy as np

    from perceiver_io_tpu.data.tokenizer import (
        MASK_TOKEN,
        PAD_TOKEN,
        load_tokenizer,
    )
    from perceiver_io_tpu.inference.mlm import (
        masked_token_ids,
        pad_token_rows,
    )
    from perceiver_io_tpu.inference.predictor import bucket_size
    from perceiver_io_tpu.resilience import AffinityLost
    from perceiver_io_tpu.serving import ReplicaSupervisor, Router
    from perceiver_io_tpu.training.checkpoint import load_hparams

    tokenizer = load_tokenizer(args.tokenizer)
    max_seq_len = load_hparams(args.checkpoint)["max_seq_len"]
    mask_id = tokenizer.token_to_id(MASK_TOKEN)
    pad_id = tokenizer.token_to_id(PAD_TOKEN)

    extra = ["--checkpoint", args.checkpoint, "--tokenizer", args.tokenizer,
             "--max_batch", str(args.max_batch), "--dtype", args.dtype,
             "--max_delay_ms", str(args.max_delay_ms),
             "--drain_timeout_s", str(args.drain_timeout_s)]
    if args.bucket_widths is not None:
        # width bucketing is an MLMServer concern; replicas serve the
        # full-width rows the router prepares
        print("serve: --bucket_widths has no effect with --replicas "
              "(fleet requests are prepared at max_seq_len width)",
              file=sys.stderr, flush=True)
    if args.cpu:
        extra.append("--cpu")
    if args.step is not None:
        extra += ["--step", str(args.step)]
    if args.quantize != "none":
        extra += ["--quantize", args.quantize]
    if args.group_size is not None:
        extra += ["--group_size", str(args.group_size)]
    if args.compile_cache:
        extra += ["--compile_cache", args.compile_cache]
    if args.no_warmup:
        extra.append("--no_warmup")
    if args.queue_limit is not None:
        extra += ["--queue_limit", str(args.queue_limit)]
    if args.request_deadline_s is not None:
        extra += ["--request_deadline_s", str(args.request_deadline_s)]
    extra += ["--dispatch_retries", str(args.dispatch_retries)]
    if args.breaker_failures:
        extra += ["--breaker_failures", str(args.breaker_failures),
                  "--breaker_cooldown_s", str(args.breaker_cooldown_s)]
    if args.heartbeat_deadline_s is not None:
        extra += ["--heartbeat_deadline_s", str(args.heartbeat_deadline_s)]
    if args.slo_p99_ms is not None:
        extra += ["--slo_p99_ms", str(args.slo_p99_ms),
                  "--slo_availability", str(args.slo_availability)]
    if args.slo_ttft_ms is not None:
        extra += ["--slo_ttft_ms", str(args.slo_ttft_ms)]
    if args.slo_itl_ms is not None:
        extra += ["--slo_itl_ms", str(args.slo_itl_ms)]

    def prepare(text):
        row = masked_token_ids(tokenizer, text)[:max_seq_len]
        ids, pad = pad_token_rows([row], max_seq_len, pad_id)
        mask_pos = np.nonzero(ids[0] == mask_id)[0]
        kb = bucket_size(max(len(mask_pos), 1), max_seq_len)
        positions = np.zeros((1, kb), np.int32)
        positions[0, : len(mask_pos)] = mask_pos
        return ids, pad, mask_pos, positions

    def topk(logits, n_masks):
        out = []
        for slot in range(n_masks):
            top = np.argsort(-np.asarray(logits[0, slot], np.float32))[:args.k]
            out.append([tokenizer.id_to_token(int(t)) for t in top])
        return out

    results = []

    def emit(text, fills):
        line = {"text": text, "fills": fills}
        results.append(line)
        print(json.dumps(line))

    sup_kw = {}
    if args.events_jsonl:
        # every fleet process owns its own JSONL (concurrent writers on one
        # file would tear lines): the router writes args.events_jsonl, each
        # replica <events_jsonl>.<name> — trace_assemble merges them into
        # per-request trace trees with cross-process clock alignment. The
        # rotation bound rides along; --trace_sample deliberately does NOT
        # (the ROUTER owns the head-sampling decision — replicas default
        # to never self-minting, so an unsampled request stays unsampled
        # at every hop instead of double-sampling)
        from perceiver_io_tpu.serving.supervisor import default_replica_argv

        def _replica_argv(name, port):
            return default_replica_argv(
                name, port,
                extra=[*extra, "--events_jsonl",
                       f"{args.events_jsonl}.{name}",
                       "--events_max_mb", str(args.events_max_mb)],
                transport=args.transport)

        sup_kw["argv_builder"] = _replica_argv
    admission = None
    if args.priority_classes or args.client_quota_rps:
        from perceiver_io_tpu.serving import (
            AdmissionController,
            parse_priority_classes,
        )

        quota = None
        if args.client_quota_rps:
            # TokenBucket requires burst >= 1: the 2x-rate default would
            # crash a sub-0.5 req/s quota at startup
            quota = (args.client_quota_rps,
                     args.client_quota_burst
                     or max(1.0, 2 * args.client_quota_rps))
        classes = (parse_priority_classes(args.priority_classes)
                   if args.priority_classes else None)
        slo = None
        if args.slo_p99_ms is not None:
            import perceiver_io_tpu.obs as obs

            slo = obs.SLO(latency_target_s=args.slo_p99_ms / 1e3,
                          availability_target=args.slo_availability,
                          name="serve", burn_alert=None)
        admission = AdmissionController(
            classes=classes, quota=quota, slo=slo,
            queue_limit=args.admission_queue_limit, name="serve")
        print("serve: admission control — classes "
              f"{sorted(admission.classes)} (default "
              f"{admission.default_class!r})"
              + (f", per-client quota {quota[0]:g} req/s burst {quota[1]:g}"
                 if quota else ""), file=sys.stderr, flush=True)
    with ReplicaSupervisor(count=args.replicas, extra_args=extra,
                           cpu=args.cpu, transport=args.transport,
                           **sup_kw) as sup:
        clients = sup.start()
        print(f"serve: spawned {args.replicas} replicas; waiting for warm "
              "pools (engine_ready)", file=sys.stderr, flush=True)
        sup.wait_ready(timeout_s=600.0)
        with Router(clients, name="serve",
                    queue_limit=args.queue_limit,
                    trace_sample=args.trace_sample,
                    admission=admission) as router:
            router.refresh()
            autoscaler = None
            if args.autoscale:
                from perceiver_io_tpu.serving import (
                    Autoscaler,
                    AutoscalePolicy,
                    SupervisorPool,
                )

                policy = AutoscalePolicy(
                    rps_per_replica=args.autoscale_rps_per_replica,
                    min_replicas=args.min_replicas,
                    max_replicas=args.max_replicas or 2 * args.replicas,
                    drain_timeout_s=args.drain_timeout_s,
                )
                autoscaler = Autoscaler(
                    router,
                    SupervisorPool(sup,
                                   drain_timeout_s=args.drain_timeout_s),
                    policy,
                    interval_s=args.autoscale_interval_s).start()
                print(f"serve: autoscaling fleet [{policy.min_replicas}, "
                      f"{policy.max_replicas}] at "
                      f"{policy.rps_per_replica:g} req/s/replica "
                      f"(tick {args.autoscale_interval_s:g}s)",
                      file=sys.stderr, flush=True)
            deployer = None
            if args.watch_checkpoints:
                from perceiver_io_tpu.deploy import RouterSwapTarget
                from perceiver_io_tpu.inference import load_mlm_checkpoint

                # the gate needs a reference forward + incumbent tree in THIS
                # process (no replica may see a candidate before it passes);
                # passing trees then roll replica-by-replica as publication
                # specs each replica loads digest-verified
                model, params, _ = load_mlm_checkpoint(
                    args.checkpoint, tokenizer, step=args.step)
                deployer = _start_deployer(
                    args, model, params, max_seq_len,
                    RouterSwapTarget(
                        router, bake_s=args.rolling_bake_s,
                        burn_threshold=args.rolling_burn_threshold),
                )
            pending = []  # (text, future-or-None, n_masks)
            # the admission identity this process's requests present at
            # the gate (quota bucket + service class)
            adm_kw = {"client": args.request_client,
                      "priority": args.request_priority}

            def submit(text):
                ids, pad, mask_pos, positions = prepare(text)
                if len(mask_pos) == 0:
                    pending.append((text, None, 0))
                    return
                if args.cached:
                    # encode-once: the encode is ASYNC so successive lines
                    # overlap and micro-batch on the replicas (a per-line
                    # sync round-trip would serialize admission into naive
                    # dispatch). The decode is submitted at RESOLVE time,
                    # after its encode established the pin — submitting it
                    # now would race the pin and land on a replica without
                    # the latents.
                    session = f"t{len(pending)}"
                    enc = router.submit(ids, pad, kind="encode",
                                        session=session, **adm_kw)
                    fut = (session, ids, pad, positions, enc)
                else:
                    fut = router.submit(ids, pad, positions, **adm_kw)
                pending.append((text, fut, len(mask_pos)))

            def resolve(fut, n_masks):
                if not isinstance(fut, tuple):
                    return topk(fut.result(timeout=600), n_masks)
                session, ids, pad, positions, enc = fut
                enc.result(timeout=600)  # pin established
                try:
                    logits = router.decode(positions, session=session,
                                           timeout=600, **adm_kw)
                except AffinityLost:
                    # the pinned replica (and its latents) died:
                    # re-encode on a live replica — which re-pins —
                    # and decode there (spill-on-death)
                    router.encode(ids, pad, session=session, timeout=600,
                                  **adm_kw)
                    logits = router.decode(positions, session=session,
                                           timeout=600, **adm_kw)
                return topk(logits, n_masks)

            try:
                try:
                    for text in (args.texts or []):
                        submit(text)
                    if args.stdin:
                        print("serve: admitting stdin", file=sys.stderr,
                              flush=True)
                        for line in sys.stdin:
                            text = line.rstrip("\n")
                            if text:
                                submit(text)
                except _DrainRequested:
                    print("serve: drain requested (signal) — admission "
                          f"stopped, finishing {len(pending)} in-flight "
                          "request(s)", file=sys.stderr, flush=True)
                # admission is over either way: mark draining so a FIRST
                # signal landing during the resolve loop is absorbed by the
                # handler (printed, not raised) — finish-in-flight can never
                # be unwound into dropping accepted results
                signaled = drain_state.get("draining")
                drain_state["draining"] = True
                for text, fut, n_masks in pending:
                    emit(text, [] if fut is None else resolve(fut, n_masks))
                if args.rolling_swap_step is not None and not signaled:
                    report = router.rolling_update(
                        {"kind": "checkpoint", "path": args.checkpoint,
                         "step": args.rolling_swap_step},
                        bake_s=args.rolling_bake_s,
                        burn_threshold=args.rolling_burn_threshold,
                    )
                    print(f"serve: rolling swap {json.dumps(report)}",
                          file=sys.stderr, flush=True)
                if args.stats:
                    print(f"serve: fleet stats {json.dumps(router.stats())}",
                          file=sys.stderr)
                    if autoscaler is not None:
                        print("serve: autoscale stats "
                              f"{json.dumps(autoscaler.stats())}",
                              file=sys.stderr)
            finally:
                # the control loop stops FIRST (no scale action may race
                # the teardown), then the drain contract extends to the
                # deployment loop: an in-progress ROLLING swap completes
                # or rolls the fleet back before teardown — never a
                # half-swapped fleet
                if autoscaler is not None:
                    autoscaler.close()
                _stop_deployer(deployer, args.drain_timeout_s)
            # graceful fleet teardown: replicas finish accepted work before
            # the supervisor's quit/terminate sequence
            router.drain(args.drain_timeout_s)
    return results


if __name__ == "__main__":
    main()
