"""Multimodal audio/video autoencoding entry point (framework extension — the
reference has no audio/video task; this exercises the Perceiver IO paper's
Kinetics-style config: fused video+audio token stream in, video+audio
reconstruction + classification out).

Usage:

    python train/train_multimodal.py --experiment=multimodal \
        --video_frames 8 --video_size 32 --audio_samples 2048 --max_epochs 10
"""

from __future__ import annotations

import argparse
from typing import Optional, Sequence

import jax

from perceiver_io_tpu.cli import common
from perceiver_io_tpu.data.av import AVDataModule
from perceiver_io_tpu.models.multimodal import build_multimodal_autoencoder
from perceiver_io_tpu.training import TrainState, make_multimodal_steps
from perceiver_io_tpu.training.trainer import Trainer


def build_parser() -> argparse.ArgumentParser:
    parser = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    common.add_trainer_args(parser)
    common.add_mesh_args(parser)
    common.add_compute_args(parser)
    common.add_model_args(parser)
    common.add_optimizer_args(parser)
    g = parser.add_argument_group("data (audio/video)")
    g.add_argument("--root", default=".cache")
    g.add_argument("--batch_size", type=int, default=8)
    g.add_argument("--video_frames", type=int, default=16)
    g.add_argument("--video_size", type=int, default=224)
    g.add_argument("--video_channels", type=int, default=3)
    g.add_argument("--audio_samples", type=int, default=30720)
    g.add_argument("--audio_channels", type=int, default=1)
    g.add_argument("--num_classes", type=int, default=4)
    g.add_argument("--synthetic", action="store_true", default=True)
    g.add_argument("--real_data", dest="synthetic", action="store_false",
                   help="read <root>/av/<split>/<class>/<clip>.npz instead of "
                        "generating synthetic clips")
    g.add_argument("--synthetic_size", type=int, default=256)
    t = parser.add_argument_group("task (multimodal)")
    t.add_argument("--video_patch", type=int, nargs=3, default=(1, 4, 4),
                   metavar=("PT", "PH", "PW"))
    t.add_argument("--samples_per_patch", type=int, default=16)
    t.add_argument("--num_modality_channels", type=int, default=8)
    t.add_argument("--video_frequency_bands", type=int, default=32)
    t.add_argument("--audio_frequency_bands", type=int, default=64)
    t.add_argument("--video_patch_loss", action="store_true",
                   help="compute the video reconstruction loss in PATCH "
                        "space (patchify the target instead of un-patchifying "
                        "the prediction — same element set, exact up to fp "
                        "reassociation; skips the (B,T,H,W,C) transpose pair "
                        "in fwd+bwd). Params/checkpoints are unaffected")
    t.add_argument("--video_weight", type=float, default=1.0)
    t.add_argument("--audio_weight", type=float, default=1.0)
    t.add_argument("--label_weight", type=float, default=1.0)
    # paper-scale defaults, scaled down by CLI flags for smoke runs.
    # attn_impl 'xla' is the measured-best for the paper AV config (r4
    # roofline A/B: the area rule routes the 52k-query decoder cross to the
    # fused kernel, which loses 30.8 vs 27.7 ms end-to-end at b2 — the same
    # overlap dilution as PERF.md negative (11)); explicit --attn_impl wins
    parser.set_defaults(experiment="multimodal", num_latents=784,
                        num_latent_channels=512, num_encoder_layers=1,
                        num_self_attention_layers_per_block=8,
                        num_cross_attention_heads=1,
                        num_self_attention_heads=8,
                        attn_impl="xla")
    return parser


def main(argv: Optional[Sequence[str]] = None):
    args = common.parse_with_resume(build_parser(), argv)
    if common.maybe_spawn_hosts(args, argv):
        return None  # training ran in the spawned processes
    common.maybe_initialize_distributed(args)
    video_shape = (
        args.video_frames, args.video_size, args.video_size, args.video_channels
    )

    data = AVDataModule(
        root=args.root,
        video_shape=video_shape,
        num_audio_samples=args.audio_samples,
        num_audio_channels=args.audio_channels,
        num_classes=args.num_classes,
        batch_size=args.batch_size,
        synthetic=args.synthetic,
        synthetic_size=args.synthetic_size,
        seed=args.seed,
        shard_id=jax.process_index(),
        num_shards=jax.process_count(),
    )
    data.prepare_data()
    data.setup()

    model = build_multimodal_autoencoder(
        video_shape=video_shape,
        num_audio_samples=args.audio_samples,
        samples_per_patch=args.samples_per_patch,
        num_audio_channels=args.audio_channels,
        num_classes=data.num_classes,
        latent_shape=(args.num_latents, args.num_latent_channels),
        video_patch_shape=tuple(args.video_patch),
        num_layers=args.num_encoder_layers,
        num_self_attention_layers_per_block=args.num_self_attention_layers_per_block,
        num_cross_attention_heads=args.num_cross_attention_heads,
        num_self_attention_heads=args.num_self_attention_heads,
        num_modality_channels=args.num_modality_channels,
        video_frequency_bands=args.video_frequency_bands,
        audio_frequency_bands=args.audio_frequency_bands,
        dropout=args.dropout,
        dtype=common.DTYPES[args.dtype],
        attn_impl=args.attn_impl,
        remat=args.remat,
        reuse_kv=not getattr(args, "no_reuse_kv", False),
        video_patch_loss=args.video_patch_loss,
    )
    example = next(iter(data.val_dataloader()))
    variables = model.init(
        {"params": jax.random.key(args.seed)},
        {"video": example["video"][:1], "audio": example["audio"][:1]},
    )
    tx, schedule = common.optimizer_from_args(args)
    state = TrainState.create(variables["params"], tx, jax.random.key(args.seed + 2))
    state, resume_dir = common.resume_state(args, state)

    train_step, eval_step = make_multimodal_steps(
        model, schedule,
        video_weight=args.video_weight,
        audio_weight=args.audio_weight,
        label_weight=args.label_weight,
    )
    mesh = common.mesh_from_args(args)

    trainer = Trainer(
        train_step,
        lambda s, b, k: eval_step(s, b),
        state,
        common.trainer_config(args),
        example_batch={k: example[k] for k in ("video", "audio", "label")},
        mesh=mesh,
        shard_seq=args.shard_seq,
        zero_opt=args.zero_opt,
        hparams=vars(args),
        run_dir=resume_dir,
    )
    with trainer:
        common.run_fit(trainer, data.train_dataloader(), data.val_dataloader())
    return trainer.run_dir


if __name__ == "__main__":
    main()
