"""Perceiver-AR causal LM pretraining entry point (the generative task).

Trains :class:`~perceiver_io_tpu.models.perceiver.PerceiverARLM` —
next-token prediction over a causal latent window covering the last
``num_latents`` positions of each sequence — on the IMDB text pipeline (the
same tokenizer/collator the MLM task uses, so ``--synthetic`` long-doc mode
works fully offline). Checkpoints embed hparams and load back through
``inference.generate.load_ar_checkpoint`` for serving
(``serve.py --task generate`` / ``serving.replica --preset tiny_ar``).

Usage:

    python -m perceiver_io_tpu.cli.train_ar --synthetic --max_steps 200 \
        --default_root_dir /tmp/ar_run
"""

from __future__ import annotations

import argparse
from typing import Optional, Sequence

import jax
import numpy as np

from perceiver_io_tpu.cli import common
from perceiver_io_tpu.data.imdb import IMDBDataModule
from perceiver_io_tpu.training import TrainState, make_ar_steps
from perceiver_io_tpu.training.trainer import Trainer

# Width/compute defaults per --preset (the train_mlm pattern): 'reference' =
# CPU/GPU-scale widths, 'flagship_tpu' = the TPU-native flagship_ar widths.
PRESET_DEFAULTS = {
    "reference": {"num_latents": 64, "num_latent_channels": 64,
                  "attn_impl": "auto"},
    "flagship_tpu": {"num_latents": 256, "num_latent_channels": 512,
                     "attn_impl": "auto"},
}


def apply_preset(args: argparse.Namespace) -> argparse.Namespace:
    for key, value in PRESET_DEFAULTS[args.preset].items():
        if getattr(args, key) is None:
            setattr(args, key, value)
    return args


def build_parser() -> argparse.ArgumentParser:
    parser = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    common.add_trainer_args(parser)
    common.add_mesh_args(parser)
    common.add_compute_args(parser)
    common.add_model_args(parser)
    common.add_optimizer_args(parser)
    common.add_imdb_args(parser)
    g = parser.add_argument_group("task (AR generation)")
    g.add_argument("--preset", choices=["reference", "flagship_tpu"],
                   default="reference",
                   help="model-width preset; explicit width flags override")
    g.add_argument("--sample_prefix_len", type=int, default=16,
                   help="per-validation-epoch sample generation: continue "
                        "this many tokens of the first validation row "
                        "(0 disables the hook)")
    g.add_argument("--sample_new_tokens", type=int, default=12)
    parser.set_defaults(experiment="ar", batch_size=64, num_latents=None,
                        num_latent_channels=None, attn_impl=None,
                        num_encoder_layers=3)
    return parser


def make_sample_hook(model, collator, prefix_len: int,
                     new_tokens: int, example_ids: np.ndarray):
    """Per-eval sample continuation (the AR analogue of train_mlm's
    predict_samples): greedy-continue a validation prefix and log the
    decoded text."""
    if prefix_len <= 0 or new_tokens <= 0:
        return None
    from perceiver_io_tpu.inference.generate import ARGenerator, SamplingConfig

    prefix = [int(t) for t in example_ids[:prefix_len] if int(t) != 0]
    if len(prefix) < 2:
        return None
    tokenizer = collator.tokenizer

    def hook(state, logger, step):
        gen = ARGenerator(model, state.params,
                          max_seq_len=collator.max_seq_len,
                          chunk=min(8, new_tokens), name="train-sample")
        tokens, _ = gen.generate(prefix, new_tokens, SamplingConfig())
        text = " ".join(tokenizer.id_to_token(int(t)) for t in tokens)
        logger.log_text("continuation", step,
                        f"prefix({len(prefix)} toks) → {text}")

    return hook


def main(argv: Optional[Sequence[str]] = None):
    args = apply_preset(common.parse_with_resume(build_parser(), argv))
    if common.maybe_spawn_hosts(args, argv):
        return None
    common.maybe_initialize_distributed(args)
    common.validate_bucket_args(args)

    data = IMDBDataModule(
        root=args.root,
        max_seq_len=args.max_seq_len,
        vocab_size=args.vocab_size,
        batch_size=args.batch_size,
        synthetic=args.synthetic,
        synthetic_size=args.synthetic_size,
        seed=args.seed,
        shard_id=jax.process_index(),
        num_shards=jax.process_count(),
        download=not args.no_download,
        bucket_widths=args.bucket_widths,
        length_sort_window=args.length_sort_window,
        dispatch_group=args.steps_per_dispatch,
    )
    data.prepare_data()
    data.setup()
    vocab_size = data.tokenizer.get_vocab_size()

    model = common.build_ar(args, vocab_size, args.max_seq_len)
    example = next(iter(data.val_dataloader()))
    variables = model.init(
        {"params": jax.random.key(args.seed)},
        example["token_ids"][:1], example["pad_mask"][:1],
    )
    tx, schedule = common.optimizer_from_args(args)
    state = TrainState.create(variables["params"], tx,
                              jax.random.key(args.seed + 2))
    state, resume_dir = common.resume_state(args, state)

    mesh = common.mesh_from_args(args)
    train_step, eval_step, _ = make_ar_steps(model, schedule)

    trainer = Trainer(
        train_step,
        eval_step,
        state,
        common.trainer_config(args),
        example_batch={k: example[k] for k in ("token_ids", "pad_mask")},
        mesh=mesh,
        shard_seq=args.shard_seq,
        zero_opt=args.zero_opt,
        hparams=vars(args),
        run_dir=resume_dir,
        predict_hook=make_sample_hook(
            model, data.collator, args.sample_prefix_len,
            args.sample_new_tokens,
            np.asarray(example["token_ids"][0]),
        ),
        tokens_per_example=args.max_seq_len,
    )
    with trainer:
        state = common.run_fit(
            trainer, data.train_dataloader(), data.val_dataloader()
        )
    return trainer.run_dir


if __name__ == "__main__":
    main()
