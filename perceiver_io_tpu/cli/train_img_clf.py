"""MNIST image-classification entry point (reference ``train/train_img_clf.py``).

Reference per-task defaults (``train_img_clf.py:42-55``): 32 latents × 128
channels, 3 encoder layers × 3 self-attention layers per block, batch 128.
The model is built from the data module's ``dims``/``num_classes``
(``train_img_clf.py:15-17``).
"""

from __future__ import annotations

import argparse
from typing import Optional, Sequence

import jax

from perceiver_io_tpu.cli import common
from perceiver_io_tpu.data.mnist import MNISTDataModule
from perceiver_io_tpu.training import TrainState, make_classifier_steps
from perceiver_io_tpu.training.trainer import Trainer


def build_parser() -> argparse.ArgumentParser:
    parser = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    common.add_trainer_args(parser)
    common.add_mesh_args(parser)
    common.add_compute_args(parser)
    common.add_model_args(parser)
    common.add_optimizer_args(parser)
    common.add_mnist_args(parser)
    g = parser.add_argument_group("task (image classification)")
    g.add_argument("--num_frequency_bands", type=int, default=32)
    # reference per-task defaults (train_img_clf.py:42-55)
    parser.set_defaults(experiment="img_clf", num_latents=32,
                        num_latent_channels=128, num_encoder_layers=3,
                        num_self_attention_layers_per_block=3)
    return parser


def main(argv: Optional[Sequence[str]] = None):
    args = common.parse_with_resume(build_parser(), argv)
    if common.maybe_spawn_hosts(args, argv):
        return None  # training ran in the spawned processes
    common.maybe_initialize_distributed(args)

    data = MNISTDataModule(
        root=args.root,
        batch_size=args.batch_size,
        random_crop=args.random_crop,
        synthetic=args.synthetic,
        synthetic_size=args.synthetic_size,
        seed=args.seed,
        shard_id=jax.process_index(),
        num_shards=jax.process_count(),
        download=not args.no_download,
    )
    data.prepare_data()
    data.setup()

    model = common.build_image_classifier(
        args, data.dims, data.num_classes,
        num_frequency_bands=args.num_frequency_bands,
    )
    example = next(iter(data.val_dataloader()))
    variables = model.init(
        {"params": jax.random.key(args.seed)}, example["image"][:1]
    )
    tx, schedule = common.optimizer_from_args(args)
    state = TrainState.create(variables["params"], tx, jax.random.key(args.seed + 2))
    state, resume_dir = common.resume_state(args, state)

    train_step, eval_step = make_classifier_steps(model, schedule, input_kind="image")
    mesh = common.mesh_from_args(args)

    trainer = Trainer(
        train_step,
        lambda s, b, k: eval_step(s, b),
        state,
        common.trainer_config(args),
        example_batch={k: example[k] for k in ("image", "label")},
        mesh=mesh,
        shard_seq=args.shard_seq,
        zero_opt=args.zero_opt,
        hparams=vars(args),
        run_dir=resume_dir,
    )
    with trainer:
        common.run_fit(trainer, data.train_dataloader(), data.val_dataloader())
    return trainer.run_dir


if __name__ == "__main__":
    main()
