"""ImageNet-1k image-classification entry point.

The Perceiver-paper configuration tracked in BASELINE.md that exceeds the
reference repo's scope (its image path stops at MNIST, reference
``train/train_img_clf.py``): 224×224 inputs (M = 50,176 pixel positions
cross-attended into the latent array), 512 latents × 1024 channels, 6 encoder
layers (layer 1 unique, 2..6 weight-shared) × 6 self-attention layers per
block, 64 Fourier bands. Rematerialization and bf16 are on by default — at
M = 50k the encoder KV streams dominate HBM, which is also where the Pallas
blockwise-KV kernel and the ``--sp`` sequence-parallel mesh axis pay off.

Data comes from a standard ImageFolder tree (``<root>/imagenet/{train,val}/
<class>/*.JPEG``); ``--synthetic`` runs on generated data (zero-egress box).
"""

from __future__ import annotations

import argparse
from typing import Optional, Sequence

import jax

from perceiver_io_tpu.cli import common
from perceiver_io_tpu.data.imagefolder import ImageFolderDataModule
from perceiver_io_tpu.training import TrainState, make_classifier_steps
from perceiver_io_tpu.training.trainer import Trainer


def build_parser() -> argparse.ArgumentParser:
    parser = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    common.add_trainer_args(parser)
    common.add_mesh_args(parser)
    common.add_compute_args(parser)
    common.add_model_args(parser)
    common.add_optimizer_args(parser)
    g = parser.add_argument_group("data (ImageFolder)")
    g.add_argument("--root", default=".cache")
    g.add_argument("--dataset_name", default="imagenet",
                   help="subdirectory of --root holding the train/val tree")
    g.add_argument("--image_size", type=int, default=224)
    g.add_argument("--batch_size", type=int, default=64,
                   help="GLOBAL batch; the default is sized for a v5e-8 "
                        "(8/chip under dp). One v5e chip fits batch 8 at "
                        "224² (batch 64 OOMs its 16 GB HBM); batch scaling "
                        "is flat b8-b32 anyway — the step is compute-bound "
                        "(PERF.md)")
    g.add_argument("--num_workers", type=int, default=8,
                   help="JPEG-decode threads per host")
    g.add_argument("--synthetic", action="store_true")
    g.add_argument("--synthetic_size", type=int, default=4096)
    g.add_argument("--synthetic_classes", type=int, default=10)
    t = parser.add_argument_group("task (ImageNet classification)")
    t.add_argument("--num_frequency_bands", type=int, default=64)
    t.add_argument("--no_remat", action="store_true",
                   help="disable the remat-by-default applied at image_size ≥ 64")
    # Perceiver-paper ImageNet defaults (BASELINE.md tracked config)
    parser.set_defaults(experiment="imagenet", num_latents=512,
                        num_latent_channels=1024, num_encoder_layers=6,
                        num_self_attention_layers_per_block=6,
                        num_cross_attention_heads=1,
                        num_self_attention_heads=8,
                        weight_decay=1e-1, optimizer="AdamW",
                        learning_rate=4e-3)
    return parser


def main(argv: Optional[Sequence[str]] = None):
    args = common.parse_with_resume(build_parser(), argv)
    if common.maybe_spawn_hosts(args, argv):
        return None  # training ran in the spawned processes
    common.maybe_initialize_distributed(args)
    # remat is the sane default at M = image_size² (opt out via --no_remat)
    if args.image_size >= 64 and not args.no_remat:
        args.remat = True

    data = ImageFolderDataModule(
        root=args.root,
        name=args.dataset_name,
        image_size=args.image_size,
        batch_size=args.batch_size,
        synthetic=args.synthetic,
        synthetic_size=args.synthetic_size,
        synthetic_classes=args.synthetic_classes,
        num_workers=args.num_workers,
        seed=args.seed,
        shard_id=jax.process_index(),
        num_shards=jax.process_count(),
    )
    data.prepare_data()
    data.setup()

    model = common.build_image_classifier(
        args, data.dims, data.num_classes,
        num_frequency_bands=args.num_frequency_bands,
    )
    example = next(iter(data.val_dataloader()))
    variables = model.init(
        {"params": jax.random.key(args.seed)}, example["image"][:1]
    )
    tx, schedule = common.optimizer_from_args(args)
    state = TrainState.create(variables["params"], tx, jax.random.key(args.seed + 2))
    state, resume_dir = common.resume_state(args, state)

    train_step, eval_step = make_classifier_steps(model, schedule, input_kind="image")
    mesh = common.mesh_from_args(args)

    trainer = Trainer(
        train_step,
        lambda s, b, k: eval_step(s, b),
        state,
        common.trainer_config(args),
        example_batch={k: example[k] for k in ("image", "label")},
        mesh=mesh,
        shard_seq=args.shard_seq,
        zero_opt=args.zero_opt,
        hparams=vars(args),
        run_dir=resume_dir,
    )
    with trainer:
        common.run_fit(trainer, data.train_dataloader(), data.val_dataloader())
    return trainer.run_dir


if __name__ == "__main__":
    main()
