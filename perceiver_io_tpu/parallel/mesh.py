"""Device-mesh construction — the framework's distributed-communication layer.

The reference distributes exclusively through Lightning's DDP plugin over NCCL
(reference ``train_mlm.py:68``, ``train_seq_clf.py:30``, ``train_img_clf.py:19``);
here distribution is a single SPMD program over one ``jax.sharding.Mesh``:
gradient synchronization, sequence-parallel softmax reductions and
tensor-parallel activation exchanges all become XLA collectives riding ICI
(intra-slice) / DCN (inter-slice) — there is no user-facing communication API,
only mesh + sharding construction.

Axes:

- ``data``  — batch-dim sharding (the DDP replacement; grads psum over this axis),
- ``model`` — tensor parallelism (attention heads / MLP width / vocab dims),
- ``seq``   — sequence/context parallelism for long inputs M: the encoder's
  cross-attention KV stream is sharded over this axis while the small latent
  array stays replicated, so the softmax over M runs as partial reductions +
  psum — Perceiver's architectural alternative to ring attention (SURVEY.md §5).

Multi-host: call ``initialize_distributed()`` once per process before mesh
construction; ``jax.devices()`` then spans all hosts and every host feeds its
own data shard (``data/pipeline.py`` shard_id/num_shards).
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import numpy as np
from jax.experimental import mesh_utils
from jax.sharding import Mesh

AXIS_DATA = "data"
AXIS_MODEL = "model"
AXIS_SEQ = "seq"

MESH_AXES = (AXIS_DATA, AXIS_MODEL, AXIS_SEQ)


def initialize_distributed(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
) -> None:
    """Multi-host bring-up: ``jax.distributed.initialize`` (auto-detected on
    TPU pods; explicit coordinator for manual launches). Safe to skip on a
    single host."""
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
    )


def make_mesh(
    dp: Optional[int] = None,
    tp: int = 1,
    sp: int = 1,
    devices: Optional[Sequence[jax.Device]] = None,
) -> Mesh:
    """A (data, model, seq) mesh over the given (default: all) devices.

    ``dp`` defaults to ``n_devices // (tp * sp)``. On TPU,
    ``mesh_utils.create_device_mesh`` lays the axes out so that the
    highest-traffic axis rides ICI neighbours.
    """
    devices = list(devices if devices is not None else jax.devices())
    n = len(devices)
    if tp < 1 or sp < 1:
        raise ValueError(f"tp and sp must be >= 1, got tp={tp} sp={sp}")
    if dp is None:
        if n % (tp * sp) != 0:
            raise ValueError(f"{n} devices not divisible by tp*sp = {tp * sp}")
        dp = n // (tp * sp)
    if dp * tp * sp != n:
        raise ValueError(f"dp*tp*sp = {dp * tp * sp} != {n} devices")

    if all(d.platform == "cpu" for d in devices):
        # host-platform (virtual-device) meshes have no physical topology —
        # row-major assignment is exact, and create_device_mesh can reject
        # shapes it cannot factor against fake topologies
        try:
            device_grid = mesh_utils.create_device_mesh((dp, tp, sp), devices=devices)
        except Exception:
            device_grid = np.asarray(devices).reshape(dp, tp, sp)
    else:
        # on real accelerators a failure here is a genuine topology error:
        # surface it rather than silently degrading ICI locality
        device_grid = mesh_utils.create_device_mesh((dp, tp, sp), devices=devices)
    return Mesh(device_grid, MESH_AXES)
