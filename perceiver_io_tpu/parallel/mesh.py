"""Device-mesh construction — the framework's distributed-communication layer.

The reference distributes exclusively through Lightning's DDP plugin over NCCL
(reference ``train_mlm.py:68``, ``train_seq_clf.py:30``, ``train_img_clf.py:19``);
here distribution is a single SPMD program over one ``jax.sharding.Mesh``:
gradient synchronization, sequence-parallel softmax reductions and
tensor-parallel activation exchanges all become XLA collectives riding ICI
(intra-slice) / DCN (inter-slice) — there is no user-facing communication API,
only mesh + sharding construction.

Axes:

- ``data``  — batch-dim sharding (the DDP replacement; grads psum over this axis),
- ``model`` — tensor parallelism (attention heads / MLP width / vocab dims),
- ``seq``   — sequence/context parallelism for long inputs M: the encoder's
  cross-attention KV stream is sharded over this axis while the small latent
  array stays replicated, so the softmax over M runs as partial reductions +
  psum — Perceiver's architectural alternative to ring attention (SURVEY.md §5).

Multi-host: call ``initialize_distributed()`` once per process before mesh
construction; ``jax.devices()`` then spans all hosts and every host feeds its
own data shard (``data/pipeline.py`` shard_id/num_shards).
"""

from __future__ import annotations

import contextlib
import contextvars
import dataclasses
from typing import Optional, Sequence

import jax
import numpy as np
from jax.experimental import mesh_utils
from jax.sharding import Mesh

AXIS_DATA = "data"
AXIS_MODEL = "model"
AXIS_SEQ = "seq"

MESH_AXES = (AXIS_DATA, AXIS_MODEL, AXIS_SEQ)


@dataclasses.dataclass(frozen=True)
class SequenceParallelContext:
    """An active sequence-parallel regime: which mesh axis carries KV shards.

    Attention layers whose KV stream is the seq-sharded input (the encoder
    cross-attention — ``seq_shard_kv=True`` in ``ops.attention``) read this at
    trace time to route the kernel path through
    ``seq_parallel_fused_attention`` instead of letting GSPMD all-gather the
    KV stream around the ``pallas_call`` (the failure mode documented on that
    op: under plain jit the O(S/n) memory benefit of sharding M is lost
    exactly where it matters).
    """

    mesh: Mesh
    axis: str = AXIS_SEQ
    batch_axis: Optional[str] = AXIS_DATA
    # head (tensor-parallel) axis: attention passes it through when the head
    # count divides the axis size, so tp meshes keep heads sharded inside the
    # shard_map instead of all-gathering them
    head_axis: Optional[str] = AXIS_MODEL


_ACTIVE_SP: contextvars.ContextVar[Optional[SequenceParallelContext]] = (
    contextvars.ContextVar("perceiver_io_tpu_sequence_parallel", default=None)
)


@contextlib.contextmanager
def sequence_parallel_context(
    mesh: Mesh, axis: str = AXIS_SEQ, batch_axis: Optional[str] = AXIS_DATA
):
    """Activate sequence-parallel kernel routing while tracing a step.

    ``make_sharded_train_step(shard_seq=True)`` (and the Trainer, for its eval
    step) wrap the step function with this, so any retrace — first call,
    new shapes, scanned multi-step dispatch — sees the regime. A mesh whose
    ``axis`` has size 1 deactivates routing (nothing to shard)."""
    if mesh.shape.get(axis, 1) <= 1:
        yield
        return
    token = _ACTIVE_SP.set(SequenceParallelContext(mesh, axis, batch_axis))
    try:
        yield
    finally:
        _ACTIVE_SP.reset(token)


def active_sequence_parallel() -> Optional[SequenceParallelContext]:
    """The active :class:`SequenceParallelContext`, or None."""
    return _ACTIVE_SP.get()


def initialize_distributed(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
) -> None:
    """Multi-host bring-up: ``jax.distributed.initialize`` (auto-detected on
    TPU pods; explicit coordinator for manual launches). Safe to skip on a
    single host."""
    import os

    if os.environ.get("JAX_PLATFORMS", "").strip().lower() == "cpu":
        # CPU-backend multi-process collectives need an implementation
        # selected (newer jax defaults to gloo; this build defaults to
        # 'none', where any cross-process psum raises "Multiprocess
        # computations aren't implemented"). Pre-init only — harmless if
        # this jax has no such knob.
        try:
            jax.config.update("jax_cpu_collectives_implementation", "gloo")
        except Exception:
            pass
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
    )


def _inner_device_grid(
    devices: Sequence[jax.Device], dp: int, tp: int, sp: int
) -> np.ndarray:
    """(dp, tp, sp) grid over devices that share one fast (ICI) network."""
    if all(d.platform == "cpu" for d in devices):
        # host-platform (virtual-device) meshes have no physical topology —
        # row-major assignment is exact, and create_device_mesh can reject
        # shapes it cannot factor against fake topologies
        try:
            return mesh_utils.create_device_mesh((dp, tp, sp), devices=devices)
        except Exception:
            return np.asarray(devices).reshape(dp, tp, sp)
    # on real accelerators a failure here is a genuine topology error:
    # surface it rather than silently degrading ICI locality
    return mesh_utils.create_device_mesh((dp, tp, sp), devices=devices)


def _hybrid_device_grid(
    devices: Sequence[jax.Device], dcn_dp: int, inner_dp: int, tp: int, sp: int
) -> np.ndarray:
    """(dcn_dp·inner_dp, tp, sp) grid, DCN-major on the first axis.

    Delegates granule discovery, evenness validation and topology-aware
    placement to ``mesh_utils.create_hybrid_device_mesh`` — slice granules
    first (multi-slice pods), then process granules (multi-host CPU /
    hosts-as-granules deployments). When neither yields ``dcn_dp`` granules,
    a SINGLE-process CPU device set falls back to contiguous chunking (so the
    layout is testable on virtual devices); real accelerators — and CPU
    devices spanning processes, where chunks could straddle host boundaries —
    surface the topology error.
    """
    errors = []
    for kwargs in ({}, {"process_is_granule": True}):
        try:
            return mesh_utils.create_hybrid_device_mesh(
                (inner_dp, tp, sp), (dcn_dp, 1, 1), devices=devices, **kwargs
            )
        except (ValueError, AssertionError) as e:
            errors.append(str(e))
    if (all(d.platform == "cpu" for d in devices)
            and len({d.process_index for d in devices}) == 1):
        per = len(devices) // dcn_dp
        return np.concatenate(
            [
                _inner_device_grid(devices[i * per:(i + 1) * per], inner_dp, tp, sp)
                for i in range(dcn_dp)
            ],
            axis=0,
        )
    raise ValueError(
        f"no slice/process granule split of {len(devices)} devices matches "
        f"dcn_dp={dcn_dp}: {errors}"
    )


def make_mesh(
    dp: Optional[int] = None,
    tp: int = 1,
    sp: int = 1,
    devices: Optional[Sequence[jax.Device]] = None,
    dcn_dp: int = 1,
) -> Mesh:
    """A (data, model, seq) mesh over the given (default: all) devices.

    ``dp`` defaults to ``n_devices // (tp * sp)``. On TPU,
    ``mesh_utils.create_device_mesh`` lays the axes out so that the
    highest-traffic axis rides ICI neighbours.

    ``dcn_dp`` > 1 builds a hybrid ICI×DCN layout for multi-slice / multi-host
    deployments: the ``data`` axis is laid out DCN-major, so its outer
    ``dcn_dp`` factor crosses slice (or host) boundaries while the inner
    ``dp // dcn_dp`` factor and the whole ``model``/``seq`` axes stay inside
    one slice's ICI. The logical mesh is unchanged — same three axis names,
    same shape ``(dp, tp, sp)`` — so every sharding rule, the ZeRO partition
    and the sequence-parallel kernel route apply as-is; only the device
    placement (and therefore which hops each collective rides) differs. This
    is the standard hybrid recipe: gradient psum over ``data`` becomes a
    hierarchical reduce (ICI within the slice, one DCN exchange across), and
    the latency-sensitive tensor/sequence collectives never touch DCN.
    """
    devices = list(devices if devices is not None else jax.devices())
    n = len(devices)
    if tp < 1 or sp < 1 or dcn_dp < 1:
        raise ValueError(
            f"tp, sp and dcn_dp must be >= 1, got tp={tp} sp={sp} dcn_dp={dcn_dp}"
        )
    if dp is None:
        if n % (tp * sp) != 0:
            raise ValueError(f"{n} devices not divisible by tp*sp = {tp * sp}")
        dp = n // (tp * sp)
    if dp * tp * sp != n:
        raise ValueError(f"dp*tp*sp = {dp * tp * sp} != {n} devices")

    if dcn_dp == 1:
        return Mesh(_inner_device_grid(devices, dp, tp, sp), MESH_AXES)

    if dp % dcn_dp != 0:
        raise ValueError(
            f"dcn_dp={dcn_dp} must divide the data-parallel size dp={dp} "
            f"(the DCN factor is the outer part of the data axis)"
        )
    inner_dp = dp // dcn_dp
    device_grid = _hybrid_device_grid(devices, dcn_dp, inner_dp, tp, sp)
    return Mesh(device_grid, MESH_AXES)
