"""Device-mesh construction — the framework's distributed-communication layer.

The reference distributes exclusively through Lightning's DDP plugin over NCCL
(reference ``train_mlm.py:68``, ``train_seq_clf.py:30``, ``train_img_clf.py:19``);
here distribution is a single SPMD program over one ``jax.sharding.Mesh``:
gradient synchronization, sequence-parallel softmax reductions and
tensor-parallel activation exchanges all become XLA collectives riding ICI
(intra-slice) / DCN (inter-slice) — there is no user-facing communication API,
only mesh + sharding construction.

Axes:

- ``data``  — batch-dim sharding (the DDP replacement; grads psum over this axis),
- ``model`` — tensor parallelism (attention heads / MLP width / vocab dims),
- ``seq``   — sequence/context parallelism for long inputs M: the encoder's
  cross-attention KV stream is sharded over this axis while the small latent
  array stays replicated, so the softmax over M runs as partial reductions +
  psum — Perceiver's architectural alternative to ring attention (SURVEY.md §5).

Multi-host: call ``initialize_distributed()`` once per process before mesh
construction; ``jax.devices()`` then spans all hosts and every host feeds its
own data shard (``data/pipeline.py`` shard_id/num_shards).
"""

from __future__ import annotations

import contextlib
import contextvars
import dataclasses
from typing import Optional, Sequence, Tuple

import jax
import numpy as np
from jax.experimental import mesh_utils
from jax.sharding import Mesh

AXIS_DATA = "data"
AXIS_MODEL = "model"
AXIS_SEQ = "seq"

MESH_AXES = (AXIS_DATA, AXIS_MODEL, AXIS_SEQ)


@dataclasses.dataclass(frozen=True)
class SequenceParallelContext:
    """An active sequence-parallel regime: which mesh axis carries KV shards.

    Attention layers whose KV stream is the seq-sharded input (the encoder
    cross-attention — ``seq_shard_kv=True`` in ``ops.attention``) read this at
    trace time to route the kernel path through
    ``seq_parallel_fused_attention`` instead of letting GSPMD all-gather the
    KV stream around the ``pallas_call`` (the failure mode documented on that
    op: under plain jit the O(S/n) memory benefit of sharding M is lost
    exactly where it matters).
    """

    mesh: Mesh
    axis: str = AXIS_SEQ
    batch_axis: Optional[str] = AXIS_DATA
    # head (tensor-parallel) axis: attention passes it through when the head
    # count divides the axis size, so tp meshes keep heads sharded inside the
    # shard_map instead of all-gathering them
    head_axis: Optional[str] = AXIS_MODEL


_ACTIVE_SP: contextvars.ContextVar[Optional[SequenceParallelContext]] = (
    contextvars.ContextVar("perceiver_io_tpu_sequence_parallel", default=None)
)


@contextlib.contextmanager
def sequence_parallel_context(
    mesh: Mesh, axis: str = AXIS_SEQ, batch_axis: Optional[str] = AXIS_DATA
):
    """Activate sequence-parallel kernel routing while tracing a step.

    ``make_sharded_train_step(shard_seq=True)`` (and the Trainer, for its eval
    step) wrap the step function with this, so any retrace — first call,
    new shapes, scanned multi-step dispatch — sees the regime. A mesh whose
    ``axis`` has size 1 deactivates routing (nothing to shard)."""
    if mesh.shape.get(axis, 1) <= 1:
        yield
        return
    token = _ACTIVE_SP.set(SequenceParallelContext(mesh, axis, batch_axis))
    try:
        yield
    finally:
        _ACTIVE_SP.reset(token)


def active_sequence_parallel() -> Optional[SequenceParallelContext]:
    """The active :class:`SequenceParallelContext`, or None."""
    return _ACTIVE_SP.get()


def initialize_distributed(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
) -> None:
    """Multi-host bring-up: ``jax.distributed.initialize`` (auto-detected on
    TPU pods; explicit coordinator for manual launches). Safe to skip on a
    single host."""
    import os

    if os.environ.get("JAX_PLATFORMS", "").strip().lower() == "cpu":
        # CPU-backend multi-process collectives need an implementation
        # selected (newer jax defaults to gloo; this build defaults to
        # 'none', where any cross-process psum raises "Multiprocess
        # computations aren't implemented"). Pre-init only — harmless if
        # this jax has no such knob.
        try:
            jax.config.update("jax_cpu_collectives_implementation", "gloo")
        except Exception:
            pass
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
    )


@dataclasses.dataclass(frozen=True)
class WorldDescriptor:
    """A re-initializable view of the multi-host world (elastic training).

    ``ranks`` are the COORDINATION node ids of the member processes — the id
    each process registered with on the coordinator, fixed for the process's
    lifetime even as the world shrinks and grows around it. What jax sees is
    the DENSE per-generation view: ``process_id = ranks.index(node_id)`` and
    ``num_processes = len(ranks)``. Keeping the two spaces separate is what
    lets a generation-2 world of survivors ``(0, 1, 3)`` present itself to
    jax as a clean 3-process job while KV-store rendezvous keys, heartbeat
    namespaces and buddy assignments keep using the stable node ids.

    ``generation`` increments on every resize (shrink, grow, or a retried
    resize after a mid-resize death) and namespaces all rendezvous state, so
    a straggler from generation N can never consume generation N+1's keys.
    """

    generation: int
    ranks: Tuple[int, ...]
    node_id: int

    def __post_init__(self):
        object.__setattr__(self, "ranks", tuple(sorted(set(self.ranks))))
        if self.node_id not in self.ranks:
            raise ValueError(
                f"node_id {self.node_id} not a member of ranks {self.ranks}")

    @property
    def process_id(self) -> int:
        return self.ranks.index(self.node_id)

    @property
    def num_processes(self) -> int:
        return len(self.ranks)

    @property
    def leader(self) -> int:
        """The node id that performs leader-only rendezvous work (PJRT key
        cleanup, invite/state publication): the lowest surviving id."""
        return self.ranks[0]

    def buddy_of(self, node_id: int) -> int:
        """The ring buddy that mirrors ``node_id``'s state shard: the next
        member id (wrapping), so every member has exactly one buddy and one
        protégé and a single death never takes a shard AND its mirror."""
        i = self.ranks.index(node_id)
        return self.ranks[(i + 1) % len(self.ranks)]

    def shrink(self, dead) -> "WorldDescriptor":
        """The next generation without ``dead`` (an id or iterable of ids)."""
        gone = {dead} if isinstance(dead, int) else set(dead)
        survivors = tuple(r for r in self.ranks if r not in gone)
        return WorldDescriptor(self.generation + 1, survivors, self.node_id)

    def grow(self, new_ids) -> "WorldDescriptor":
        """The next generation with ``new_ids`` joined (spare/hot join)."""
        joined = {new_ids} if isinstance(new_ids, int) else set(new_ids)
        return WorldDescriptor(
            self.generation + 1, self.ranks + tuple(joined), self.node_id)

    def make_mesh(self, tp: int = 1, sp: int = 1, dcn_dp: int = 1) -> Mesh:
        """The generation's mesh over the CURRENT global device set (call
        after :func:`adopt_world` + backend bring-up)."""
        return make_mesh(tp=tp, sp=sp, dcn_dp=dcn_dp)


def reset_backend() -> None:
    """Demolish the live jax backend so a NEW world can be built in-process.

    The elastic-resize primitive: drops the backend registry, every jit
    cache, and the global mesh cache, so the next ``jax.devices()`` call
    re-runs distributed CPU bring-up against whatever
    ``jax._src.distributed.global_state`` then says (see
    :func:`adopt_world`). The old PJRT client itself is NOT freed — live
    jitted functions and arrays keep it referenced indefinitely — which is
    why the elastic runtime pairs this with socket fencing
    (``resilience/elastic.py``) instead of waiting for a destructor that
    never runs.
    """
    import gc

    from jax._src import mesh as mesh_lib
    from jax._src import xla_bridge

    xla_bridge._clear_backends()
    jax.clear_caches()
    mesh_lib._mesh_object_dict.clear()
    gc.collect()


def adopt_world(descriptor: WorldDescriptor) -> None:
    """Point jax's distributed global state at the descriptor's dense view.

    The next backend bring-up (first ``jax.devices()`` after
    :func:`reset_backend`) then constructs an ``N = num_processes`` world:
    CPU topology exchange and gloo ring re-run over the coordinator KV store
    exactly as at process start, just with fewer (or more) participants.
    """
    from jax._src import distributed

    state = distributed.global_state
    state.process_id = descriptor.process_id
    state.num_processes = descriptor.num_processes


def _inner_device_grid(
    devices: Sequence[jax.Device], dp: int, tp: int, sp: int
) -> np.ndarray:
    """(dp, tp, sp) grid over devices that share one fast (ICI) network."""
    if all(d.platform == "cpu" for d in devices):
        # host-platform (virtual-device) meshes have no physical topology —
        # row-major assignment is exact, and create_device_mesh can reject
        # shapes it cannot factor against fake topologies
        try:
            return mesh_utils.create_device_mesh((dp, tp, sp), devices=devices)
        except Exception:
            return np.asarray(devices).reshape(dp, tp, sp)
    # on real accelerators a failure here is a genuine topology error:
    # surface it rather than silently degrading ICI locality
    return mesh_utils.create_device_mesh((dp, tp, sp), devices=devices)


def _hybrid_device_grid(
    devices: Sequence[jax.Device], dcn_dp: int, inner_dp: int, tp: int, sp: int
) -> np.ndarray:
    """(dcn_dp·inner_dp, tp, sp) grid, DCN-major on the first axis.

    Delegates granule discovery, evenness validation and topology-aware
    placement to ``mesh_utils.create_hybrid_device_mesh`` — slice granules
    first (multi-slice pods), then process granules (multi-host CPU /
    hosts-as-granules deployments). When neither yields ``dcn_dp`` granules,
    a SINGLE-process CPU device set falls back to contiguous chunking (so the
    layout is testable on virtual devices); real accelerators — and CPU
    devices spanning processes, where chunks could straddle host boundaries —
    surface the topology error.
    """
    errors = []
    for kwargs in ({}, {"process_is_granule": True}):
        try:
            return mesh_utils.create_hybrid_device_mesh(
                (inner_dp, tp, sp), (dcn_dp, 1, 1), devices=devices, **kwargs
            )
        except (ValueError, AssertionError) as e:
            errors.append(str(e))
    if (all(d.platform == "cpu" for d in devices)
            and len({d.process_index for d in devices}) == 1):
        per = len(devices) // dcn_dp
        return np.concatenate(
            [
                _inner_device_grid(devices[i * per:(i + 1) * per], inner_dp, tp, sp)
                for i in range(dcn_dp)
            ],
            axis=0,
        )
    raise ValueError(
        f"no slice/process granule split of {len(devices)} devices matches "
        f"dcn_dp={dcn_dp}: {errors}"
    )


def make_mesh(
    dp: Optional[int] = None,
    tp: int = 1,
    sp: int = 1,
    devices: Optional[Sequence[jax.Device]] = None,
    dcn_dp: int = 1,
) -> Mesh:
    """A (data, model, seq) mesh over the given (default: all) devices.

    ``dp`` defaults to ``n_devices // (tp * sp)``. On TPU,
    ``mesh_utils.create_device_mesh`` lays the axes out so that the
    highest-traffic axis rides ICI neighbours.

    ``dcn_dp`` > 1 builds a hybrid ICI×DCN layout for multi-slice / multi-host
    deployments: the ``data`` axis is laid out DCN-major, so its outer
    ``dcn_dp`` factor crosses slice (or host) boundaries while the inner
    ``dp // dcn_dp`` factor and the whole ``model``/``seq`` axes stay inside
    one slice's ICI. The logical mesh is unchanged — same three axis names,
    same shape ``(dp, tp, sp)`` — so every sharding rule, the ZeRO partition
    and the sequence-parallel kernel route apply as-is; only the device
    placement (and therefore which hops each collective rides) differs. This
    is the standard hybrid recipe: gradient psum over ``data`` becomes a
    hierarchical reduce (ICI within the slice, one DCN exchange across), and
    the latency-sensitive tensor/sequence collectives never touch DCN.
    """
    devices = list(devices if devices is not None else jax.devices())
    n = len(devices)
    if tp < 1 or sp < 1 or dcn_dp < 1:
        raise ValueError(
            f"tp, sp and dcn_dp must be >= 1, got tp={tp} sp={sp} dcn_dp={dcn_dp}"
        )
    if dp is None:
        if n % (tp * sp) != 0:
            raise ValueError(f"{n} devices not divisible by tp*sp = {tp * sp}")
        dp = n // (tp * sp)
    if dp * tp * sp != n:
        raise ValueError(f"dp*tp*sp = {dp * tp * sp} != {n} devices")

    if dcn_dp == 1:
        return Mesh(_inner_device_grid(devices, dp, tp, sp), MESH_AXES)

    if dp % dcn_dp != 0:
        raise ValueError(
            f"dcn_dp={dcn_dp} must divide the data-parallel size dp={dp} "
            f"(the DCN factor is the outer part of the data axis)"
        )
    inner_dp = dp // dcn_dp
    device_grid = _hybrid_device_grid(devices, dcn_dp, inner_dp, tp, sp)
    return Mesh(device_grid, MESH_AXES)
