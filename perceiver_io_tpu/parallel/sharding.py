"""Sharding rules and the pjit-ed train step — the DDP replacement.

Recipe (the scaling-book flow): pick a mesh, annotate the params/opt-state and
batch shardings once, ``jax.jit`` the existing pure step function with those
shardings, and let XLA's SPMD partitioner insert the collectives (grad psum
over ``data``, all-gather/reduce-scatter for ``model``-sharded tensors,
softmax-stat psum over ``seq``-sharded attention).

Parameter rules are path-regex → PartitionSpec, applied to any params-shaped
tree — optimizer states (Adam's mu/nu mirror the param tree paths) pick up the
same specs automatically, which keeps ZeRO-style optimizer-state sharding one
rule-table away.

Tensor-parallel layout (Megatron-style pairing, per attention/MLP block):

- q/k/v projection kernels: output (head) dim over ``model`` → attention runs
  head-parallel; out-projection input dim over ``model`` closes the pair with
  one psum.
- MLP: dense_1 output and dense_2 input over ``model``.
- vocab-sized output projection (``linear/kernel``) over ``model`` — the
  (B, 512, vocab) MLM logits, the memory hot spot (SURVEY.md §3.1), never
  materialize unsharded; the CE softmax reduces over the sharded axis in-place.
"""

from __future__ import annotations

import re
from typing import Any, Dict, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from perceiver_io_tpu.parallel.mesh import (
    AXIS_DATA,
    AXIS_MODEL,
    AXIS_SEQ,
    sequence_parallel_context,
)


# The bare-name "/"-joined path rendering the PARAM_RULES regexes match
# against — ONE definition shared with perceiver_io_tpu.quant (its scale
# map is keyed by the same rendering; see utils/treepath.py).
from perceiver_io_tpu.utils.treepath import simple_keystr as _simple_keystr

# (path regex, spec). First match wins; default is fully replicated.
PARAM_RULES: Sequence[Tuple[str, P]] = (
    (r"(q_proj|k_proj|v_proj)/kernel$", P(None, AXIS_MODEL)),
    (r"(q_proj|k_proj|v_proj)/bias$", P(AXIS_MODEL)),
    (r"out_proj/kernel$", P(AXIS_MODEL, None)),
    (r"dense_1/kernel$", P(None, AXIS_MODEL)),
    (r"dense_1/bias$", P(AXIS_MODEL)),
    (r"dense_2/kernel$", P(AXIS_MODEL, None)),
    (r"linear/kernel$", P(None, AXIS_MODEL)),
    (r"linear/bias$", P(AXIS_MODEL)),
)


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def _spec_fits(spec: P, shape: Tuple[int, ...], mesh: Mesh) -> bool:
    """A spec is usable when every named axis divides its dimension.

    (XLA supports uneven sharding via padding, but for parameters we prefer
    clean replication over padded shards — e.g. a 10003-vocab projection on a
    tp=2 mesh stays replicated rather than padding every optimizer step.)
    """
    if len(spec) > len(shape):
        return False
    for dim, axis in zip(shape, spec):
        if axis is None:
            continue
        if dim % mesh.shape[axis] != 0:
            return False
    return True


def sharding_for_tree(tree: Any, mesh: Mesh, rules: Sequence[Tuple[str, P]] = PARAM_RULES):
    """NamedSharding tree for a params-shaped pytree by path-regex rules.

    Works on concrete arrays or ShapeDtypeStructs (use with ``jax.eval_shape``
    to plan shardings before allocating).
    """
    compiled = [(re.compile(pat), spec) for pat, spec in rules]

    def assign(path, leaf) -> NamedSharding:
        shape = getattr(leaf, "shape", ())
        name = _simple_keystr(path)
        for pat, spec in compiled:
            if pat.search(name):
                if _spec_fits(spec, shape, mesh):
                    return NamedSharding(mesh, spec)
                return replicated(mesh)
        return replicated(mesh)

    return jax.tree_util.tree_map_with_path(assign, tree)


def batch_pspecs(
    batch: Dict[str, Any], mesh: Mesh, shard_seq: bool = False,
    stacked: bool = False,
) -> Dict[str, P]:
    """PartitionSpecs for a batch dict: leading axis over ``data``, and
    optionally the sequence axis over ``seq`` — axis 1 for text tensors
    (token_ids/pad_mask) and for images/frames ('image': (B, H, W, C),
    'frames': (B, 2, H, W, C) → axis 2), whose first spatial axis maps
    contiguously onto the flattened input axis M = H·W the encoder consumes.

    Sequence sharding is the Perceiver sequence-parallel scheme: the encoder
    cross-attention KV stream (derived from these tensors) is sharded over
    ``seq`` while latents replicate — no ring required (SURVEY.md §5).

    ``stacked=True``: the batch leaves carry a leading scan axis of K
    per-step batches (multi-step dispatch, ``TrainerConfig
    .steps_per_dispatch``) — it stays unsharded and the usual specs apply
    one axis later.
    """
    seq_axis = AXIS_SEQ if shard_seq and mesh.shape[AXIS_SEQ] > 1 else None
    off = 1 if stacked else 0

    specs: Dict[str, P] = {}
    for key, value in batch.items():
        ndim = np.ndim(value) if not hasattr(value, "ndim") else value.ndim
        ndim -= off
        if key in ("token_ids", "pad_mask") and ndim >= 2:
            spec = (AXIS_DATA, seq_axis) + (None,) * (ndim - 2)
        elif key == "image" and ndim >= 3:
            spec = (AXIS_DATA, seq_axis) + (None,) * (ndim - 2)
        elif key == "frames" and ndim >= 4:
            spec = (AXIS_DATA, None, seq_axis) + (None,) * (ndim - 3)
        else:
            spec = (AXIS_DATA,) + (None,) * (ndim - 1)
        specs[key] = P(*(((None,) * off) + spec))
    return specs


def batch_shardings(
    batch: Dict[str, Any], mesh: Mesh, shard_seq: bool = False,
    stacked: bool = False,
):
    return {
        k: NamedSharding(mesh, spec)
        for k, spec in batch_pspecs(batch, mesh, shard_seq, stacked).items()
    }


# width of the coordination bitmask carried by the coord_flags channel
# (bit 0: preemption — training/trainer.py _PREEMPT_BIT; room to grow)
_COORD_FLAG_BITS = 8


def coord_flags_sharding(mesh: Mesh) -> NamedSharding:
    """Sharding of the ``(num_devices,)`` int32 coordination-flags vector —
    the multi-host agreement channel (``make_sharded_train_step(coord_flags=
    True)``): one element per device, every element of a host's shard
    holding that host's local flag bitmask. A host builds its slice with
    ``jax.make_array_from_process_local_data`` (all-equal values, so the
    device-order permutation inside the shard is irrelevant), and the step
    reduces the vector on device — the same all-reduce a ``psum`` would
    lower to — so the agreed value comes back replicated and bit-identical
    on every host, riding the training dispatch itself (no extra host
    round-trip, no side channel that could observe a different step)."""
    return NamedSharding(mesh, P(tuple(mesh.axis_names)))


def _with_data_axis(spec: P, shape: Tuple[int, ...], mesh: Mesh) -> P:
    """Add ``data`` over the first free, divisible dimension of ``spec``."""
    dp = mesh.shape[AXIS_DATA]
    if dp <= 1:
        return spec
    entries = list(spec) + [None] * (len(shape) - len(spec))
    for i, (dim, axis) in enumerate(zip(shape, entries)):
        if axis is None and dim % dp == 0:
            entries[i] = AXIS_DATA
            return P(*entries)
    return spec


def zero_state_shardings(state, mesh: Mesh, rules=PARAM_RULES,
                         params_too: bool = False):
    """ZeRO-style sharding plan: params follow the rules; OPTIMIZER-STATE
    leaves additionally shard over ``data``.

    SURVEY.md §2.3's "optimizer-state sharding on the data axis": Adam's
    mu/nu (2x the param bytes in f32) are pure per-parameter state, so each
    data-parallel rank can own a 1/dp slice — the per-chip optimizer
    footprint drops by dp, at the cost of one XLA-inserted all-gather of the
    (sharded) updates per step. With ``params_too=False`` params stay
    replicated (ZeRO-1/2 flavor): the forward/backward are untouched.

    ``params_too=True`` is the ZeRO-3/FSDP flavor: the PARAMS shard over
    ``data`` as well (on top of any ``model``-axis rule sharding). Nothing
    else changes — under ``jit`` GSPMD sees data-sharded parameter inputs
    feeding unsharded compute and inserts the all-gather-on-use in the
    forward/backward and the reduce-scatter on the gradients itself (the
    scaling-book recipe: FSDP is a sharding annotation, not an algorithm).
    Per-chip param+grad+opt residency drops by ~dp; the price is per-step
    gather/scatter collectives over ICI.

    Each leaf keeps any ``model``-axis sharding its param rule implies, and
    ``data`` is added over the first free divisible dimension (leaves with
    no data-divisible free dimension stay as ruled — e.g. tiny biases).
    """
    shardings = sharding_for_tree(state, mesh, rules)

    def add_data(path, leaf, sharding):
        name = _simple_keystr(path)
        shape = getattr(leaf, "shape", ())
        wanted = "opt_state" in name or (params_too and name.startswith("params"))
        if not wanted or len(shape) == 0:
            return sharding
        if hasattr(leaf, "dtype") and jax.dtypes.issubdtype(
            leaf.dtype, jax.dtypes.prng_key
        ):
            return sharding
        return NamedSharding(mesh, _with_data_axis(sharding.spec, shape, mesh))

    return jax.tree_util.tree_map_with_path(add_data, state, shardings)


def _place_tree(tree: Any, shardings: Any):
    """Place host-resident values onto (possibly multi-process) shardings.

    Single-process: plain ``device_put``. Multi-process: ``device_put``
    rejects shardings spanning non-addressable devices, so each process
    materializes only its addressable shards via ``make_array_from_callback``
    — every host holds an identical full copy (the standard replicated-init
    contract), and the callback slices this host's pieces out of it. Typed
    PRNG-key leaves carry an extended dtype the callback path can't build
    directly; they round-trip through their uint32 key data.
    """
    if jax.process_count() == 1:
        return jax.device_put(tree, shardings)

    def place(x, s):
        if hasattr(x, "dtype") and jax.dtypes.issubdtype(x.dtype, jax.dtypes.prng_key):
            data = jax.random.key_data(x)
            placed = jax.make_array_from_callback(
                data.shape, s, lambda idx, d=np.asarray(data): d[idx]
            )
            return jax.random.wrap_key_data(placed, impl=jax.random.key_impl(x))
        arr = np.asarray(x)
        return jax.make_array_from_callback(arr.shape, s, lambda idx: arr[idx])

    return jax.tree.map(place, tree, shardings)


def shard_train_state(state, mesh: Mesh, rules=PARAM_RULES, zero_opt=False):
    """Place an existing TrainState onto the mesh per the rules.

    Params and optimizer state follow the same path rules (mu/nu mirror the
    param paths); scalars and rng keys replicate. ``zero_opt=True`` shards
    the optimizer state over ``data``; ``zero_opt='params'`` additionally
    shards the PARAMS over ``data`` (ZeRO-3/FSDP flavor — see
    :func:`zero_state_shardings`).
    """
    if zero_opt:
        if mesh.shape[AXIS_DATA] <= 1:
            import warnings

            warnings.warn(
                "zero_opt requested but the mesh has data=1 — optimizer-state "
                "sharding divides by the data-parallel size, so this is a "
                "no-op; increase dp to save memory",
                stacklevel=2,
            )
        shardings = zero_state_shardings(
            state, mesh, rules, params_too=zero_opt == "params"
        )
    else:
        shardings = sharding_for_tree(state, mesh, rules)
    return _place_tree(state, shardings), shardings


def reresolve_shardings(tree: Any, old_mesh: Mesh, new_mesh: Mesh,
                        rules=PARAM_RULES):
    """Re-resolve the path-regex rules against a NEW mesh (elastic resize).

    An elastic shrink/grow rebuilds the mesh with a different device count;
    the RULES are mesh-independent, so the plan for the new world is just
    :func:`sharding_for_tree` over the new mesh — but a spec that fit the
    old axis sizes can silently degrade to replication on the new ones
    (``_spec_fits``: e.g. a ``model``-sharded 6-wide head dim on tp=3 after
    a tp=2 generation). Degradation is LEGAL — the state stays correct,
    just bigger per chip — but an operator resizing a memory-tight job must
    hear about it, so this returns ``(shardings, degraded)`` where
    ``degraded`` lists the "/"-joined paths whose rule spec applied on
    ``old_mesh`` but falls back to replicated on ``new_mesh``.
    """
    compiled = [(re.compile(pat), spec) for pat, spec in rules]
    degraded = []

    def check(path, leaf):
        shape = getattr(leaf, "shape", ())
        name = _simple_keystr(path)
        for pat, spec in compiled:
            if pat.search(name):
                if (_spec_fits(spec, shape, old_mesh)
                        and not _spec_fits(spec, shape, new_mesh)):
                    degraded.append(name)
                return
        return

    jax.tree_util.tree_map_with_path(check, tree)
    return sharding_for_tree(tree, new_mesh, rules), sorted(degraded)


def sp_gradient_canary(mesh: Mesh, axis: str = AXIS_SEQ) -> None:
    """One tiny known-gradient probe through the sequence-parallel kernel.

    ``_sp_bwd`` (ops/pallas_attention.py) compensates for shard_map's
    check_rep=False transpose convention as observed on the pinned JAX
    version — an UNDOCUMENTED contract: a future JAX upgrade could change it
    silently, leaving the forward exact but every gradient scaled by the
    product of some mesh axis sizes. This probe turns that silent rescale
    into a loud failure at trainer setup: it differentiates a sum-of-squares
    loss through :func:`seq_parallel_fused_attention` on throwaway inputs
    and checks dq/dk/dv against the analytic XLA formula computed locally.
    Cost: one tiny shard_map compile (~seconds), once per
    ``make_sharded_train_step(shard_seq=True)``.
    """
    from perceiver_io_tpu.ops.pallas_attention import (
        seq_parallel_fused_attention,
    )

    if jax.process_count() > 1:
        # the probe runs eagerly with host-local arrays, which cannot feed a
        # shard_map over a non-fully-addressable (multi-host) mesh; the
        # convention it guards is per-JAX-build, not per-topology, so the
        # single-controller probe in CI / single-host runs is the coverage
        return
    cache_key = (tuple(sorted(mesh.shape.items())), axis, jax.default_backend())
    if cache_key in _SP_CANARY_OK:
        return
    n = int(mesh.shape[axis])
    b, t, s, h, d = 1, 8, 16 * n, 1, 8
    keys = jax.random.split(jax.random.key(1234), 3)
    q = jax.random.normal(keys[0], (b, t, h, d), jnp.float32)
    k = jax.random.normal(keys[1], (b, s, h, d), jnp.float32)
    v = jax.random.normal(keys[2], (b, s, h, d), jnp.float32)

    def ref_loss(q, k, v):
        logits = jnp.einsum("bthd,bshd->bhts", q * (d ** -0.5), k)
        probs = jax.nn.softmax(logits, axis=-1)
        return jnp.sum(jnp.einsum("bhts,bshd->bthd", probs, v) ** 2)

    def sp_loss(q, k, v):
        out = seq_parallel_fused_attention(q, k, v, mesh=mesh, axis=axis)
        return jnp.sum(out ** 2)

    ref = jax.grad(ref_loss, argnums=(0, 1, 2))(q, k, v)
    got = jax.grad(sp_loss, argnums=(0, 1, 2))(q, k, v)
    for name, r, g in zip(("dq", "dk", "dv"), ref, got):
        r, g = np.asarray(r), np.asarray(g)
        if not np.allclose(g, r, atol=1e-3, rtol=1e-3):
            denom = np.abs(r) + 1e-12
            ratio = float(np.median(np.abs(g) / denom))
            raise RuntimeError(
                f"sequence-parallel gradient canary FAILED on {name}: the "
                f"shard_map transpose convention _sp_bwd compensates for "
                f"(ops/pallas_attention.py) no longer matches this JAX "
                f"version — median |got|/|expected| = {ratio:.4g} on mesh "
                f"{dict(mesh.shape)}. Re-derive the psum scaling in _sp_bwd "
                f"before training under --shard_seq."
            )
    _SP_CANARY_OK.add(cache_key)


# meshes (by axis sizes + backend) whose canary already passed this process —
# the convention is a property of the JAX build, not of a particular Mesh
# object, so one probe per topology is enough
_SP_CANARY_OK: set = set()


def make_sharded_train_step(
    train_step,
    mesh: Mesh,
    state,
    example_batch: Dict[str, Any],
    rules=PARAM_RULES,
    shard_seq: bool = False,
    donate_state: bool = True,
    zero_opt=False,  # False | True (opt-state over data) | 'params' (ZeRO-3)
    stacked: bool = False,
    coord_flags: bool = False,
):
    """jit the pure ``(state, batch) → (state, metrics)`` step with explicit
    in/out shardings over the mesh. Returns ``(step_fn, sharded_state,
    batch_shardings)``.

    The example batch's keys define the step's input contract: loader batches
    may carry extra keys (e.g. ``label`` on an MLM batch) — the returned step
    selects only the contracted keys, so loader output feeds in directly.
    Batches can be host numpy (dispatch places them per the shardings) or
    pre-placed via ``jax.device_put(batch, batch_shardings)``.

    ``coord_flags=True`` grows the step a third input — the
    :func:`coord_flags_sharding` ``(num_devices,)`` int32 vector of per-host
    flag bitmasks — and a ``metrics['coord_flags']`` output scalar holding
    the fleet-wide OR (a bitwise-or reduce over the sharded vector, which GSPMD
    lowers to the cross-host all-reduce a psum would use). The trainer's
    multi-host preemption agreement rides this channel; the returned step
    then has signature ``(state, batch, flags)`` and exposes the flags
    sharding as ``step.coord_flags_sharding``.
    """
    keys = tuple(sorted(example_batch))
    sharded_state, state_shardings = shard_train_state(state, mesh, rules, zero_opt=zero_opt)
    b_shardings = batch_shardings(example_batch, mesh, shard_seq, stacked)

    if shard_seq and mesh.shape[AXIS_SEQ] > 1:
        # Runtime canary (VERDICT r3 item 6): fail loudly AT SETUP if a JAX
        # upgrade changed the shard_map transpose convention _sp_bwd encodes,
        # instead of training with silently rescaled gradients.
        sp_gradient_canary(mesh)
        # Activate sequence-parallel kernel routing for every (re)trace: the
        # encoder cross-attention (seq_shard_kv) then runs its Pallas path
        # under shard_map with S/n KV per device instead of letting GSPMD
        # all-gather the stream around the pallas_call.
        inner_step = train_step

        def train_step(state, batch):  # noqa: F811 — deliberate rebind
            with sequence_parallel_context(mesh):
                return inner_step(state, batch)

    if coord_flags:
        flags_sharding = coord_flags_sharding(mesh)
        base_step = train_step

        def coordinated(state, batch, flags):
            new_state, metrics = base_step(state, batch)
            metrics = dict(metrics)
            # fleet-wide OR of the per-host bitmasks, replicated everywhere.
            # A plain max would drop bits once two hosts raise DIFFERENT
            # bits, and XLA's cross-device reduce has no integer `or` — so
            # OR = per-bit any = per-bit MAX, recombined (8 flag bits).
            bit_positions = jnp.arange(_COORD_FLAG_BITS, dtype=jnp.int32)
            bits = (flags[:, None] >> bit_positions) & 1
            metrics["coord_flags"] = jnp.sum(
                jnp.max(bits, axis=0) << bit_positions, dtype=jnp.int32)
            return new_state, metrics

        jitted = jax.jit(
            coordinated,
            in_shardings=(state_shardings, b_shardings, flags_sharding),
            out_shardings=(state_shardings, None),
            donate_argnums=(0,) if donate_state else (),
        )

        def step(state, batch, flags):
            return jitted(state, {k: batch[k] for k in keys}, flags)

        step.coord_flags_sharding = flags_sharding
    else:
        jitted = jax.jit(
            train_step,
            in_shardings=(state_shardings, b_shardings),
            out_shardings=(state_shardings, None),
            donate_argnums=(0,) if donate_state else (),
        )

        def step(state, batch):
            return jitted(state, {k: batch[k] for k in keys})

        step.coord_flags_sharding = None

    # expose the underlying jit wrapper for lowering/cost-analysis reuse
    step.jitted = jitted
    return step, sharded_state, b_shardings
