from perceiver_io_tpu.parallel.mesh import (
    AXIS_DATA,
    AXIS_MODEL,
    AXIS_SEQ,
    SequenceParallelContext,
    active_sequence_parallel,
    make_mesh,
    initialize_distributed,
    sequence_parallel_context,
)
from perceiver_io_tpu.parallel.sharding import (
    PARAM_RULES,
    batch_pspecs,
    replicated,
    sharding_for_tree,
    shard_train_state,
    make_sharded_train_step,
    zero_state_shardings,
)

__all__ = [
    "AXIS_DATA",
    "AXIS_MODEL",
    "AXIS_SEQ",
    "SequenceParallelContext",
    "active_sequence_parallel",
    "make_mesh",
    "initialize_distributed",
    "sequence_parallel_context",
    "PARAM_RULES",
    "batch_pspecs",
    "replicated",
    "sharding_for_tree",
    "shard_train_state",
    "make_sharded_train_step",
    "zero_state_shardings",
]
