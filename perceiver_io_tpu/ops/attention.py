"""Attention primitives for the Perceiver core, built TPU-first on flax/XLA.

Semantics intentionally match the reference composition so that golden-parity
tests against a torch-assembled model pass bit-for-bit (up to float tolerance):

- ``MultiHeadAttention``: the behavior of ``torch.nn.MultiheadAttention`` with
  ``kdim=vdim=num_kv_channels, batch_first=True`` (reference
  ``perceiver/model.py:59-74``): separate q/k/v projections (with bias),
  1/sqrt(head_dim) scaling, ``key_padding_mask`` (True = ignore), dropout on
  attention probabilities, and an output projection.
- ``CrossAttention``: pre-LN on both query and kv streams, embedding dim = query
  channels (reference ``perceiver/model.py:77-99``).
- ``SelfAttention``: single pre-LN, q = kv (reference ``perceiver/model.py:102-116``).
- ``Residual``: ``dropout(f(*args)) + args[0]`` — the residual applies to the
  *first* positional argument (reference ``perceiver/model.py:47-56``).
- ``MLP``: LayerNorm → Linear → GELU(exact) → Linear at constant width
  (reference ``perceiver/model.py:20-26``).

Initialization matches torch defaults so quality parity holds from step 0:
xavier-uniform q/k/v projections with zero biases, U(±1/sqrt(fan_in)) for
plain Linear layers (torch ``nn.Linear`` default), zero out-proj bias.
LayerNorm uses torch's epsilon (1e-5, vs flax's 1e-6 default) — material on
the low-variance latent stream (init std 0.02), where the epsilon shifts the
normalized output by ~0.1%.

The attention inner product is pluggable: ``attn_impl='xla'`` uses pure
jnp/einsum (XLA fuses this well on the MXU); ``attn_impl='pallas'`` dispatches
to the streaming fused Pallas kernel in ``perceiver_io_tpu.ops.pallas_attention``;
``attn_impl='packed'`` is the experimental small-latent packed-heads kernel
(opt-in — see PERF.md's negative-results note); ``'auto'`` (default) picks per
call site: the fused kernel for long KV streams (image/flow inputs) and for
big-logits self-attention stacks, XLA for small/shallow shapes (text) — see
``auto_attention_impl``.

Sequence parallelism: under an active regime
(``parallel.mesh.sequence_parallel_context`` — entered by
``make_sharded_train_step(shard_seq=True)``), attention calls marked
``seq_shard_kv=True`` (the encoder cross-attention, whose KV stream is the
seq-sharded input) route the kernel path through
``seq_parallel_fused_attention``: each device's ``pallas_call`` streams only
its S/n KV shard and softmax statistics merge with O(B·H·T) collectives,
instead of GSPMD all-gathering the KV stream around the kernel.
``attn_impl='pallas_sp'`` forces the kernel path with sp routing (degrading
to plain 'pallas' where sp doesn't apply); ``'auto'`` picks sp whenever it
would have picked the kernel and the regime is active.
"""

from __future__ import annotations

from typing import Any, Optional, Tuple

import jax
import jax.numpy as jnp
from flax import linen as nn

# Every projection in this module applies through ``linear_apply``: plain
# tensors take the exact flax-Dense math (promote_dtype + x @ w + b), while
# a quantized tree's ``QKernel`` leaves (quant/int8.py) dispatch to the
# fused dequant-matmul kernel — the model code itself never branches on
# quantization beyond the fused-stack special case below.
from perceiver_io_tpu.ops.pallas_matmul import linear_apply
from perceiver_io_tpu.quant.int8 import QKernel

Array = jax.Array

# torch nn.Linear default init: U(±1/sqrt(fan_in)) for weight and bias
# (kaiming_uniform(a=sqrt(5)) reduces to this bound for the weight).
torch_linear_kernel_init = nn.initializers.variance_scaling(
    scale=1.0 / 3.0, mode="fan_in", distribution="uniform"
)

# torch nn.LayerNorm default epsilon (flax defaults to 1e-6)
LN_EPS = 1e-5

# 'auto' attention dispatch (v5e measurements, tools/attn_shapes_bench.py).
# The XLA path materializes (B, H, T, S) logits, so its cost per logit byte is
# ~d/2 FLOPs: deep-contraction heads (d >= 1024) are compute-bound and XLA's
# matmul emitter wins (1.4x at ImageNet's 1-head d=1024 cross-attn); shallow
# heads are HBM-bound on the logits and the fused kernel wins (2.4x fwd+bwd
# at d=128, S=50k). d=512 measures a wash on time, where the kernel's O(S)
# memory breaks the tie. Short streams (text, S<=512 latents) are always XLA:
# those MXU-hostile d=16 shapes express worse in Mosaic than in the einsum.
#
# A second, area-based trigger covers big SELF-attention stacks whose S sits
# under the KV threshold: at flow's (2, 2048, 2048, 8, 64) the materialized
# logits are 67M elements and the kernel measures 2.0x fwd+bwd (1.44 vs
# 2.85 ms — it never writes the 134 MB/layer logits). The d >= 32 guard keeps
# the MXU-hostile d=16 text shapes on XLA at any batch (measured 6x slower in
# Mosaic at d=16).
AUTO_PALLAS_MIN_KV = 4096
AUTO_PALLAS_MAX_HEAD_DIM = 512
AUTO_PALLAS_MIN_LOGITS = 32 * 1024 * 1024  # B·H·T·S elements
AUTO_PALLAS_AREA_MIN_HEAD_DIM = 32


def auto_attention_impl(
    b: int, t: int, s: int, h: int, d: int, backend: Optional[str] = None
) -> str:
    """Resolve ``attn_impl='auto'`` for a (B, T, S, H, D) attention call.

    Pallas iff the backend is TPU, D ≤ 512, and either the KV stream is long
    (S ≥ 4096 — the streaming-cross case) or the materialized logits would be
    large with a non-tiny head (B·H·T·S ≥ 32M and D ≥ 32 — the big
    self-attention case). Encodes the `tools/attn_shapes_bench.py`
    measurements in PERF.md; change only with new rows there.
    """
    if backend is None:
        backend = jax.default_backend()
    if backend != "tpu" or d > AUTO_PALLAS_MAX_HEAD_DIM:
        return "xla"
    long_kv = s >= AUTO_PALLAS_MIN_KV
    big_logits = (
        b * h * t * s >= AUTO_PALLAS_MIN_LOGITS
        and d >= AUTO_PALLAS_AREA_MIN_HEAD_DIM
    )
    return "pallas" if (long_kv or big_logits) else "xla"


def layer_norm(dtype, name: str) -> nn.LayerNorm:
    return nn.LayerNorm(epsilon=LN_EPS, dtype=dtype, name=name)


def torch_linear_bias_init(fan_in: int):
    bound = 1.0 / (fan_in**0.5)

    def init(key, shape, dtype=jnp.float32):
        return jax.random.uniform(key, shape, dtype, -bound, bound)

    return init


def _dot_product_attention(
    q: Array,
    k: Array,
    v: Array,
    pad_mask: Optional[Array],
    attn_mask: Optional[Array],
    dropout_rate: float,
    dropout_rng: Optional[Array],
    deterministic: bool,
) -> Array:
    """Scaled dot-product attention over (B, T, H, D) tensors.

    pad_mask: (B, S) bool, True = position is padding (masked OUT) — the
    ``key_padding_mask`` convention of the reference's torch MHA.
    attn_mask: (T, S) or (B, T, S) additive-style bool, True = masked OUT.
    """
    d = q.shape[-1]
    scale = d**-0.5
    # (B, H, T, S) logits: contract head dim. For f32 operands request
    # HIGHEST precision (the MXU's default single bf16 pass costs ~3 decimal
    # digits; the Pallas kernel does the same — ops/pallas_attention) and
    # keep f32 logits. For bf16 operands, *store* the materialized logits in
    # bf16: the MXU still accumulates in f32 and only the stored value is
    # rounded (~2⁻⁸ relative), while softmax math below upcasts to f32 inside
    # the fused reduction. The (B, H, T, S) logits are the dominant HBM
    # traffic of the latent self-attention stack, and XLA cannot fuse across
    # the two matmuls — halving their bytes is a measured ~30% step-time win
    # on the flagship MLM config (PERF.md).
    if q.dtype == jnp.float32:
        precision, logits_dtype = jax.lax.Precision.HIGHEST, jnp.float32
    else:
        precision, logits_dtype = None, q.dtype
    logits = jnp.einsum(
        "bthd,bshd->bhts", q * scale, k,
        preferred_element_type=logits_dtype, precision=precision,
    )

    neg = jnp.finfo(logits.dtype).min
    if pad_mask is not None:
        logits = jnp.where(pad_mask[:, None, None, :], neg, logits)
    if attn_mask is not None:
        if attn_mask.ndim == 2:
            attn_mask = attn_mask[None]
        logits = jnp.where(attn_mask[:, None, :, :], neg, logits)

    probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)

    if dropout_rate > 0.0 and not deterministic:
        keep = jax.random.bernoulli(dropout_rng, 1.0 - dropout_rate, probs.shape)
        probs = jnp.where(keep, probs / (1.0 - dropout_rate), 0.0)

    probs = probs.astype(v.dtype)
    return jnp.einsum("bhts,bshd->bthd", probs, v, precision=precision)


class _LinearParams(nn.Module):
    """Declare a Linear's kernel/bias without applying it — the param tree is
    identical to ``nn.Dense`` (``{name: {kernel, bias}}``), so checkpoints,
    sharding path rules, and the torch-parity mapping are unchanged, while the
    caller is free to fuse several projections into one matmul."""

    in_features: int
    features: int
    kernel_init: Any = nn.initializers.xavier_uniform()
    bias_init: Any = nn.initializers.zeros_init()

    @nn.compact
    def __call__(self) -> Tuple[Array, Array]:
        kernel = self.param(
            "kernel", self.kernel_init, (self.in_features, self.features)
        )
        bias = self.param("bias", self.bias_init, (self.features,))
        return kernel, bias


class MultiHeadAttention(nn.Module):
    """Multi-head attention with distinct query / key-value channel counts.

    Mirrors torch ``nn.MultiheadAttention(embed_dim=num_q_channels,
    kdim=vdim=num_kv_channels, batch_first=True)`` as used at reference
    ``perceiver/model.py:59-74``.
    """

    num_q_channels: int
    num_kv_channels: int
    num_heads: int
    dropout: float = 0.0
    dtype: jnp.dtype = jnp.float32
    attn_impl: str = "auto"  # 'auto' | 'xla' | 'pallas' | 'pallas_sp' | 'packed'
    # Structural marker set by the ENCODER on its cross-attention: this call's
    # KV stream is the adapted input whose sequence axis shards over the mesh's
    # seq axis under shard_seq=True. Only such calls may route to the
    # sequence-parallel kernel — the latent self-attention and decoder
    # cross-attention have replicated (latent-sized) KV, where sp routing
    # would be legal but pointless collective traffic.
    seq_shard_kv: bool = False

    @nn.compact
    def __call__(
        self,
        x_q: Array,
        x_kv: Array,
        pad_mask: Optional[Array] = None,
        attn_mask: Optional[Array] = None,
        deterministic: bool = True,
        kv: Optional[Tuple[Array, Array]] = None,
        return_kv: bool = False,
        causal_offset: Optional[int] = None,
        kv_only: bool = False,
    ) -> Any:
        """``kv``: optional precomputed (k, v) projections — (B, S, E) in
        compute dtype, as returned by a previous call with ``return_kv=True``.
        When the same weights attend the same KV stream repeatedly (the
        encoder's shared ``layer_n`` recurrence), the K/V projections are
        identical across applications; passing them back in skips the repeat.
        Exact by construction — same tensors, not a re-computation. The
        forward dedup XLA's CSE sometimes finds anyway; the real win is the
        BACKWARD, where autodiff otherwise emits a full dW/dx projection pass
        per application (measured on the 131k-token MLM config, PERF.md r5).

        ``causal_offset``: static int — query row i may attend key positions
        ``<= i + causal_offset`` (``ops.masking.causal_mask``), composed with
        ``pad_mask``/``attn_mask`` by OR. The explicit kernel path applies it
        in-kernel (``fused_attention(causal_offset=)``); 'auto' dispatches
        causal shapes to XLA for now — the decode-shape sweep that would set
        kernel thresholds is queued on the tunnel (PERF.md §Generation), and
        an unmeasured dispatch flip is exactly what the threshold invariants
        forbid.

        ``kv_only``: project and return ONLY this call's (k, v) of ``x_kv``
        — no attention, no output projection. The incremental-decode path
        uses it to append one new row to a KV cache ring with the SAME
        weights the dense path projects with (cache parity by construction).
        """
        e = self.num_q_channels
        h = self.num_heads
        if e % h != 0:
            raise ValueError(f"num_q_channels {e} not divisible by num_heads {h}")
        if self.attn_impl not in ("auto", "xla", "pallas", "pallas_sp", "packed"):
            # a typo'd impl must not silently fall through to the XLA branch
            # and get benchmarked under the wrong label (PERF.md discipline)
            raise ValueError(
                f"unknown attn_impl {self.attn_impl!r}; expected one of "
                "'auto', 'xla', 'pallas', 'pallas_sp', 'packed'"
            )
        d = e // h

        if kv_only:
            # k/v projections only — q_proj/out_proj are neither declared
            # nor touched (their in_features belong to the query stream,
            # which this call does not have)
            wk, bk = _LinearParams(x_kv.shape[-1], e, name="k_proj")()
            wv, bv = _LinearParams(x_kv.shape[-1], e, name="v_proj")()
            return (linear_apply(x_kv, wk, bk, self.dtype),
                    linear_apply(x_kv, wv, bv, self.dtype))

        wq, bq = _LinearParams(x_q.shape[-1], e, name="q_proj")()
        wk, bk = _LinearParams(x_kv.shape[-1], e, name="k_proj")()
        wv, bv = _LinearParams(x_kv.shape[-1], e, name="v_proj")()
        if kv is not None:
            k, v = kv
            q = linear_apply(x_q, wq, bq, self.dtype)
        elif isinstance(wq, QKernel) and x_q is x_kv:
            # quantized self-attention: the fused-stack trick below cannot
            # stack int kernels with distinct scale grids, so the three
            # projections apply separately through the dequant-matmul
            # kernel. The stack's win was reading the input once on the
            # TRAINING path; on the quantized serving path the weight
            # stream is the bill, and that still streams int bytes here.
            q = linear_apply(x_q, wq, bq, self.dtype)
            k = linear_apply(x_kv, wk, bk, self.dtype)
            v = linear_apply(x_kv, wv, bv, self.dtype)
        elif x_q is x_kv:
            # self-attention: one fused matmul instead of three — the input
            # is read once and the three skinny gemms become one (measured
            # ~6% step win on the flagship MLM config, PERF.md). Identical
            # math: each output column is an independent dot product.
            # The fusion stacks the weights on a FRESH leading axis, (3, C,
            # E), rather than concatenating to (C, 3E): the three kernels
            # are tensor-parallel-sharded over their LAST axis (PARAM_RULES
            # (None, 'model')), and a concat along that sharded axis forces
            # an interleaving reshard that this XLA build's SPMD partitioner
            # miscompiles (repro'd: ~10 abs error on a 2-way model mesh; the
            # stacked form is bitwise-identical unsharded and exact sharded).
            w = jnp.stack([wq, wk, wv])
            bias = jnp.stack([bq, bk, bv])
            x, w, bias = nn.dtypes.promote_dtype(x_q, w, bias, dtype=self.dtype)
            qkv = jnp.einsum("btc,nce->btne", x, w) + bias
            q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]
        else:
            q = linear_apply(x_q, wq, bq, self.dtype)
            k = linear_apply(x_kv, wk, bk, self.dtype)
            v = linear_apply(x_kv, wv, bv, self.dtype)

        b, t = q.shape[:2]
        s = k.shape[1]

        dropout_active = self.dropout > 0.0 and not deterministic
        dropout_rng = self.make_rng("dropout") if dropout_active else None

        # The fused kernels cover the Perceiver hot path: pad-masked or
        # unmasked attention without prob-dropout. attn_mask / prob-dropout
        # fall back to the XLA path (never silently dropped).
        #
        # 'auto' (the default) picks per call site — long KV stream with
        # shallow heads → streaming fused kernel; everything else → XLA
        # einsum. 'packed' is the small-latent kernel reading the un-split
        # (B, T, E) layout (head separation in-VMEM by channel masking) —
        # opt-in while its end-to-end wins are shape-dependent.
        impl = self.attn_impl
        # Sequence-parallel routing: active regime (make_sharded_train_step
        # shard_seq=True over a mesh with seq > 1) + this call marked as the
        # seq-sharded KV consumer + KV length divisible by the axis. Explicit
        # 'pallas_sp' degrades to 'pallas' wherever sp doesn't apply, so one
        # model-level flag flips only the encoder cross-attention.
        sp = None
        if (self.seq_shard_kv and causal_offset is None
                and impl in ("auto", "pallas", "pallas_sp")):
            from perceiver_io_tpu.parallel.mesh import active_sequence_parallel

            ctx = active_sequence_parallel()
            if ctx is not None and s % ctx.mesh.shape[ctx.axis] == 0 and (
                ctx.batch_axis is None
                or b % ctx.mesh.shape[ctx.batch_axis] == 0
            ):
                # both divisibility guards matter: shard_map's in_specs
                # require exact splits, and eval batches (e.g. a drop_last=
                # False tail) may not divide the data axis — those fall back
                # to the plain kernel/XLA path, which GSPMD handles
                sp = ctx
        if impl == "pallas_sp":
            impl = "pallas"
        if impl == "auto":
            # TPU-only (off-TPU the kernel would run in interpreter mode,
            # orders of magnitude slower; explicit 'pallas' keeps that
            # fallback for tests): long KV streams and big-logits
            # self-attention go to the fused kernel, everything else to XLA
            # (see auto_attention_impl). Mesh-aware: under an active
            # seq-parallel regime the same shapes route to the sp kernel.
            # Causal (AR decode) shapes resolve CONSERVATIVELY to XLA until
            # the decode-shape sweep lands (tools/attn_shapes_bench.py
            # --decode; queued in PERF.md §Generation — dispatch thresholds
            # only move with measurements). Explicit 'pallas' takes the
            # kernel's in-kernel causal flag.
            impl = ("xla" if causal_offset is not None
                    else auto_attention_impl(b, t, s, h, d))
        if impl == "packed" and causal_offset is not None:
            raise ValueError(
                "attn_impl='packed' does not implement causal_offset — use "
                "'auto'/'xla' (masked einsum) or 'pallas' (in-kernel flag)"
            )
        fusable = attn_mask is None and not dropout_active
        if impl == "pallas" and fusable and sp is not None:
            from perceiver_io_tpu.ops.pallas_attention import (
                seq_parallel_fused_attention,
            )

            head_axis = sp.head_axis
            if head_axis is not None and h % sp.mesh.shape[head_axis]:
                head_axis = None  # indivisible heads replicate over tp
            out = seq_parallel_fused_attention(
                q.reshape(b, t, h, d), k.reshape(b, s, h, d),
                v.reshape(b, s, h, d), pad_mask=pad_mask,
                mesh=sp.mesh, axis=sp.axis, batch_axis=sp.batch_axis,
                head_axis=head_axis,
            ).reshape(b, t, e)
        elif impl == "packed" and fusable:
            from perceiver_io_tpu.ops.pallas_attention import (
                packed_fits_vmem,
                packed_latent_attention,
            )

            if not packed_fits_vmem(t, s, e, jnp.dtype(q.dtype).itemsize):
                raise ValueError(
                    f"attn_impl='packed' shapes T={t} S={s} E={e} exceed the "
                    "kernel's per-example VMEM budget (see "
                    "pallas_attention.packed_vmem_bytes)"
                )
            out = packed_latent_attention(q, k, v, h, pad_mask=pad_mask)
        elif impl == "pallas" and fusable:
            from perceiver_io_tpu.ops.pallas_attention import fused_attention

            out = fused_attention(
                q.reshape(b, t, h, d), k.reshape(b, s, h, d),
                v.reshape(b, s, h, d), pad_mask=pad_mask,
                causal_offset=causal_offset,
            ).reshape(b, t, e)
        else:
            if causal_offset is not None:
                from perceiver_io_tpu.ops.masking import causal_mask

                cmask = causal_mask(t, s, causal_offset)
                attn_mask = (cmask if attn_mask is None
                             else attn_mask | cmask)
            out = _dot_product_attention(
                q.reshape(b, t, h, d), k.reshape(b, s, h, d),
                v.reshape(b, s, h, d), pad_mask, attn_mask,
                self.dropout, dropout_rng, deterministic,
            ).reshape(b, t, e)
        wo, bo = _LinearParams(e, e, kernel_init=torch_linear_kernel_init,
                               name="out_proj")()
        out = linear_apply(out, wo, bo, self.dtype)
        if return_kv:
            return out, (k, v)
        return out


class CrossAttention(nn.Module):
    """Pre-LN cross-attention; embedding dim = query channels.

    Reference ``perceiver/model.py:77-99`` (including its documented
    simplification: the attention embedding dimension equals the number of
    query channels rather than being independently configurable).
    """

    num_q_channels: int
    num_kv_channels: int
    num_heads: int
    dropout: float = 0.0
    dtype: jnp.dtype = jnp.float32
    attn_impl: str = "auto"
    seq_shard_kv: bool = False

    @nn.compact
    def __call__(self, x_q, x_kv, pad_mask=None, attn_mask=None, deterministic=True,
                 kv=None, return_kv=False, causal_offset=None, kv_only=False):
        """``kv``/``return_kv``: precomputed K/V reuse across shared-weight
        applications (see ``MultiHeadAttention``). With ``kv`` given, the
        kv_norm + k/v projections are skipped entirely — the cached tensors
        already include them. ``kv_only``: kv_norm + k/v projections of
        ``x_kv`` ONLY (no query side at all) — what a decode step appends to
        its cache ring, bit-identical to what a dense forward would have
        projected for the same rows. ``causal_offset``: see
        :class:`MultiHeadAttention`."""
        mha = MultiHeadAttention(
            num_q_channels=self.num_q_channels,
            num_kv_channels=self.num_kv_channels,
            num_heads=self.num_heads,
            dropout=self.dropout,
            dtype=self.dtype,
            attn_impl=self.attn_impl,
            seq_shard_kv=self.seq_shard_kv,
            name="attention",
        )
        if kv_only:
            x_kv = layer_norm(self.dtype, "kv_norm")(x_kv)
            return mha(x_kv, x_kv, kv_only=True)
        x_q = layer_norm(self.dtype, "q_norm")(x_q)
        if kv is None:
            x_kv = layer_norm(self.dtype, "kv_norm")(x_kv)
        return mha(x_q, x_kv, pad_mask=pad_mask, attn_mask=attn_mask,
                   deterministic=deterministic, kv=kv, return_kv=return_kv,
                   causal_offset=causal_offset)


class SelfAttention(nn.Module):
    """Pre-LN self-attention, q = kv (reference ``perceiver/model.py:102-116``)."""

    num_channels: int
    num_heads: int
    dropout: float = 0.0
    dtype: jnp.dtype = jnp.float32
    attn_impl: str = "auto"

    @nn.compact
    def __call__(self, x, pad_mask=None, attn_mask=None, deterministic=True,
                 causal_offset=None, kv=None, kv_only=False):
        """``causal_offset``/``kv``/``kv_only``: the causal + KV-cache
        surface (see :class:`MultiHeadAttention`) — ``kv_only`` returns this
        stream's post-norm (k, v) rows for a decode cache ring; ``kv`` runs
        the query side of ``x`` against a caller-held ring instead of
        re-projecting the stream."""
        x = layer_norm(self.dtype, "norm")(x)
        mha = MultiHeadAttention(
            num_q_channels=self.num_channels,
            num_kv_channels=self.num_channels,
            num_heads=self.num_heads,
            dropout=self.dropout,
            dtype=self.dtype,
            attn_impl=self.attn_impl,
            name="attention",
        )
        if kv_only:
            return mha(x, x, kv_only=True)
        return mha(x, x, pad_mask=pad_mask, attn_mask=attn_mask,
                   deterministic=deterministic, causal_offset=causal_offset,
                   kv=kv)


class MLP(nn.Module):
    """LayerNorm → Linear → GELU(exact) → Linear, constant width.

    Reference ``perceiver/model.py:20-26``. torch-default Linear init.
    """

    num_channels: int
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x):
        c = self.num_channels
        x = layer_norm(self.dtype, "norm")(x)
        w1, b1 = _LinearParams(
            x.shape[-1], c, kernel_init=torch_linear_kernel_init,
            bias_init=torch_linear_bias_init(c), name="dense_1")()
        x = linear_apply(x, w1, b1, self.dtype)
        x = nn.gelu(x, approximate=False)
        w2, b2 = _LinearParams(
            c, c, kernel_init=torch_linear_kernel_init,
            bias_init=torch_linear_bias_init(c), name="dense_2")()
        x = linear_apply(x, w2, b2, self.dtype)
        return x


class CrossAttentionLayer(nn.Module):
    """Residual(CrossAttention) → Residual(MLP) on the query stream.

    Reference ``perceiver/model.py:29-34``: the residual adds the *first*
    positional argument — for cross-attention, the query/latent stream.
    """

    num_q_channels: int
    num_kv_channels: int
    num_heads: int
    dropout: float = 0.0
    dtype: jnp.dtype = jnp.float32
    attn_impl: str = "auto"
    seq_shard_kv: bool = False

    @nn.compact
    def __call__(self, x_q, x_kv, pad_mask=None, deterministic=True,
                 kv=None, return_kv=False, causal_offset=None,
                 kv_only=False):
        # Residual adds the FIRST positional arg (reference model.py:47-56):
        # for cross-attention that is the query/latent stream.
        drop = nn.Dropout(rate=self.dropout)
        xattn = CrossAttention(
            num_q_channels=self.num_q_channels,
            num_kv_channels=self.num_kv_channels,
            num_heads=self.num_heads,
            dropout=self.dropout,
            dtype=self.dtype,
            attn_impl=self.attn_impl,
            seq_shard_kv=self.seq_shard_kv,
            name="cross_attention",
        )
        if kv_only:
            # the decode-step cache append: kv_norm + k/v projections of
            # x_kv only, no query/residual/MLP work (see CrossAttention)
            return xattn(x_q, x_kv, kv_only=True)
        attn_out = xattn(x_q, x_kv, pad_mask=pad_mask,
                         deterministic=deterministic, kv=kv,
                         return_kv=return_kv, causal_offset=causal_offset)
        if return_kv:
            attn_out, kv_out = attn_out
        x = drop(attn_out, deterministic=deterministic) + x_q
        mlp_out = MLP(self.num_q_channels, dtype=self.dtype, name="mlp")(x)
        out = drop(mlp_out, deterministic=deterministic) + x
        if return_kv:
            return out, kv_out
        return out


class SelfAttentionLayer(nn.Module):
    """Residual(SelfAttention) → Residual(MLP) (reference ``perceiver/model.py:37-40``)."""

    num_channels: int
    num_heads: int
    dropout: float = 0.0
    dtype: jnp.dtype = jnp.float32
    attn_impl: str = "auto"

    @nn.compact
    def __call__(self, x, deterministic=True, attn_mask=None,
                 causal_offset=None, return_kv=False,
                 cache=None, cache_index=None, cache_pad=None):
        """Three modes sharing one weight set:

        - plain (default): the MLM path, unchanged.
        - dense causal (``causal_offset``/``attn_mask``): the AR training /
          prefill forward. ``return_kv=True`` additionally returns this
          layer's post-norm (k, v) of the full stream — exactly the rows a
          decode cache ring holds, so prefill builds its caches from the
          SAME tensors the dense forward attends over (parity by
          construction).
        - incremental (``cache``): ``x`` is the (B, 1, C) new-row stream;
          the layer projects the row's k/v, writes them at ``cache_index``
          (scalar int array) into the (B, S_cap, E) rings, attends the
          single query over the updated rings under ``cache_pad`` (B, S_cap;
          True = empty/invalid slot), and returns ``(out, updated_cache)``.
        """
        import jax.lax as lax

        drop = nn.Dropout(rate=self.dropout)
        attn = SelfAttention(
            num_channels=self.num_channels,
            num_heads=self.num_heads,
            dropout=self.dropout,
            dtype=self.dtype,
            attn_impl=self.attn_impl,
            name="self_attention",
        )
        if cache is not None:
            k_ring, v_ring = cache
            k_new, v_new = attn(x, kv_only=True)
            zero = jnp.zeros((), jnp.int32)
            k_ring = lax.dynamic_update_slice(
                k_ring, k_new.astype(k_ring.dtype), (zero, cache_index, zero))
            v_ring = lax.dynamic_update_slice(
                v_ring, v_new.astype(v_ring.dtype), (zero, cache_index, zero))
            attn_out = attn(x, pad_mask=cache_pad, kv=(k_ring, v_ring),
                            deterministic=deterministic)
        elif return_kv:
            k_full, v_full = attn(x, kv_only=True)
            attn_out = attn(x, attn_mask=attn_mask,
                            causal_offset=causal_offset,
                            kv=(k_full, v_full),
                            deterministic=deterministic)
        else:
            attn_out = attn(x, attn_mask=attn_mask,
                            causal_offset=causal_offset,
                            deterministic=deterministic)
        x = drop(attn_out, deterministic=deterministic) + x
        mlp_out = MLP(self.num_channels, dtype=self.dtype, name="mlp")(x)
        out = drop(mlp_out, deterministic=deterministic) + x
        if cache is not None:
            return out, (k_ring, v_ring)
        if return_kv:
            return out, (k_full, v_full)
        return out


class SelfAttentionBlock(nn.Module):
    """N stacked self-attention layers, each with its own weights.

    Reference ``perceiver/model.py:43-44``. Inside an encoder layer, the whole
    block's weights are shared across recurrent applications (see
    ``PerceiverEncoder``), but layers *within* a block are distinct.
    """

    num_layers: int
    num_channels: int
    num_heads: int
    dropout: float = 0.0
    dtype: jnp.dtype = jnp.float32
    attn_impl: str = "auto"

    @nn.compact
    def __call__(self, x, deterministic=True, attn_mask=None,
                 causal_offset=None, return_kv=False,
                 cache=None, cache_index=None, cache_pad=None):
        """Causal/cache surface mirrors :class:`SelfAttentionLayer`, with
        ``cache`` (and the ``return_kv`` harvest) as a LIST of per-layer
        (k, v) pairs — each stacked layer owns one ring."""
        kvs = []
        updated = []
        for i in range(self.num_layers):
            layer = SelfAttentionLayer(
                num_channels=self.num_channels,
                num_heads=self.num_heads,
                dropout=self.dropout,
                dtype=self.dtype,
                attn_impl=self.attn_impl,
                name=f"layer_{i}",
            )
            if cache is not None:
                x, ring = layer(x, deterministic=deterministic,
                                cache=cache[i], cache_index=cache_index,
                                cache_pad=cache_pad)
                updated.append(ring)
            elif return_kv:
                x, kv = layer(x, deterministic=deterministic,
                              attn_mask=attn_mask,
                              causal_offset=causal_offset, return_kv=True)
                kvs.append(kv)
            else:
                x = layer(x, deterministic=deterministic,
                          attn_mask=attn_mask, causal_offset=causal_offset)
        if cache is not None:
            return x, updated
        if return_kv:
            return x, kvs
        return x
