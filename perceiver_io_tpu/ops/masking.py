"""BERT-style MLM text masking as a pure function of (rng key, batch).

Reproduces the reference corruption scheme exactly (``perceiver/model.py:240-293``),
including its nested-draw idiosyncrasy:

- special positions = ``(x == unk_id) | pad_mask``; only non-special positions
  are candidates,
- ``selected``   = Bernoulli(mask_p) ∧ candidate            (15% default),
- ``selected_1`` = selected ∧ Bernoulli(0.9)                 (these become [MASK]),
- ``selected_2`` = selected_1 ∧ Bernoulli(1/9)               (then overwritten with a
  random non-special token — note selected_2 ⊆ selected_1, so the random
  tokens are drawn *from the masked set*, giving the 80/10/10 marginal split),
- labels are ``-100`` everywhere except selected positions.

Random replacement tokens are uniform over ``[num_special_tokens, vocab_size)``,
relying on the same contract as the reference (``model.py:284-289``): special
tokens occupy the first ids.

The device RNG is a threaded ``jax.random`` key, so masking is deterministic
given (key, batch) — the TPU-native replacement for per-step CUDA RNG.
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp

Array = jax.Array

IGNORE_LABEL = -100


# -- causal attention masks (the Perceiver-AR decode path) --------------------
#
# Mask convention throughout ops/attention.py: True = masked OUT (the torch
# ``key_padding_mask`` sense). A causal mask is a pure function of the query
# row's absolute position: query row i sits at position ``offset + i`` and may
# attend key j iff ``j <= offset + i``. offset = 0 is the square causal
# self-attention mask; offset = L - N is the Perceiver-AR cross-attention
# mask, where N latent queries cover the LAST N positions of an L-token input
# and each latent sees the full prefix up to (and including) its own token.


def causal_mask(num_queries: int, num_keys: int, offset: int = 0) -> Array:
    """(T, S) bool causal mask, True = masked out: query row ``i`` (absolute
    position ``offset + i``) may attend key positions ``<= offset + i``.

    Composes with a (B, S) pad mask by OR — ``ops.attention`` applies both
    independently, which is exactly that composition (a position is masked
    when padded OR acausal). The fused Pallas kernel takes the same rule as
    a ``causal_offset`` flag and applies it in-kernel instead of reading a
    materialized (T, S) mask (``ops.pallas_attention.fused_attention``)."""
    rows = jnp.arange(num_queries, dtype=jnp.int32)[:, None]
    cols = jnp.arange(num_keys, dtype=jnp.int32)[None, :]
    return cols > rows + offset


def combine_attention_masks(
    pad_mask: Optional[Array],
    attn_mask: Optional[Array],
    num_queries: Optional[int] = None,
) -> Optional[Array]:
    """The effective (B, T, S) True=masked-out mask the attention paths apply
    — pad (B, S) OR'd with a (T, S)/(B, T, S) structural mask. The dense
    oracle the masking-parity tests check the kernel paths against; returns
    None when neither input masks anything."""
    if pad_mask is None and attn_mask is None:
        return None
    if attn_mask is not None and attn_mask.ndim == 2:
        attn_mask = attn_mask[None]
    if pad_mask is None:
        return attn_mask
    pad = pad_mask[:, None, :]
    if num_queries is not None:
        pad = jnp.broadcast_to(
            pad, (pad_mask.shape[0], num_queries, pad_mask.shape[-1])
        )
    if attn_mask is None:
        return pad
    return pad | attn_mask


def shift_ar_labels(token_ids: Array, pad_mask: Optional[Array],
                    latent_offset: int = 0) -> Array:
    """Next-token labels for the causal AR window: the query at absolute
    position ``latent_offset + i`` predicts ``token_ids[:, latent_offset + i
    + 1]``. Returns (B, L - latent_offset) int32 labels with
    :data:`IGNORE_LABEL` at the final position (no successor) and wherever
    the TARGET token is padding — the same ignore convention MLM's CE uses,
    so ``cross_entropy_with_ignore`` applies unchanged."""
    b, l = token_ids.shape
    n = l - latent_offset
    # Successor ids via roll-then-slice, NOT concat: under a seq-sharded
    # batch (shard_seq=True with tp x sp meshes) this XLA build's SPMD
    # partitioner miscompiles a concat along the sharded axis (the r6
    # fused-QKV repro — here it surfaced as NaN loss in the dp2/tp2/sp2
    # dry run); roll lowers to a collective permute, which partitions
    # correctly. The wrapped-around element lands at the final slot, which
    # is ignored anyway (no successor exists there).
    succ = jnp.roll(token_ids, -1, axis=1)[:, latent_offset:]
    labels = succ.astype(jnp.int32)
    last = jnp.arange(n, dtype=jnp.int32)[None, :] == n - 1
    invalid = jnp.broadcast_to(last, (b, n))
    if pad_mask is not None:
        invalid = invalid | jnp.roll(pad_mask, -1, axis=1)[:, latent_offset:]
    return jnp.where(invalid, IGNORE_LABEL, labels)


def apply_text_masking(
    key: Array,
    x: Array,
    pad_mask: Array,
    *,
    vocab_size: int,
    unk_token_id: int,
    mask_token_id: int,
    num_special_tokens: int,
    mask_p: float = 0.15,
) -> Tuple[Array, Array]:
    """Corrupt token ids ``x`` (B, L) for MLM; returns ``(x_masked, labels)``.

    ``pad_mask`` is True at padding positions. Labels are ``IGNORE_LABEL`` at
    non-selected positions.
    """
    k_sel, k_mask90, k_rand19, k_tok = jax.random.split(key, 4)
    shape = x.shape

    if pad_mask is None:
        pad_mask = jnp.zeros(shape, dtype=bool)

    is_special = (x == unk_token_id) | pad_mask
    is_input = ~is_special

    is_selected = (jax.random.uniform(k_sel, shape) < mask_p) & is_input
    is_selected_1 = is_selected & (jax.random.uniform(k_mask90, shape) < 0.9)
    is_selected_2 = is_selected_1 & (jax.random.uniform(k_rand19, shape) < 1.0 / 9.0)

    random_tokens = jax.random.randint(
        k_tok, shape, num_special_tokens, vocab_size, dtype=x.dtype
    )

    x_masked = jnp.where(is_selected_1, jnp.asarray(mask_token_id, x.dtype), x)
    x_masked = jnp.where(is_selected_2, random_tokens, x_masked)

    # Labels must be signed so IGNORE_LABEL=-100 cannot wrap for unsigned
    # token-id dtypes.
    labels = jnp.where(is_selected, x.astype(jnp.int32), IGNORE_LABEL)
    return x_masked, labels


class TextMasking:
    """Config holder mirroring the reference's ``TextMasking`` module surface
    (``perceiver/model.py:240-263``), as a plain dataclass-style callable —
    masking itself is stateless and keyed."""

    def __init__(
        self,
        vocab_size: int,
        unk_token_id: int,
        mask_token_id: int,
        num_special_tokens: int,
        mask_p: float = 0.15,
    ):
        self.vocab_size = vocab_size
        self.unk_token_id = unk_token_id
        self.mask_token_id = mask_token_id
        self.num_special_tokens = num_special_tokens
        self.mask_p = mask_p

    @classmethod
    def create(cls, tokenizer, **kwargs):
        """Build from a tokenizer exposing vocab_size / token_to_id, mirroring
        ``TextMasking.create`` (reference ``model.py:254-260``)."""
        from perceiver_io_tpu.data.tokenizer import UNK_TOKEN, MASK_TOKEN, SPECIAL_TOKENS

        return cls(
            vocab_size=tokenizer.get_vocab_size(),
            unk_token_id=tokenizer.token_to_id(UNK_TOKEN),
            mask_token_id=tokenizer.token_to_id(MASK_TOKEN),
            num_special_tokens=len(SPECIAL_TOKENS),
            **kwargs,
        )

    def __call__(self, key: Array, x: Array, pad_mask: Array) -> Tuple[Array, Array]:
        return apply_text_masking(
            key,
            x,
            pad_mask,
            vocab_size=self.vocab_size,
            unk_token_id=self.unk_token_id,
            mask_token_id=self.mask_token_id,
            num_special_tokens=self.num_special_tokens,
            mask_p=self.mask_p,
        )
