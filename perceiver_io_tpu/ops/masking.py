"""BERT-style MLM text masking as a pure function of (rng key, batch).

Reproduces the reference corruption scheme exactly (``perceiver/model.py:240-293``),
including its nested-draw idiosyncrasy:

- special positions = ``(x == unk_id) | pad_mask``; only non-special positions
  are candidates,
- ``selected``   = Bernoulli(mask_p) ∧ candidate            (15% default),
- ``selected_1`` = selected ∧ Bernoulli(0.9)                 (these become [MASK]),
- ``selected_2`` = selected_1 ∧ Bernoulli(1/9)               (then overwritten with a
  random non-special token — note selected_2 ⊆ selected_1, so the random
  tokens are drawn *from the masked set*, giving the 80/10/10 marginal split),
- labels are ``-100`` everywhere except selected positions.

Random replacement tokens are uniform over ``[num_special_tokens, vocab_size)``,
relying on the same contract as the reference (``model.py:284-289``): special
tokens occupy the first ids.

The device RNG is a threaded ``jax.random`` key, so masking is deterministic
given (key, batch) — the TPU-native replacement for per-step CUDA RNG.
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp

Array = jax.Array

IGNORE_LABEL = -100


def apply_text_masking(
    key: Array,
    x: Array,
    pad_mask: Array,
    *,
    vocab_size: int,
    unk_token_id: int,
    mask_token_id: int,
    num_special_tokens: int,
    mask_p: float = 0.15,
) -> Tuple[Array, Array]:
    """Corrupt token ids ``x`` (B, L) for MLM; returns ``(x_masked, labels)``.

    ``pad_mask`` is True at padding positions. Labels are ``IGNORE_LABEL`` at
    non-selected positions.
    """
    k_sel, k_mask90, k_rand19, k_tok = jax.random.split(key, 4)
    shape = x.shape

    if pad_mask is None:
        pad_mask = jnp.zeros(shape, dtype=bool)

    is_special = (x == unk_token_id) | pad_mask
    is_input = ~is_special

    is_selected = (jax.random.uniform(k_sel, shape) < mask_p) & is_input
    is_selected_1 = is_selected & (jax.random.uniform(k_mask90, shape) < 0.9)
    is_selected_2 = is_selected_1 & (jax.random.uniform(k_rand19, shape) < 1.0 / 9.0)

    random_tokens = jax.random.randint(
        k_tok, shape, num_special_tokens, vocab_size, dtype=x.dtype
    )

    x_masked = jnp.where(is_selected_1, jnp.asarray(mask_token_id, x.dtype), x)
    x_masked = jnp.where(is_selected_2, random_tokens, x_masked)

    # Labels must be signed so IGNORE_LABEL=-100 cannot wrap for unsigned
    # token-id dtypes.
    labels = jnp.where(is_selected, x.astype(jnp.int32), IGNORE_LABEL)
    return x_masked, labels


class TextMasking:
    """Config holder mirroring the reference's ``TextMasking`` module surface
    (``perceiver/model.py:240-263``), as a plain dataclass-style callable —
    masking itself is stateless and keyed."""

    def __init__(
        self,
        vocab_size: int,
        unk_token_id: int,
        mask_token_id: int,
        num_special_tokens: int,
        mask_p: float = 0.15,
    ):
        self.vocab_size = vocab_size
        self.unk_token_id = unk_token_id
        self.mask_token_id = mask_token_id
        self.num_special_tokens = num_special_tokens
        self.mask_p = mask_p

    @classmethod
    def create(cls, tokenizer, **kwargs):
        """Build from a tokenizer exposing vocab_size / token_to_id, mirroring
        ``TextMasking.create`` (reference ``model.py:254-260``)."""
        from perceiver_io_tpu.data.tokenizer import UNK_TOKEN, MASK_TOKEN, SPECIAL_TOKENS

        return cls(
            vocab_size=tokenizer.get_vocab_size(),
            unk_token_id=tokenizer.token_to_id(UNK_TOKEN),
            mask_token_id=tokenizer.token_to_id(MASK_TOKEN),
            num_special_tokens=len(SPECIAL_TOKENS),
            **kwargs,
        )

    def __call__(self, key: Array, x: Array, pad_mask: Array) -> Tuple[Array, Array]:
        return apply_text_masking(
            key,
            x,
            pad_mask,
            vocab_size=self.vocab_size,
            unk_token_id=self.unk_token_id,
            mask_token_id=self.mask_token_id,
            num_special_tokens=self.num_special_tokens,
            mask_p=self.mask_p,
        )
