"""Fourier position encodings, computed once on host/at-trace as constants.

Matches the reference scheme (``perceiver/adapter.py:53-97``):

- positions: per spatial dim, evenly spaced coordinates in [-1, 1]
  (``linspace``), combined with an 'ij'-indexed meshgrid and stacked channel-last.
- encodings: per dim *i*, ``num_bands`` frequencies linearly spaced from 1.0 to
  ``max_freq_i / 2`` where ``max_freq_i`` defaults to the spatial size of dim
  *i*; features are the raw positions followed by ``sin(pi f p)`` then
  ``cos(pi f p)`` for every (dim, band) pair.

Total channels: ``ndim * (2 * num_bands + include_positions)``.

These are pure jnp functions; adapters precompute the encoding for one example
and close over it as a traced constant, which XLA folds into the program (the
analogue of the reference's ``register_buffer`` at ``adapter.py:43-51``).
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

import jax.numpy as jnp


def spatial_positions(
    spatial_shape: Sequence[int], v_min: float = -1.0, v_max: float = 1.0
) -> jnp.ndarray:
    """Evenly spaced coordinates for each point of ``spatial_shape``.

    Returns an array of shape ``(*spatial_shape, len(spatial_shape))`` with
    values in ``[v_min, v_max]`` (reference ``adapter.py:53-62``).
    """
    coords = [jnp.linspace(v_min, v_max, num=s) for s in spatial_shape]
    grid = jnp.meshgrid(*coords, indexing="ij")
    return jnp.stack(grid, axis=-1)


def fourier_position_encodings(
    p: jnp.ndarray,
    num_frequency_bands: int,
    max_frequencies: Optional[Tuple[int, ...]] = None,
    include_positions: bool = True,
) -> jnp.ndarray:
    """Fourier-encode positions ``p`` of shape ``(*d, c)`` with c = len(d).

    Returns shape ``(*d, c * (2 * num_bands + include_positions))``
    (reference ``adapter.py:64-94``; feature order: positions, all sins, all cosines).
    """
    if max_frequencies is None:
        max_frequencies = p.shape[:-1]
    if len(max_frequencies) != p.shape[-1]:
        raise ValueError(
            f"need one max frequency per position dim: got {len(max_frequencies)} "
            f"for {p.shape[-1]} dims"
        )

    frequency_grids = []
    for i, max_freq in enumerate(max_frequencies):
        freqs = jnp.linspace(1.0, max_freq / 2.0, num=num_frequency_bands)
        frequency_grids.append(p[..., i : i + 1] * freqs)

    encodings = []
    if include_positions:
        encodings.append(p)
    encodings.extend(jnp.sin(jnp.pi * g) for g in frequency_grids)
    encodings.extend(jnp.cos(jnp.pi * g) for g in frequency_grids)
    return jnp.concatenate(encodings, axis=-1)


def num_position_encoding_channels(
    num_spatial_dims: int, num_frequency_bands: int, include_positions: bool = True
) -> int:
    """Channel count produced by :func:`fourier_position_encodings`
    (reference ``adapter.py:96-97``)."""
    return num_spatial_dims * (2 * num_frequency_bands + int(include_positions))
