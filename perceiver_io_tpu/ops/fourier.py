"""Fourier position encodings, computed once on host as true constants.

Matches the reference scheme (``perceiver/adapter.py:53-97``):

- positions: per spatial dim, evenly spaced coordinates in [-1, 1]
  (``linspace``), combined with an 'ij'-indexed meshgrid and stacked channel-last.
- encodings: per dim *i*, ``num_bands`` frequencies linearly spaced from 1.0 to
  ``max_freq_i / 2`` where ``max_freq_i`` defaults to the spatial size of dim
  *i*; features are the raw positions followed by ``sin(pi f p)`` then
  ``cos(pi f p)`` for every (dim, band) pair.

Total channels: ``ndim * (2 * num_bands + include_positions)``.

These are NUMPY functions on purpose — every call site passes static shapes,
so the encodings are host constants the adapters close over (the analogue of
the reference's ``register_buffer`` at ``adapter.py:43-51``). Computing them
with jnp inside a jitted adapter stages the whole meshgrid/stack/concat
subgraph into the program, where the SPMD partitioner reshards it when the
consuming axis is sequence-sharded — a pattern the XLA build this runs under
miscompiles (repro: seq-sharded image inputs came back with permuted
encodings; the host constant is exact). f32 throughout, matching the
previous traced-constant numerics.
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

import numpy as np


def spatial_positions(
    spatial_shape: Sequence[int], v_min: float = -1.0, v_max: float = 1.0
) -> np.ndarray:
    """Evenly spaced coordinates for each point of ``spatial_shape``.

    Returns an array of shape ``(*spatial_shape, len(spatial_shape))`` with
    values in ``[v_min, v_max]`` (reference ``adapter.py:53-62``).
    """
    coords = [
        np.linspace(v_min, v_max, num=s, dtype=np.float32)
        for s in spatial_shape
    ]
    grid = np.meshgrid(*coords, indexing="ij")
    return np.stack(grid, axis=-1)


def fourier_position_encodings(
    p: np.ndarray,
    num_frequency_bands: int,
    max_frequencies: Optional[Tuple[int, ...]] = None,
    include_positions: bool = True,
) -> np.ndarray:
    """Fourier-encode positions ``p`` of shape ``(*d, c)`` with c = len(d).

    Returns shape ``(*d, c * (2 * num_bands + include_positions))``
    (reference ``adapter.py:64-94``; feature order: positions, all sins, all cosines).
    """
    p = np.asarray(p, dtype=np.float32)
    if max_frequencies is None:
        max_frequencies = p.shape[:-1]
    if len(max_frequencies) != p.shape[-1]:
        raise ValueError(
            f"need one max frequency per position dim: got {len(max_frequencies)} "
            f"for {p.shape[-1]} dims"
        )

    frequency_grids = []
    for i, max_freq in enumerate(max_frequencies):
        freqs = np.linspace(
            1.0, max_freq / 2.0, num=num_frequency_bands, dtype=np.float32
        )
        frequency_grids.append(p[..., i : i + 1] * freqs)

    encodings = []
    if include_positions:
        encodings.append(p)
    encodings.extend(np.sin(np.float32(np.pi) * g) for g in frequency_grids)
    encodings.extend(np.cos(np.float32(np.pi) * g) for g in frequency_grids)
    return np.concatenate(encodings, axis=-1)


def num_position_encoding_channels(
    num_spatial_dims: int, num_frequency_bands: int, include_positions: bool = True
) -> int:
    """Channel count produced by :func:`fourier_position_encodings`
    (reference ``adapter.py:96-97``)."""
    return num_spatial_dims * (2 * num_frequency_bands + int(include_positions))
