"""Fused dequant-matmul Pallas kernel (TPU) for weight-only quantized serving.

The serving roofline (PERF.md, `tools/hbm_roofline.py`) is bound by the HBM
weight stream, and r20's continuous batching made the decode weight stream
essentially the whole bill. r8's weight-only int8 (`quant/int8.py`) leans on
XLA to fuse ``convert × scale`` into the consuming matmul's operand read —
which works, but leaves the fusion decision to XLA and cannot express the
grouped-int4 layout at all. This kernel closes the loop: the int8/int4
weight tiles themselves are what streams from HBM, the ``convert × scale``
runs in VMEM per tile, and the matmul accumulates in f32 scratch across the
K grid — the same streamed-operand + sequential-reduction shape as the
flash-attention/flash-CE kernels in this repo.

Design:

- grid ``(M/bm, N/bn, K/bk)`` with the contraction axis INNERMOST
  (sequential): the f32 accumulator lives in VMEM scratch across K blocks,
  zeroed at ``k==0`` and flushed to the output dtype at ``k==n_k-1``.
- weight tile dequant: ``q_tile.astype(f32) * scale_tile``. Per-channel
  scales ride as a ``(1, N)`` array blocked ``(1, bn)`` (same block for
  every K step); grouped scales as ``(K/gs, N)`` blocked ``(1, bn)`` with
  the K-block size pinned to ``group_size`` so grid step ``k`` reads
  exactly group ``k``'s scales and the in-kernel multiply is a plain
  broadcast (no sublane reshapes, which are not free on Mosaic).
- f32 activations keep ``Precision.HIGHEST`` (multi-pass MXU — same policy
  as ``pallas_attention._dot`` and the XLA f32 parity path); bf16
  activations take the fast single pass with f32 accumulation via
  ``preferred_element_type``.
- M/N/K are padded to the resolved blocks with zeros (zero K rows
  contribute nothing; padded N columns are sliced off), so arbitrary
  serving shapes — batch-1 decode rows included — hit one code path.

VMEM budget (conservative until measured — the tunnel has been dark since
r5, so unlike the attention kernel's tiers these blocks encode *budget
math*, not a hardware sweep; the sweep rides PERF.md §r10 pending): per
grid step the kernel holds x ``bm·bk·xB``, the weight tile ``bk·bn`` int
bytes plus its ``bk·bn·4`` f32 dequant temp, the ``bm·bn·4`` accumulator,
and the ``bm·bn`` output tile, ×2 on the streamed refs for the pipeline's
double buffering. The defaults (bm 128, bn 512, bk 512) total ~2.3 MB f32
— an order of magnitude inside the measured ~16 MB scoped-VMEM boundary
(r3), and ``_auto_blocks`` halves bn/bk if a custom request would cross
``QMM_VMEM_BUDGET`` (half the boundary, same guard philosophy as
``_auto_kv_block``).
"""

from __future__ import annotations

import functools
import os
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from flax.linen import dtypes as _flax_dtypes

from perceiver_io_tpu.quant.int8 import QKernel

# the TPUCompilerParams -> CompilerParams rename landed in newer jax; alias
# whichever spelling this build ships
_CompilerParams = getattr(pltpu, "CompilerParams", None) or pltpu.TPUCompilerParams

Array = jax.Array

_LANES = 128
_SUBLANES = 8
# int8 min tile is (32, 128) on TPU; blocks must keep the second-minor dim a
# multiple of 32 when compiled (interpret mode has no tiling constraint)
_INT_SUBLANES = 32

DEFAULT_M_BLOCK = 128
DEFAULT_N_BLOCK = 512
DEFAULT_K_BLOCK = 512
# Half the measured ~16 MB scoped-VMEM boundary (PERF.md r3): headroom for
# Mosaic's own scratch and the double-buffered pipeline. Conservative until
# the real-TPU block sweep lands (§r10 pending) — NOT a measured tier.
QMM_VMEM_BUDGET = 8 * 1024 * 1024

_VALID_IMPLS = ("pallas", "xla")


def _ceil_to(x: int, mult: int) -> int:
    return -(-x // mult) * mult


def _tile_vmem_bytes(bm: int, bk: int, bn: int, x_itemsize: int,
                     out_itemsize: int) -> int:
    """Budget-math VMEM residency of one grid step (documented above).
    Weight tiles count at 1 B/element even for int4 — whether Mosaic keeps
    s4 packed in VMEM is unmeasured, so the guard assumes it does not."""
    x_b = bm * bk * x_itemsize
    q_b = bk * bn  # int bytes (int4 counted unpacked — conservative)
    w_b = bk * bn * 4  # f32 dequant temp
    acc_b = bm * bn * 4
    out_b = bm * bn * out_itemsize
    return 2 * (x_b + q_b) + w_b + acc_b + out_b


def _auto_blocks(m: int, k: int, n: int, x_itemsize: int, out_itemsize: int,
                 group_size: Optional[int]) -> Tuple[int, int, int]:
    """Resolve (bm, bk, bn). Grouped scales pin bk to ``group_size`` (one
    scale row per grid step); otherwise blocks start at the defaults,
    shrink to the (padded) dims when those are smaller, and halve bn then
    bk until the budget math clears ``QMM_VMEM_BUDGET``. Every choice here
    is conservative-until-measured (module docstring) — re-tier only with
    real-TPU sweep rows in PERF.md."""
    bm = min(DEFAULT_M_BLOCK, _ceil_to(m, _SUBLANES))
    bn = min(DEFAULT_N_BLOCK, _ceil_to(n, _LANES))
    if group_size is not None:
        bk = group_size
    else:
        bk = min(DEFAULT_K_BLOCK, _ceil_to(k, _LANES))
    while (_tile_vmem_bytes(bm, bk, bn, x_itemsize, out_itemsize)
           > QMM_VMEM_BUDGET and bn > _LANES):
        bn //= 2
    while (group_size is None
           and _tile_vmem_bytes(bm, bk, bn, x_itemsize, out_itemsize)
           > QMM_VMEM_BUDGET and bk > _LANES):
        bk //= 2
    return bm, bk, bn


def _dequant_matmul_kernel(x_ref, q_ref, s_ref, o_ref, acc_ref):
    k_idx = pl.program_id(2)

    @pl.when(k_idx == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)

    # convert × scale in VMEM: the only HBM-side weight traffic is q's int
    # bytes (+ the skinny scale row). s_ref is (1, bn) — per-channel blocks
    # re-read the same row every K step; grouped blocks read row k (= this
    # K block's group), and the multiply broadcasts over the bk rows.
    w = q_ref[...].astype(jnp.float32) * s_ref[...]
    x = x_ref[...]
    if x.dtype == jnp.float32:
        # f32 parity path: multi-pass MXU, same policy as the attention
        # kernel's _dot — a single bf16 pass would cost ~3 decimal digits
        # and break the 2e-5 golden bound
        acc_ref[...] += jax.lax.dot_general(
            x, w, dimension_numbers=(((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
            precision=jax.lax.Precision.HIGHEST,
        )
    else:
        acc_ref[...] += jax.lax.dot_general(
            x, w.astype(x.dtype), dimension_numbers=(((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    @pl.when(k_idx == pl.num_programs(2) - 1)
    def _flush():
        o_ref[...] = acc_ref[...].astype(o_ref.dtype)


@functools.partial(
    jax.jit,
    static_argnames=("out_dtype", "group_size", "block_m", "block_n",
                     "block_k", "interpret"),
)
def dequant_matmul(
    x: Array,
    q: Array,
    scale: Array,
    out_dtype=None,
    group_size: Optional[int] = None,
    block_m: Optional[int] = None,
    block_n: Optional[int] = None,
    block_k: Optional[int] = None,
    interpret: bool = False,
) -> Array:
    """``x (M, K) @ dequant(q (K, N), scale)`` with in-VMEM dequantization.

    ``scale`` is ``(N,)`` per-channel or ``(K/group_size, N)`` grouped (pass
    ``group_size`` for the latter — it must divide K; `quant.quantize_array`
    guarantees that by falling back to per-channel when it would not).
    Explicit ``block_*`` are still budget-guarded by ``_auto_blocks``'s
    shrink loop semantics only when auto-resolved; callers overriding blocks
    own the VMEM math (kernel_smoke pins the boundary geometries).
    """
    m, k = x.shape
    k2, n = q.shape
    if k != k2:
        raise ValueError(f"contraction mismatch: x {x.shape} vs q {q.shape}")
    out_dtype = jnp.dtype(out_dtype or x.dtype)
    if group_size is not None:
        if k % group_size:
            raise ValueError(
                f"group_size {group_size} does not divide K={k}")
        if scale.shape != (k // group_size, n):
            raise ValueError(
                f"grouped scale shape {scale.shape} != {(k // group_size, n)}")
        s2d = scale
    else:
        if scale.shape != (n,):
            raise ValueError(f"per-channel scale shape {scale.shape} != ({n},)")
        s2d = scale.reshape(1, n)

    bm, bk, bn = _auto_blocks(m, k, n, x.dtype.itemsize, out_dtype.itemsize,
                              group_size)
    if block_m is not None:
        bm = block_m
    if block_n is not None:
        bn = block_n
    if block_k is not None and group_size is None:
        bk = block_k

    mp, np_, kp = _ceil_to(m, bm), _ceil_to(n, bn), _ceil_to(k, bk)
    if mp != m or kp != k:
        x = jnp.pad(x, ((0, mp - m), (0, kp - k)))
    if kp != k or np_ != n:
        q = jnp.pad(q, ((0, kp - k), (0, np_ - n)))
    if np_ != n:
        # padded columns are sliced off below; 1.0 keeps the scales benign
        s2d = jnp.pad(s2d, ((0, 0), (0, np_ - n)), constant_values=1.0)

    if group_size is not None:
        s_index = lambda i, j, kk: (kk, j)  # noqa: E731 — block index map
    else:
        s_index = lambda i, j, kk: (0, j)  # noqa: E731 — block index map

    out = pl.pallas_call(
        _dequant_matmul_kernel,
        grid=(mp // bm, np_ // bn, kp // bk),
        in_specs=[
            pl.BlockSpec((bm, bk), lambda i, j, kk: (i, kk)),
            pl.BlockSpec((bk, bn), lambda i, j, kk: (kk, j)),
            pl.BlockSpec((1, bn), s_index),
        ],
        out_specs=pl.BlockSpec((bm, bn), lambda i, j, kk: (i, j)),
        out_shape=jax.ShapeDtypeStruct((mp, np_), out_dtype),
        scratch_shapes=[pltpu.VMEM((bm, bn), jnp.float32)],
        compiler_params=_CompilerParams(
            # M/N tiles are independent; only K carries the accumulator
            dimension_semantics=("parallel", "parallel", "arbitrary"),
        ),
        interpret=interpret,
    )(x, q, s2d)
    if mp != m or np_ != n:
        out = out[:m, :n]
    return out


def _resolve_impl(impl: Optional[str]) -> str:
    impl = impl or os.environ.get("PIT_QMM_IMPL") or (
        "pallas" if jax.default_backend() == "tpu" else "xla")
    if impl not in _VALID_IMPLS:
        # a typo'd impl must not silently fall through to the XLA branch and
        # get benchmarked under the wrong label (same rule as attn_impl)
        raise ValueError(
            f"unknown quantized-matmul impl {impl!r}; expected one of "
            f"{_VALID_IMPLS} (PIT_QMM_IMPL overrides)")
    return impl


def _blocks_compile_safe(bm: int, bk: int, bn: int) -> bool:
    """Mosaic tiling legality for COMPILED kernels: int8/int4 weight tiles
    need second-minor multiples of 32 and lane multiples of 128. Interpret
    mode (CPU tests) has no such constraint and skips this gate."""
    return bm % _SUBLANES == 0 and bk % _INT_SUBLANES == 0 and bn % _LANES == 0


def quantized_matmul(x: Array, w: QKernel, impl: Optional[str] = None) -> Array:
    """``x (..., K) @ w`` for a :class:`QKernel` weight, in its compute dtype.

    Dispatch: ``impl`` arg > ``PIT_QMM_IMPL`` env (read at trace time, like
    ``PIT_DRYRUN_ATTN``) > backend default (pallas on TPU, xla elsewhere —
    off-TPU the kernel only runs in interpreter mode, orders of magnitude
    slower; explicit ``impl='pallas'`` keeps that fallback for tests). On
    TPU, geometries the conservative tiling gate cannot prove legal fall
    back to the XLA dequant path rather than risk a remote-compile OOM —
    the r3 lesson: those 500s are real scoped-VMEM OOMs, not flakiness.
    """
    impl = _resolve_impl(impl)
    compute = jnp.dtype(w.compute_dtype)
    k, n = w.q.shape
    gs = w.group_size
    if impl == "pallas":
        lead = x.shape[:-1]
        x2 = x.reshape(-1, k).astype(compute)
        m = x2.shape[0]
        interpret = jax.default_backend() != "tpu"
        bm, bk, bn = _auto_blocks(m, k, n, compute.itemsize, compute.itemsize,
                                  gs)
        if interpret or _blocks_compile_safe(bm, bk, bn):
            out = dequant_matmul(
                x2, w.q, w.scale, out_dtype=compute, group_size=gs,
                interpret=interpret,
            )
            return out.reshape(*lead, n)
    # XLA path: dequantize feeds the matmul operand read (r8 fusion)
    return (x.astype(compute) @ w.dequantize()).astype(compute)


def linear_apply(x: Array, w, b, dtype) -> Array:
    """The ``_LinearParams`` apply: ``x @ w + b`` under flax dtype promotion
    — except a :class:`QKernel` weight routes to :func:`quantized_matmul`,
    which is the whole point of carrying quantized kernels through the tree
    as structured leaves rather than pre-dequantized tensors."""
    if isinstance(w, QKernel):
        y = quantized_matmul(x, w)
        if b is not None:
            y = y + jnp.asarray(b, y.dtype)
        return y
    if b is None:
        x, w = _flax_dtypes.promote_dtype(x, w, dtype=dtype)
        return x @ w
    x, w, b = _flax_dtypes.promote_dtype(x, w, b, dtype=dtype)
    return x @ w + b
