from perceiver_io_tpu.ops.attention import (
    MultiHeadAttention,
    CrossAttention,
    SelfAttention,
    CrossAttentionLayer,
    SelfAttentionLayer,
    SelfAttentionBlock,
    MLP,
)
from perceiver_io_tpu.ops.fourier import (
    spatial_positions,
    fourier_position_encodings,
    num_position_encoding_channels,
)
from perceiver_io_tpu.ops.masking import IGNORE_LABEL, TextMasking, apply_text_masking

# Pallas kernels resolve lazily (PEP 562) so `import perceiver_io_tpu.ops`
# stays light — jax.experimental.pallas only loads when a kernel is touched,
# matching the deferred imports on MultiHeadAttention's dispatch path.
_LAZY = {"fused_attention", "packed_latent_attention",
         "seq_parallel_fused_attention"}


def __getattr__(name):
    if name in _LAZY:
        from perceiver_io_tpu.ops import pallas_attention

        return getattr(pallas_attention, name)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")

__all__ = [
    "MultiHeadAttention",
    "CrossAttention",
    "SelfAttention",
    "CrossAttentionLayer",
    "SelfAttentionLayer",
    "SelfAttentionBlock",
    "MLP",
    "spatial_positions",
    "fourier_position_encodings",
    "num_position_encoding_channels",
    "IGNORE_LABEL",
    "TextMasking",
    "apply_text_masking",
    "fused_attention",
    "packed_latent_attention",
    "seq_parallel_fused_attention",
]
