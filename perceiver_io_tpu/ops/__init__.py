from perceiver_io_tpu.ops.attention import (
    MultiHeadAttention,
    CrossAttention,
    SelfAttention,
    CrossAttentionLayer,
    SelfAttentionLayer,
    SelfAttentionBlock,
    MLP,
)
from perceiver_io_tpu.ops.fourier import (
    spatial_positions,
    fourier_position_encodings,
    num_position_encoding_channels,
)
from perceiver_io_tpu.ops.masking import TextMasking, apply_text_masking

__all__ = [
    "MultiHeadAttention",
    "CrossAttention",
    "SelfAttention",
    "CrossAttentionLayer",
    "SelfAttentionLayer",
    "SelfAttentionBlock",
    "MLP",
    "spatial_positions",
    "fourier_position_encodings",
    "num_position_encoding_channels",
    "TextMasking",
    "apply_text_masking",
]
