"""Fused latent-attention Pallas kernel (TPU).

Covers the Perceiver hot path — attention of a small resident query block
(latents or output queries) against a KV stream — as one fused kernel:
QK^T, masking, online softmax, and PV accumulation never round-trip to HBM,
and the KV sequence is streamed block-by-block so the input length M is never
fully resident in VMEM (the blockwise cross-attention called for in
SURVEY.md §5). This replaces the reference's ``torch.nn.MultiheadAttention``
CUDA kernels (reference ``perceiver/model.py:66-74``).

Design:

- grid ``(B, H, T/T_blk, S/S_blk)``; the KV axis is the innermost (sequential)
  grid dimension, so the running max / denominator / PV accumulator live in
  VMEM scratch across KV blocks (the standard TPU flash-attention recurrence).
  The query axis is blocked too, so large query counts (e.g. the flow
  decoder's dense 2D queries) stay inside the ~16MB VMEM scoped limit.
- logits and the accumulator are f32 regardless of input dtype; the P·V
  matmul feeds the MXU in the input dtype with f32 accumulation.
- padding (``pad_mask`` True = masked out) enters as a finite additive bias,
  reproducing the XLA path's semantics including the fully-masked-row case
  (uniform probabilities) without NaNs.
- backward: fused flash backward — two Pallas kernels (dq; dk/dv) recompute
  the probabilities blockwise as exp(logits − m)/l from the saved softmax
  max ``m`` and denominator ``l``, so the (T, S) logits never materialize in
  HBM in either direction. ``m``/``l`` are saved lane-broadcast as
  (B, H, T, 128) f32 (the layout jax's own TPU flash-attention kernel uses —
  sublane↔lane moves are not free on Mosaic) and kept separate rather than
  folded into a logsumexp, which would absorb log l on fully padded rows
  (m = -1e30 in f32); ``delta = Σ_d g·out`` is computed in XLA and passed in
  the same layout. On a fully padded row the probabilities recompute as
  uniform 1/l (the -1e30 bias absorbs the logits in f32 rounding), ``dv``
  keeps the uniform contribution, and ``ds`` is zeroed so dq/dk match the
  XLA path's where-style masking (zero grads through the mask).

Contract (enforced by the dispatcher in ``ops.attention``): no attention-prob
dropout, optional key padding mask only.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# the TPUCompilerParams -> CompilerParams rename landed in newer jax; alias
# whichever spelling this build ships
_CompilerParams = getattr(pltpu, "CompilerParams", None) or pltpu.TPUCompilerParams

Array = jax.Array

# Finite stand-in for -inf: exp() underflows to exactly 0 against any live
# logit, while a fully-masked row still softmaxes to uniform (XLA-path parity).
MASK_VALUE = -1e30
# Bias for keys the kernel itself padded in: strictly below MASK_VALUE so that
# even a fully-masked row's uniform softmax excludes them (exp(PAD - MASK) = 0).
PAD_BIAS = 2.0 * MASK_VALUE

_LANES = 128
DEFAULT_KV_BLOCK = 512
DEFAULT_Q_BLOCK = 512
# Test hook (tests/test_pallas_attention.py fuzz): force the COMPILED lane
# alignment while running the kernel in interpret mode, so CPU property
# tests drive the exact divisor/padding/full-residency resolution branches
# hardware takes (interpret alone resolves with alignment=1, which skips
# them all — the two resolution bugs on record, the 131k row-divisor
# pathology and the awkward-S guard ordering, were only ever reachable at
# lane alignment). None = derive from ``interpret`` as usual.
_TEST_ALIGNMENT: Optional[int] = None
# Larger query blocks measure +3.7-5.1% at streamed-KV shapes (flow
# encoder-cross sweep, PERF.md r3), but VMEM safety depends on the RESOLVED
# block triple, not the raw shape: the sweep's compile boundary at d=512 is
# (t_blk 1024, s_blk 256) OK vs (t_blk 1024, s_blk 512) an 18 MB > 16 MB
# scoped-VMEM OOM in the dkv backward. The auto bump (``q_block_size=None``,
# applied inside ``_prepare_blocks`` AFTER s_blk resolution) therefore
# requires ALL of: the resolved s_blk·d product within the measured-safe
# 256×512 bound, d within the sweep's measured range (≤ 512 — a deeper head
# would grow the 1024-row query block + f32 accumulator past anything
# measured even when s_blk·d stays small), and T dividing the big block
# exactly (no query padding and no widening of the full-residency
# ``t <= 2·q_block`` fallback — shapes the sweep never measured).
LONG_KV_Q_BLOCK = 1024
LONG_KV_SAFE_SBLK_D = 256 * 512
LONG_KV_MAX_D = 512
# The q bump additionally keeps the per-block probs area t_blk·s_blk inside
# the measured compile region: 1024·1024 and 512·2048 elements compile,
# 1024·2048 is a remote-compile OOM (long-context kv sweep, PERF.md r3).
LONG_KV_SAFE_PROBS = 1024 * 1024

# Auto KV-block sizing (``kv_block_size=None``): streaming more keys per
# sequential grid step amortizes per-step kernel overhead, and how much VMEM
# that costs scales with d. Measured (PERF.md r3 kv sweep, fwd+bwd): d=16
# S=131k kv 512→2048 is 3.47→2.45 ms (and 2048 + q capped at 512 beats
# 512 + q 1024 everywhere tried); d=64 S=2048 (flow-self) 1.34→0.98 ms;
# d=128 was 1024 through r4 (S=50k in-8h 8.55→6.44, with 2048 measuring "no
# better" that session) — re-swept in r5 at the TPU-width long-context
# shapes, where the sequential grid is longer and b·h parallelism smaller:
# kv2048 wins 9-12% at (1,256,131k,4,128)/(8,256,8k,4,128) AND re-measures
# ahead at in-8h itself (7.44-7.65 vs 7.81-7.85 ms, interleaved ×2), so the
# d≤128 tier is now 2048. kv4096 measured a further ~3% at t=256 shapes but
# is a REAL remote-compile OOM at in-8h's t=512 (probs area 512·4096 = 2M >
# the 1M boundary — the guard below must shrink it, so the tier stays 2048);
# d=512 kv ≥ 1024 is the flow sweep's measured scoped-VMEM OOM, so deep
# heads stay at 512. The measured KV-side footprint envelope is now
# s_blk·d ≤ 2048·128 = 262144 (compile-checked at the r5 sweep shapes and
# by tools/kernel_smoke.py per round); S shorter than the block resolves to
# full-dim/divisor blocks exactly as an explicit request would.


def _auto_kv_block(
    s: int, d: int, t: int, alignment: int, q_block_size: Optional[int]
) -> int:
    if d <= 128:
        kv = 2048
    else:
        return DEFAULT_KV_BLOCK
    # The widened KV block must keep the resolved (t_blk, s_blk) probs area
    # inside the measured compile boundary for EVERY way t_blk can resolve:
    # an explicit q_block_size (mirroring _prepare_blocks's resolution), and
    # the full-residency fallback (t_blk = t when T has no aligned divisor
    # but fits two blocks). The auto q-bump branch carries its own guard.
    qb = DEFAULT_Q_BLOCK if q_block_size is None else q_block_size
    tb = _kv_block_size(t, qb, alignment)
    if tb == 0:
        t_bound = t if t <= 2 * qb else max(qb - qb % alignment, alignment)
    else:
        t_bound = tb
    while kv > DEFAULT_KV_BLOCK and t_bound * kv > LONG_KV_SAFE_PROBS:
        kv //= 2
    if (kv > DEFAULT_KV_BLOCK
            and _kv_block_size(s, kv, alignment) == 0
            and 4 * DEFAULT_KV_BLOCK < s <= 4 * kv):
        # Checked against the POST-shrink kv (the probs loop above can halve
        # it, changing which divisors exist): S with no block-aligned divisor
        # that sits inside the widened block's full-residency fallback window
        # (s <= 4·kv ⇒ s_blk = s, unmeasured probs/VMEM territory) but
        # outside the default's keeps the tuned 512 path; larger awkward S
        # takes the pad-to-block path and keeps the widened block.
        return DEFAULT_KV_BLOCK
    return kv


def _dot(a, b, contract):
    """MXU matmul contracting ``contract`` = (a_dim, b_dim), f32 accumulation.

    The MXU multiplies in bf16; for f32 operands a single pass loses ~3
    decimal digits vs XLA's einsum (which defaults to multi-pass for f32), so
    request HIGHEST precision there. bf16 operands keep the fast single pass —
    the production bf16 training path pays nothing for this.
    """
    precision = (jax.lax.Precision.HIGHEST
                 if a.dtype == jnp.float32 and b.dtype == jnp.float32 else None)
    return jax.lax.dot_general(
        a, b,
        dimension_numbers=(((contract[0],), (contract[1],)), ((), ())),
        preferred_element_type=jnp.float32,
        precision=precision,
    )


def _kv_block_size(s: int, requested: int, alignment: int) -> int:
    """KV length to stream per grid step: a divisor of S, aligned to the TPU
    tile constraint (Mosaic requires block dims to be lane/sublane multiples
    or equal to the full array dim). Returns 0 when S must be padded instead."""
    requested = max(requested - requested % alignment, alignment)
    if s <= requested:
        return s  # single block — full-dim blocks are always legal
    best = 0
    for cand in range(requested, alignment - 1, -alignment):
        if s % cand == 0:
            best = cand
            break
    # a tiny block (many grid steps) is worse than padding to a full block
    return best if best * 2 >= requested else 0


def _causal_bias(t_blk: int, s_blk: int, t_idx, s_idx, offset: int):
    """(T_blk, S_blk) additive causal bias for the current grid tile: query
    row i (GLOBAL row ``t_idx*t_blk + i``, absolute position row + offset)
    may attend key ``j <= row + offset`` — the in-kernel twin of
    ``ops.masking.causal_mask``. Additive MASK_VALUE (not a where) so a
    fully-masked row keeps the uniform-softmax semantics of the pad path."""
    rows = t_idx * t_blk + jax.lax.broadcasted_iota(
        jnp.int32, (t_blk, s_blk), 0)
    cols = s_idx * s_blk + jax.lax.broadcasted_iota(
        jnp.int32, (t_blk, s_blk), 1)
    return jnp.where(cols > rows + offset, MASK_VALUE, 0.0)


def _attention_kernel(bias_ref, q_ref, k_ref, v_ref, out_ref, *rest,
                      scale: float, with_lse: bool,
                      causal_offset: Optional[int]):
    if with_lse:
        m_out, l_out, m_ref, l_ref, acc_ref = rest
        lse_ref = (m_out, l_out)
    else:
        lse_ref, (m_ref, l_ref, acc_ref) = None, rest
    s_idx = pl.program_id(3)

    @pl.when(s_idx == 0)
    def _init():
        m_ref[:] = jnp.full_like(m_ref, MASK_VALUE)
        l_ref[:] = jnp.zeros_like(l_ref)
        acc_ref[:] = jnp.zeros_like(acc_ref)

    q = q_ref[0, 0]  # (T_blk, D)
    k = k_ref[0, 0]  # (S_blk, D)
    logits = _dot(q, k, (1, 1)) * scale  # (T_blk, S_blk)
    logits += bias_ref[0]  # (1, S_blk) broadcasts over T_blk
    if causal_offset is not None:
        logits += _causal_bias(q.shape[0], k.shape[0], pl.program_id(2),
                               s_idx, causal_offset)

    m_prev = m_ref[:, :1]  # (T_blk, 1)
    l_prev = l_ref[:, :1]
    m_cur = jnp.max(logits, axis=-1, keepdims=True)
    m_new = jnp.maximum(m_prev, m_cur)
    alpha = jnp.exp(m_prev - m_new)
    p = jnp.exp(logits - m_new)  # (T_blk, S_blk)

    l_new = alpha * l_prev + jnp.sum(p, axis=-1, keepdims=True)
    pv = _dot(p.astype(v_ref.dtype), v_ref[0, 0], (1, 0))  # (T_blk, D)
    acc_ref[:] = acc_ref[:] * alpha + pv
    m_ref[:] = jnp.broadcast_to(m_new, m_ref.shape)
    l_ref[:] = jnp.broadcast_to(l_new, l_ref.shape)

    @pl.when(s_idx == pl.num_programs(3) - 1)
    def _finish():
        out_ref[0, 0] = (acc_ref[:] / l_ref[:, :1]).astype(out_ref.dtype)
        if with_lse:
            m_out_ref, l_out_ref = lse_ref
            m_out_ref[0, 0] = m_ref[:]
            l_out_ref[0, 0] = l_ref[:]


@functools.partial(
    jax.jit,
    static_argnames=("t_blk", "s_blk", "interpret", "with_lse",
                     "causal_offset"),
)
def _fused_attention_fwd_impl(
    q: Array, k: Array, v: Array, bias: Array,
    t_blk: int, s_blk: int, interpret: bool, with_lse: bool = False,
    causal_offset: Optional[int] = None,
):
    """(B, H, T, D) q against (B, H, S, D) k/v with (B, S) additive bias.
    ``t_blk``/``s_blk`` must divide T/S (the wrapper guarantees it).
    With ``with_lse`` also returns the softmax running max ``m`` and
    denominator ``l``, each lane-broadcast to (B, H, T, LANES) f32, for the
    fused backward. They are saved separately — not as ``m + log l`` — so a
    fully padded row (m pinned at MASK_VALUE, which absorbs log l in f32)
    still recomputes exactly as exp(logits − m)/l."""
    b, h, t, d = q.shape
    s = k.shape[2]
    scale = d**-0.5
    grid = (b, h, t // t_blk, s // s_blk)

    out_shape = jax.ShapeDtypeStruct((b, h, t, d), q.dtype)
    out_specs = pl.BlockSpec((1, 1, t_blk, d), lambda bi, hi, ti, si: (bi, hi, ti, 0))
    if with_lse:
        lm_shape = jax.ShapeDtypeStruct((b, h, t, _LANES), jnp.float32)
        lm_spec = pl.BlockSpec((1, 1, t_blk, _LANES),
                               lambda bi, hi, ti, si: (bi, hi, ti, 0))
        out_shape = (out_shape, lm_shape, lm_shape)
        out_specs = (out_specs, lm_spec, lm_spec)

    bias = bias[:, None, :]  # (B, 1, S)
    kernel = pl.pallas_call(
        functools.partial(_attention_kernel, scale=scale, with_lse=with_lse,
                          causal_offset=causal_offset),
        grid=grid,
        in_specs=[
            # (B, 1, S) so the block's trailing dims satisfy TPU tiling
            pl.BlockSpec((1, 1, s_blk), lambda bi, hi, ti, si: (bi, 0, si)),
            pl.BlockSpec((1, 1, t_blk, d), lambda bi, hi, ti, si: (bi, hi, ti, 0)),
            pl.BlockSpec((1, 1, s_blk, d), lambda bi, hi, ti, si: (bi, hi, si, 0)),
            pl.BlockSpec((1, 1, s_blk, d), lambda bi, hi, ti, si: (bi, hi, si, 0)),
        ],
        out_specs=out_specs,
        out_shape=out_shape,
        scratch_shapes=[
            pltpu.VMEM((t_blk, _LANES), jnp.float32),  # running max
            pltpu.VMEM((t_blk, _LANES), jnp.float32),  # running denominator
            pltpu.VMEM((t_blk, d), jnp.float32),  # PV accumulator
        ],
        compiler_params=_CompilerParams(
            # batch/head/query-block grid steps are independent; only the KV
            # axis carries the softmax recurrence
            dimension_semantics=("parallel", "parallel", "parallel", "arbitrary"),
        ),
        interpret=interpret,
    )
    return kernel(bias, q, k, v)


def _recompute_probs_and_ds(bias_ref, q_ref, k_ref, v_ref, g_ref,
                            m_ref, l_ref, di_ref, *, scale: float,
                            causal_offset: Optional[int],
                            t_idx, s_idx):
    """Shared backward tile math: recompute p = exp(logits − m)/l for this
    (T_blk, S_blk) tile and the softmax gradient ds = p·(dp − delta).

    ds is zeroed on fully padded rows (m pinned at MASK_VALUE) so dq/dk
    reproduce the XLA path's where-masking; p is left intact there (uniform
    1/l) because dv keeps the uniform contribution on that path. With a
    ``causal_offset`` the tile recomputes the same in-kernel causal bias the
    forward applied (``t_idx``/``s_idx`` are the GLOBAL query/key block
    indices — the two backward kernels run swapped grids, so the caller
    passes whichever program_id carries each axis)."""
    q = q_ref[0, 0]  # (T_blk, D)
    k = k_ref[0, 0]  # (S_blk, D)
    g = g_ref[0, 0]  # (T_blk, D)
    logits = _dot(q, k, (1, 1)) * scale  # (T_blk, S_blk)
    logits += bias_ref[0]  # (1, S_blk) broadcasts over T_blk
    if causal_offset is not None:
        logits += _causal_bias(q.shape[0], k.shape[0], t_idx, s_idx,
                               causal_offset)
    m = m_ref[0, 0][:, :1]  # (T_blk, 1)
    l = l_ref[0, 0][:, :1]
    p = jnp.exp(logits - m) / l
    dp = _dot(g, v_ref[0, 0], (1, 1))  # (T_blk, S_blk)
    ds = p * (dp - di_ref[0, 0][:, :1])
    ds = jnp.where(m <= 0.5 * MASK_VALUE, 0.0, ds)
    return p, ds, q, k, g


def _bwd_dq_kernel(bias_ref, q_ref, k_ref, v_ref, g_ref, m_ref, l_ref, di_ref,
                   dq_ref, acc_ref, *, scale: float,
                   causal_offset: Optional[int]):
    s_idx = pl.program_id(3)

    @pl.when(s_idx == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)

    _, ds, _, k, _ = _recompute_probs_and_ds(
        bias_ref, q_ref, k_ref, v_ref, g_ref, m_ref, l_ref, di_ref,
        scale=scale, causal_offset=causal_offset,
        t_idx=pl.program_id(2), s_idx=s_idx,
    )
    acc_ref[:] += _dot(ds.astype(k.dtype), k, (1, 0))  # (T_blk, D)

    @pl.when(s_idx == pl.num_programs(3) - 1)
    def _finish():
        dq_ref[0, 0] = (acc_ref[:] * scale).astype(dq_ref.dtype)


def _bwd_dkv_kernel(bias_ref, q_ref, k_ref, v_ref, g_ref, m_ref, l_ref, di_ref,
                    dk_ref, dv_ref, dk_acc, dv_acc, *, scale: float,
                    causal_offset: Optional[int]):
    t_idx = pl.program_id(3)

    @pl.when(t_idx == 0)
    def _init():
        dk_acc[:] = jnp.zeros_like(dk_acc)
        dv_acc[:] = jnp.zeros_like(dv_acc)

    p, ds, q, _, g = _recompute_probs_and_ds(
        bias_ref, q_ref, k_ref, v_ref, g_ref, m_ref, l_ref, di_ref,
        scale=scale, causal_offset=causal_offset,
        t_idx=t_idx, s_idx=pl.program_id(2),
    )
    # contract the query axis: (T_blk, S_blk)ᵀ·(T_blk, D) → (S_blk, D)
    dv_acc[:] += _dot(p.astype(g.dtype), g, (0, 0))
    dk_acc[:] += _dot(ds.astype(q.dtype), q, (0, 0))

    @pl.when(t_idx == pl.num_programs(3) - 1)
    def _finish():
        dk_ref[0, 0] = (dk_acc[:] * scale).astype(dk_ref.dtype)
        dv_ref[0, 0] = dv_acc[:].astype(dv_ref.dtype)


@functools.partial(
    jax.jit,
    static_argnames=("t_blk", "s_blk", "interpret", "causal_offset"),
)
def _fused_attention_bwd_impl(
    q: Array, k: Array, v: Array, bias: Array, out: Array,
    m: Array, l: Array,
    g: Array, t_blk: int, s_blk: int, interpret: bool,
    causal_offset: Optional[int] = None,
):
    b, h, t, d = q.shape
    s = k.shape[2]
    scale = d**-0.5

    # delta = Σ_d g·out per query row, lane-broadcast like lse
    di = jnp.sum(g.astype(jnp.float32) * out.astype(jnp.float32), axis=-1)
    di = jnp.broadcast_to(di[..., None], (b, h, t, _LANES))

    bias = bias[:, None, :]  # (B, 1, S)
    qo_spec = pl.BlockSpec((1, 1, t_blk, d), lambda bi, hi, ti, si: (bi, hi, ti, 0))
    kv_spec = pl.BlockSpec((1, 1, s_blk, d), lambda bi, hi, ti, si: (bi, hi, si, 0))
    lm_spec = pl.BlockSpec((1, 1, t_blk, _LANES),
                           lambda bi, hi, ti, si: (bi, hi, ti, 0))
    bias_spec = pl.BlockSpec((1, 1, s_blk), lambda bi, hi, ti, si: (bi, 0, si))

    dq = pl.pallas_call(
        functools.partial(_bwd_dq_kernel, scale=scale,
                          causal_offset=causal_offset),
        grid=(b, h, t // t_blk, s // s_blk),  # KV axis sequential
        in_specs=[bias_spec, qo_spec, kv_spec, kv_spec, qo_spec,
                  lm_spec, lm_spec, lm_spec],
        out_specs=qo_spec,
        out_shape=jax.ShapeDtypeStruct(q.shape, q.dtype),
        scratch_shapes=[pltpu.VMEM((t_blk, d), jnp.float32)],
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "parallel", "parallel", "arbitrary"),
        ),
        interpret=interpret,
    )(bias, q, k, v, g, m, l, di)

    # dkv grid puts the query axis innermost (sequential): same index maps
    # apply, with ti/si read from swapped grid positions
    qo_spec2 = pl.BlockSpec((1, 1, t_blk, d), lambda bi, hi, si, ti: (bi, hi, ti, 0))
    kv_spec2 = pl.BlockSpec((1, 1, s_blk, d), lambda bi, hi, si, ti: (bi, hi, si, 0))
    lm_spec2 = pl.BlockSpec((1, 1, t_blk, _LANES),
                            lambda bi, hi, si, ti: (bi, hi, ti, 0))
    bias_spec2 = pl.BlockSpec((1, 1, s_blk), lambda bi, hi, si, ti: (bi, 0, si))
    dk, dv = pl.pallas_call(
        functools.partial(_bwd_dkv_kernel, scale=scale,
                          causal_offset=causal_offset),
        grid=(b, h, s // s_blk, t // t_blk),  # query axis sequential
        in_specs=[bias_spec2, qo_spec2, kv_spec2, kv_spec2, qo_spec2,
                  lm_spec2, lm_spec2, lm_spec2],
        out_specs=(kv_spec2, kv_spec2),
        out_shape=(jax.ShapeDtypeStruct(k.shape, k.dtype),
                   jax.ShapeDtypeStruct(v.shape, v.dtype)),
        scratch_shapes=[pltpu.VMEM((s_blk, d), jnp.float32),
                        pltpu.VMEM((s_blk, d), jnp.float32)],
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "parallel", "parallel", "arbitrary"),
        ),
        interpret=interpret,
    )(bias, q, k, v, g, m, l, di)
    return dq, dk, dv


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6, 7))
def _fused_attention(q, k, v, bias, t_blk, s_blk, interpret, causal_offset):
    return _fused_attention_fwd_impl(q, k, v, bias, t_blk, s_blk, interpret,
                                     causal_offset=causal_offset)


def _fwd(q, k, v, bias, t_blk, s_blk, interpret, causal_offset):
    out, m, l = _fused_attention_fwd_impl(
        q, k, v, bias, t_blk, s_blk, interpret, with_lse=True,
        causal_offset=causal_offset,
    )
    return out, (q, k, v, bias, out, m, l)


def _bwd(t_blk, s_blk, interpret, causal_offset, residuals, g):
    q, k, v, bias, out, m, l = residuals
    dq, dk, dv = _fused_attention_bwd_impl(
        q, k, v, bias, out, m, l, g, t_blk, s_blk, interpret,
        causal_offset=causal_offset,
    )
    return dq, dk, dv, jnp.zeros_like(bias)


_fused_attention.defvjp(_fwd, _bwd)


def _prepare_blocks(q, k, v, bias, kv_block_size, q_block_size, interpret):
    """Shared preamble: heads-major transpose, KV/query block sizing, and
    tiling-legal padding. Returns ``(q, k, v, bias, t_blk, s_blk, t_pad)``
    with q/k/v in (B, H, T/S, D) layout. ``q_block_size=None`` resolves per
    shape after s_blk is known (see LONG_KV_Q_BLOCK)."""
    t = q.shape[1]
    s = k.shape[1]
    d = q.shape[-1]

    # heads-major layout so each (b, h) grid step reads contiguous KV rows
    q = jnp.transpose(q, (0, 2, 1, 3))
    k = jnp.transpose(k, (0, 2, 1, 3))
    v = jnp.transpose(v, (0, 2, 1, 3))

    # Stream the KV axis in blocks. Compiled TPU blocks must be lane-aligned
    # (a multiple of 128, or the full dim); when S has no aligned divisor, pad
    # it up to a block multiple with PAD_BIAS keys (excluded from the softmax
    # even on fully-masked rows).
    alignment = _TEST_ALIGNMENT or (1 if interpret else _LANES)
    if kv_block_size is None:
        kv_block_size = _auto_kv_block(s, d, t, alignment, q_block_size)
    s_blk = _kv_block_size(s, kv_block_size, alignment)
    if s_blk == 0:
        if s <= 4 * kv_block_size:
            s_blk = s  # full-dim blocks are always tiling-legal; skip padding
        else:
            block = max(kv_block_size - kv_block_size % alignment, alignment)
            s_pad = -s % block
            k = jnp.pad(k, ((0, 0), (0, 0), (0, s_pad), (0, 0)))
            v = jnp.pad(v, ((0, 0), (0, 0), (0, s_pad), (0, 0)))
            bias = jnp.pad(bias, ((0, 0), (0, s_pad)), constant_values=PAD_BIAS)
            s_blk = block

    if q_block_size is None:
        # auto: the big query block only in its measured-safe regime (see
        # the LONG_KV_Q_BLOCK note — both guards are load-bearing)
        if (t % LONG_KV_Q_BLOCK == 0 and d <= LONG_KV_MAX_D
                and s_blk * d <= LONG_KV_SAFE_SBLK_D
                and s_blk * LONG_KV_Q_BLOCK <= LONG_KV_SAFE_PROBS):
            q_block_size = LONG_KV_Q_BLOCK
        else:
            q_block_size = DEFAULT_Q_BLOCK

    # Block the query axis too: a fully resident query block (plus its f32
    # accumulator and double-buffered output) blows the VMEM scoped limit once
    # T reaches a few thousand (e.g. dense flow decoder queries). Padded query
    # rows attend normally and are sliced off after.
    t_pad = 0
    t_blk = _kv_block_size(t, q_block_size, alignment)
    if t_blk == 0:
        if t <= 2 * q_block_size:
            t_blk = t
        else:
            t_blk = max(q_block_size - q_block_size % alignment, alignment)
            t_pad = -t % t_blk
            q = jnp.pad(q, ((0, 0), (0, 0), (0, t_pad), (0, 0)))

    return q, k, v, bias, t_blk, s_blk, t_pad


def fused_attention(
    q: Array,
    k: Array,
    v: Array,
    pad_mask: Optional[Array] = None,
    kv_block_size: Optional[int] = None,
    q_block_size: Optional[int] = None,
    interpret: Optional[bool] = None,
    causal_offset: Optional[int] = None,
) -> Array:
    """Fused multi-head attention over (B, T, H, D) q and (B, S, H, D) k/v.

    ``pad_mask``: optional (B, S) bool, True = key position masked out (the
    torch ``key_padding_mask`` convention). ``causal_offset``: static int —
    query row i may attend key positions ``<= i + causal_offset`` (the
    ``ops.masking.causal_mask`` rule applied IN-KERNEL as an additive bias,
    never a materialized (T, S) mask; composes with ``pad_mask`` by
    addition, i.e. OR). 0 = square causal self-attention; L − N = the
    Perceiver-AR latent-window cross-attention. Covers forward AND both
    backward kernels. ``kv_block_size=None`` (default) resolves per shape —
    wider KV streaming for shallow heads at long S (see ``_auto_kv_block``);
    ``q_block_size=None`` (default) resolves per shape after KV-block sizing
    (see LONG_KV_Q_BLOCK). Off-TPU backends run the kernel in interpreter
    mode (slow — for tests), overridable via ``interpret``.
    """
    if q.ndim != 4 or k.ndim != 4 or v.ndim != 4:
        raise ValueError(f"expected (B, T/S, H, D) tensors, got {q.shape=} {k.shape=}")
    if interpret is None:
        interpret = jax.default_backend() != "tpu"

    b, t, h, d = q.shape
    s = k.shape[1]
    if pad_mask is None:
        bias = jnp.zeros((b, s), jnp.float32)
    else:
        bias = jnp.where(pad_mask, MASK_VALUE, 0.0).astype(jnp.float32)

    q, k, v, bias, t_blk, s_blk, t_pad = _prepare_blocks(
        q, k, v, bias, kv_block_size, q_block_size, interpret
    )
    out = _fused_attention(
        q, k, v, bias, t_blk, s_blk, interpret,
        None if causal_offset is None else int(causal_offset),
    )
    if t_pad:
        out = out[:, :, :t]
    return jnp.transpose(out, (0, 2, 1, 3))


# -- sequence-parallel fused attention ---------------------------------------
#
# The distributed-flash combine: each device runs the streaming kernel over
# its LOCAL KV shard, then the per-shard softmax statistics (running max m,
# denominator l) merge across the mesh axis with one pmax + two psums — the
# Perceiver-shaped equivalent of ring attention (latents/queries are
# replicated along the axis and S is the only long dimension, so a single
# all-reduce of O(B·H·T) stats replaces a ring of KV exchanges). The
# backward reruns the flash backward per shard against the GLOBAL (m, l)
# and psums only dq (dk/dv stay shard-local).


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6, 7))
def _sp_fused(q, k, v, bias, t_blk, s_blk, interpret, axis):
    out, _, _ = _sp_forward(q, k, v, bias, t_blk, s_blk, interpret, axis)
    return out


def _sp_forward(q, k, v, bias, t_blk, s_blk, interpret, axis):
    out_l, m_l, l_l = _fused_attention_fwd_impl(
        q, k, v, bias, t_blk, s_blk, interpret, with_lse=True
    )
    # the kernel saves stats lane-broadcast as (B, H, T, LANES); collect the
    # collectives on the [:, :, :, :1] slice so each stat all-reduce moves
    # O(B·H·T), not 128x that, then re-broadcast for the backward residuals
    m_g = jax.lax.pmax(m_l[..., :1], axis)
    # a shard whose keys are all padded has m_l pinned at MASK_VALUE: its
    # weight underflows to exactly 0 against any real shard, and when EVERY
    # shard is padded (fully masked row) the weights reduce to l_l > 0 — the
    # same uniform-attention semantics as the single-device kernel
    w = jnp.exp(m_l[..., :1] - m_g) * l_l[..., :1]  # (B, H, T, 1) f32
    l_g = jax.lax.psum(w, axis)
    out = jax.lax.psum(out_l.astype(jnp.float32) * (w / l_g), axis)
    bcast = lambda x: jnp.broadcast_to(x, x.shape[:-1] + (m_l.shape[-1],))
    return out.astype(out_l.dtype), bcast(m_g), bcast(l_g)


def _sp_fwd(q, k, v, bias, t_blk, s_blk, interpret, axis):
    out, m_g, l_g = _sp_forward(q, k, v, bias, t_blk, s_blk, interpret, axis)
    return out, (q, k, v, bias, out, m_g, l_g)


def _sp_bwd(t_blk, s_blk, interpret, axis, residuals, g):
    q, k, v, bias, out, m_g, l_g = residuals
    # JAX-version sensitivity: the scaling below encodes shard_map's
    # check_rep=False transpose convention as observed on jax 0.9.x. It is
    # not a documented contract — a future upgrade could change it SILENTLY
    # (gradients off by exactly the product of some mesh axis sizes, forward
    # unchanged). The canary is TestSeqParallelFusedAttention
    # .test_gradients_match_single_device (dp/tp/sp parametrized): if it
    # fails with grads wrong by an integer factor after a JAX upgrade, this
    # is the first place to look.
    #
    # shard_map's transpose conventions under check_rep=False (empirically
    # pinned by the gradient tests across dp/tp/sp mesh mixes): the
    # cotangent of an output replicated over mesh axes arrives DIVIDED by
    # the product of those axis sizes, and the returned input cotangents are
    # psum'd over each input's own unmentioned axes on the way out. Those
    # outgoing psums already restore the factor for every replicated NON-seq
    # axis (each of its replicas computes an identical cotangent), so the
    # only factor to reconstruct here is the seq axis itself — its replicas
    # hold genuinely PARTIAL contributions, not copies.
    g = jax.lax.psum(g, axis)
    # global (m, l) make each shard's recomputed tile probabilities the
    # GLOBAL softmax restricted to its keys; out/g are replicated, so the
    # in-kernel delta = Σ g·out is already global
    dq_partial, dk, dv = _fused_attention_bwd_impl(
        q, k, v, bias, out, m_g, l_g, g, t_blk, s_blk, interpret
    )
    return dq_partial, dk, dv, jnp.zeros_like(bias)


_sp_fused.defvjp(_sp_fwd, _sp_bwd)


def seq_parallel_fused_attention(
    q: Array,
    k: Array,
    v: Array,
    pad_mask: Optional[Array] = None,
    *,
    mesh,
    axis: str = "seq",
    batch_axis: Optional[str] = None,
    head_axis: Optional[str] = None,
    kv_block_size: Optional[int] = None,
    q_block_size: Optional[int] = None,
    interpret: Optional[bool] = None,
) -> Array:
    """:func:`fused_attention` with the KV axis SHARDED over a mesh axis.

    Sequence/context parallelism for the kernel path: under plain ``jit``
    GSPMD cannot partition a ``pallas_call``, so a seq-sharded KV stream gets
    all-gathered before the kernel — the memory benefit of sharding M is
    lost exactly where it matters (SURVEY.md §5's long-context plan). This
    wrapper runs the kernel under ``shard_map`` instead: every device
    processes only its S/n_shards slice of keys/values (O(S/n) HBM and VMEM),
    and the softmax statistics merge with one ``pmax`` + two ``psum`` of
    O(B·H·T) — no ring, because Perceiver attention has replicated queries
    and a single long axis. Gradients: flash backward per shard against the
    global statistics; only dq is psum'd (dk/dv are shard-local like k/v).

    Args mirror :func:`fused_attention`, plus:
      mesh: the ``jax.sharding.Mesh`` to shard over.
      axis: mesh axis name carrying the KV shards (default ``'seq'``).
      batch_axis: optional mesh axis for the leading batch dimension (compose
        with data parallelism).
      head_axis: optional mesh axis for the head dimension (compose with
        tensor parallelism: each device keeps only its H/tp heads — without
        this, a tp mesh axis is unmentioned in the specs and shard_map forces
        an all-gather of all heads onto every device). Heads are independent
        in every matmul and in the softmax-stat merge (the collectives reduce
        over ``axis`` only), so the math is unchanged. The axis size must
        divide H (e.g. 8 heads on tp=4: two heads per device).
    Inputs may be global ``jax.Array``s (sharded or not) or host arrays; S
    must divide evenly by the axis size.
    """
    # jax >= 0.8 moved shard_map to the top level and renamed check_rep to
    # check_vma; support both spellings (this build may ship either)
    try:
        from jax import shard_map
        check_kw = "check_vma"
    except ImportError:
        from jax.experimental.shard_map import shard_map
        check_kw = "check_rep"
    from jax.sharding import PartitionSpec as P

    if q.ndim != 4 or k.ndim != 4 or v.ndim != 4:
        raise ValueError(f"expected (B, T/S, H, D) tensors, got {q.shape=} {k.shape=}")
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    n_shards = mesh.shape[axis]
    b, t, h, d = q.shape
    s = k.shape[1]
    if s % n_shards:
        raise ValueError(
            f"KV length {s} must be divisible by the '{axis}' mesh axis "
            f"size ({n_shards}) — pad S to a multiple"
        )
    if head_axis is not None and h % mesh.shape[head_axis]:
        raise ValueError(
            f"head count {h} must be divisible by the '{head_axis}' mesh "
            f"axis size ({mesh.shape[head_axis]})"
        )
    # q_block_size=None resolves inside _prepare_blocks, which runs on the
    # shard_map-LOCAL arrays — the auto choice sees each device's actual
    # S/n slice and resolved s_blk

    if pad_mask is None:
        bias = jnp.zeros((b, s), jnp.float32)
    else:
        bias = jnp.where(pad_mask, MASK_VALUE, 0.0).astype(jnp.float32)

    def local(q_l, k_l, v_l, bias_l):
        qh, kh, vh, bias_p, t_blk, s_blk, t_pad = _prepare_blocks(
            q_l, k_l, v_l, bias_l, kv_block_size, q_block_size, interpret
        )
        out = _sp_fused(qh, kh, vh, bias_p, t_blk, s_blk, interpret, axis)
        if t_pad:
            out = out[:, :, :t]
        return jnp.transpose(out, (0, 2, 1, 3))

    return shard_map(
        local,
        mesh=mesh,
        in_specs=(
            P(batch_axis, None, head_axis),
            P(batch_axis, axis, head_axis),
            P(batch_axis, axis, head_axis),
            P(batch_axis, axis),
        ),
        out_specs=P(batch_axis, None, head_axis),
        # disable replication/varying-manual-axes checking (check_rep, or its
        # jax>=0.8 successor check_vma) — custom_vjp + collectives confuse
        # it. The transpose convention _sp_bwd compensates for is pinned by
        # the gradient-parity tests; see its docstring.
        **{check_kw: False},
    )(q, k, v, bias)


# -- packed-heads latent kernel ----------------------------------------------
#
# The streaming kernel above pays for its generality at the Perceiver's OWN
# shapes: with E = 64 channels over H = 4 heads, per-head (T, 16) operands
# waste 7/8 of every (8, 128) memory tile and feed the MXU 16-wide
# contractions. This kernel instead reads the PACKED (B, T, E) tensors —
# never materializing a head-split layout in HBM — and computes each head's
# logits as an E-wide contraction against a channel-masked K:
#
#     logits_h = Q @ (K ⊙ mask_h)^T      (mask_h selects head h's channels)
#     out     += softmax(logits_h) @ (V ⊙ mask_h)
#
# The masked operands add H× MXU work, but at these shapes the step is
# HBM-bound, not FLOP-bound (PERF.md): trading 4× cheap MXU passes for an 8×
# reduction in bytes wins. Softmax and the (T, S) probabilities live only in
# VMEM; the backward recomputes them (flash style) so neither direction puts
# logits in HBM. Grid is (B,) — everything for one example fits in VMEM at
# latent shapes, which the dispatcher enforces (PACKED_MAX_* below).


def _head_masked(x, h: int, d: int):
    col = jax.lax.broadcasted_iota(jnp.int32, x.shape, x.ndim - 1)
    return jnp.where((col >= h * d) & (col < (h + 1) * d), x, 0)


def _packed_fwd_kernel(bias_ref, q_ref, k_ref, v_ref, out_ref, *,
                       num_heads: int, scale: float):
    q = q_ref[0]  # (T, E)
    k = k_ref[0]  # (S, E)
    v = v_ref[0]
    bias = bias_ref[0]  # (1, S), broadcasts over T
    d = q.shape[-1] // num_heads
    acc = jnp.zeros(q.shape, jnp.float32)
    for h in range(num_heads):
        kh = _head_masked(k, h, d)
        vh = _head_masked(v, h, d)
        logits = _dot(q, kh, (1, 1)) * scale + bias  # (T, S) f32, VMEM only
        m = jnp.max(logits, axis=-1, keepdims=True)
        p = jnp.exp(logits - m)
        p = p / jnp.sum(p, axis=-1, keepdims=True)
        # vh is zero outside head h's channels, so each head's PV lands in
        # its own output columns; summing concatenates the heads for free
        acc += _dot(p.astype(v.dtype), vh, (1, 0))
    out_ref[0] = acc.astype(out_ref.dtype)


def _packed_bwd_kernel(bias_ref, q_ref, k_ref, v_ref, g_ref,
                       dq_ref, dk_ref, dv_ref, *, num_heads: int, scale: float):
    q = q_ref[0]
    k = k_ref[0]
    v = v_ref[0]
    g = g_ref[0]  # (T, E) output cotangent
    bias = bias_ref[0]
    d = q.shape[-1] // num_heads
    dq = jnp.zeros(q.shape, jnp.float32)
    dk = jnp.zeros(k.shape, jnp.float32)
    dv = jnp.zeros(v.shape, jnp.float32)
    for h in range(num_heads):
        kh = _head_masked(k, h, d)
        vh = _head_masked(v, h, d)
        gh = _head_masked(g, h, d)
        logits = _dot(q, kh, (1, 1)) * scale + bias
        m = jnp.max(logits, axis=-1, keepdims=True)
        p = jnp.exp(logits - m)
        p = p / jnp.sum(p, axis=-1, keepdims=True)  # (T, S) f32
        dp = _dot(gh.astype(v.dtype), vh, (1, 1))  # (T, S): gh @ vh^T
        delta = jnp.sum(p * dp, axis=-1, keepdims=True)
        ds = p * (dp - delta) * scale
        # fully-masked rows (m pinned at the MASK_VALUE bias): probabilities
        # are uniform — dv keeps that contribution, but dq/dk must be exactly
        # zero to match the XLA path's where-style masking (same rule as the
        # streaming kernel's backward above)
        ds = jnp.where(m <= 0.5 * MASK_VALUE, 0.0, ds).astype(q.dtype)
        pb = p.astype(q.dtype)
        qh = _head_masked(q, h, d)
        # masked operands confine every contribution to head h's channels
        dv += _dot(pb, gh, (0, 0))        # (S, E): p^T @ gh
        dq += _dot(ds, kh, (1, 0))        # (T, E): ds @ kh
        dk += _dot(ds, qh, (0, 0))        # (S, E): ds^T @ qh
    dq_ref[0] = dq.astype(dq_ref.dtype)
    dk_ref[0] = dk.astype(dk_ref.dtype)
    dv_ref[0] = dv.astype(dv_ref.dtype)


@functools.partial(jax.jit, static_argnames=("num_heads", "interpret"))
def _packed_fwd_impl(q, k, v, bias, num_heads, interpret):
    b, t, e = q.shape
    s = k.shape[1]
    d = e // num_heads
    kernel = functools.partial(
        _packed_fwd_kernel, num_heads=num_heads, scale=d**-0.5
    )
    return pl.pallas_call(
        kernel,
        grid=(b,),
        in_specs=[
            pl.BlockSpec((1, 1, s), lambda i: (i, 0, 0)),
            pl.BlockSpec((1, t, e), lambda i: (i, 0, 0)),
            pl.BlockSpec((1, s, e), lambda i: (i, 0, 0)),
            pl.BlockSpec((1, s, e), lambda i: (i, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, t, e), lambda i: (i, 0, 0)),
        out_shape=jax.ShapeDtypeStruct((b, t, e), q.dtype),
        interpret=interpret,
    )(bias, q, k, v)


@functools.partial(jax.jit, static_argnames=("num_heads", "interpret"))
def _packed_bwd_impl(q, k, v, bias, g, num_heads, interpret):
    b, t, e = q.shape
    s = k.shape[1]
    d = e // num_heads
    kernel = functools.partial(
        _packed_bwd_kernel, num_heads=num_heads, scale=d**-0.5
    )
    return pl.pallas_call(
        kernel,
        grid=(b,),
        in_specs=[
            pl.BlockSpec((1, 1, s), lambda i: (i, 0, 0)),
            pl.BlockSpec((1, t, e), lambda i: (i, 0, 0)),
            pl.BlockSpec((1, s, e), lambda i: (i, 0, 0)),
            pl.BlockSpec((1, s, e), lambda i: (i, 0, 0)),
            pl.BlockSpec((1, t, e), lambda i: (i, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, t, e), lambda i: (i, 0, 0)),
            pl.BlockSpec((1, s, e), lambda i: (i, 0, 0)),
            pl.BlockSpec((1, s, e), lambda i: (i, 0, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b, t, e), q.dtype),
            jax.ShapeDtypeStruct((b, s, e), k.dtype),
            jax.ShapeDtypeStruct((b, s, e), v.dtype),
        ],
        interpret=interpret,
    )(bias, q, k, v, g)


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5))
def _packed_attention(q, k, v, bias, num_heads, interpret):
    return _packed_fwd_impl(q, k, v, bias, num_heads, interpret)


def _packed_fwd(q, k, v, bias, num_heads, interpret):
    out = _packed_fwd_impl(q, k, v, bias, num_heads, interpret)
    return out, (q, k, v, bias)


def _packed_bwd(num_heads, interpret, residuals, g):
    q, k, v, bias = residuals
    dq, dk, dv = _packed_bwd_impl(q, k, v, bias, g, num_heads, interpret)
    return dq, dk, dv, jnp.zeros_like(bias)


_packed_attention.defvjp(_packed_fwd, _packed_bwd)

# VMEM guardrail for the (B,)-grid packed kernel: one backward grid step
# holds three f32 (T, S) tiles (logits/p, dp, ds), three f32 (rows, E)
# accumulators, and the packed operands — all live at once (Mosaic does not
# spill). Budget them jointly against a conservative slice of the ~16 MB
# scoped VMEM; independent per-dim caps would admit shapes whose combination
# cannot compile.
PACKED_VMEM_BUDGET = 8 * 1024 * 1024


def packed_vmem_bytes(t: int, s: int, e: int, itemsize: int = 2) -> int:
    """Estimated live VMEM of one backward grid step (the larger direction)."""
    tiles = 3 * t * s * 4                      # logits/p, dp, ds (f32)
    accs = (t + 2 * s) * e * 4                 # dq, dk, dv accumulators (f32)
    operands = (2 * t + 2 * s) * e * itemsize  # q, g, k, v blocks
    return tiles + accs + operands


def packed_fits_vmem(t: int, s: int, e: int, itemsize: int = 2) -> bool:
    return packed_vmem_bytes(t, s, e, itemsize) <= PACKED_VMEM_BUDGET


def packed_latent_attention(
    q: Array,
    k: Array,
    v: Array,
    num_heads: int,
    pad_mask: Optional[Array] = None,
    interpret: Optional[bool] = None,
) -> Array:
    """Fused multi-head attention over PACKED (B, T, E) q and (B, S, E) k/v.

    The head-split (B, T, H, D) layout never exists: heads are separated
    in-kernel by channel masking. Returns (B, T, E) — heads already merged.
    ``pad_mask``: optional (B, S) bool, True = masked out.
    """
    if q.ndim != 3 or k.ndim != 3 or v.ndim != 3:
        raise ValueError(f"expected packed (B, T/S, E) tensors, got {q.shape=}")
    if q.shape[-1] % num_heads != 0:
        raise ValueError(f"E {q.shape[-1]} not divisible by num_heads {num_heads}")
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    b, _, _ = q.shape
    s = k.shape[1]
    if pad_mask is None:
        bias = jnp.zeros((b, 1, s), jnp.float32)
    else:
        bias = jnp.where(pad_mask, MASK_VALUE, 0.0).astype(jnp.float32)[:, None, :]
    return _packed_attention(q, k, v, bias, num_heads, interpret)
