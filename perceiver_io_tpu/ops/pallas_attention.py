"""Fused latent-attention Pallas kernel (TPU).

Covers the Perceiver hot path: cross-attention of a small resident latent/query
block against a long KV stream (blockwise over M so the input never fully
materializes in VMEM), and latent self-attention — the TPU-native replacement
for the reference's ``torch.nn.MultiheadAttention`` CUDA kernels
(reference ``perceiver/model.py:66-74``).

Contract (enforced by the dispatcher in ``ops.attention``): no attention-prob
dropout, optional key padding mask only.
"""

from __future__ import annotations

from typing import Optional

import jax

Array = jax.Array


def fused_attention(
    q: Array, k: Array, v: Array, pad_mask: Optional[Array] = None
) -> Array:
    """Fused multi-head attention over (B, T, H, D) q and (B, S, H, D) k/v.

    Not yet implemented — the XLA einsum path in ``ops.attention`` is the
    current production path; use ``attn_impl='xla'``.
    """
    raise NotImplementedError(
        "The fused Pallas attention kernel has not landed yet; "
        "construct modules with attn_impl='xla'."
    )
