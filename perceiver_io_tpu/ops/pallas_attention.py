"""Fused latent-attention Pallas kernel (TPU).

Covers the Perceiver hot path — attention of a small resident query block
(latents or output queries) against a KV stream — as one fused kernel:
QK^T, masking, online softmax, and PV accumulation never round-trip to HBM,
and the KV sequence is streamed block-by-block so the input length M is never
fully resident in VMEM (the blockwise cross-attention called for in
SURVEY.md §5). This replaces the reference's ``torch.nn.MultiheadAttention``
CUDA kernels (reference ``perceiver/model.py:66-74``).

Design:

- grid ``(B, H, T/T_blk, S/S_blk)``; the KV axis is the innermost (sequential)
  grid dimension, so the running max / denominator / PV accumulator live in
  VMEM scratch across KV blocks (the standard TPU flash-attention recurrence).
  The query axis is blocked too, so large query counts (e.g. the flow
  decoder's dense 2D queries) stay inside the ~16MB VMEM scoped limit.
- logits and the accumulator are f32 regardless of input dtype; the P·V
  matmul feeds the MXU in the input dtype with f32 accumulation.
- padding (``pad_mask`` True = masked out) enters as a finite additive bias,
  reproducing the XLA path's semantics including the fully-masked-row case
  (uniform probabilities) without NaNs.
- backward: ``jax.custom_vjp`` recomputing attention gradients with the XLA
  einsum path (flash-style recompute-in-backward; the fused forward still
  saves the HBM round-trips where inference/eval spend their time).

Contract (enforced by the dispatcher in ``ops.attention``): no attention-prob
dropout, optional key padding mask only.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

Array = jax.Array

# Finite stand-in for -inf: exp() underflows to exactly 0 against any live
# logit, while a fully-masked row still softmaxes to uniform (XLA-path parity).
MASK_VALUE = -1e30
# Bias for keys the kernel itself padded in: strictly below MASK_VALUE so that
# even a fully-masked row's uniform softmax excludes them (exp(PAD - MASK) = 0).
PAD_BIAS = 2.0 * MASK_VALUE

_LANES = 128
DEFAULT_KV_BLOCK = 512
DEFAULT_Q_BLOCK = 512


def _kv_block_size(s: int, requested: int, alignment: int) -> int:
    """KV length to stream per grid step: a divisor of S, aligned to the TPU
    tile constraint (Mosaic requires block dims to be lane/sublane multiples
    or equal to the full array dim). Returns 0 when S must be padded instead."""
    requested = max(requested - requested % alignment, alignment)
    if s <= requested:
        return s  # single block — full-dim blocks are always legal
    best = 0
    for cand in range(requested, alignment - 1, -alignment):
        if s % cand == 0:
            best = cand
            break
    # a tiny block (many grid steps) is worse than padding to a full block
    return best if best * 2 >= requested else 0


def _attention_kernel(bias_ref, q_ref, k_ref, v_ref, out_ref,
                      m_ref, l_ref, acc_ref, *, scale: float):
    s_idx = pl.program_id(3)

    @pl.when(s_idx == 0)
    def _init():
        m_ref[:] = jnp.full_like(m_ref, MASK_VALUE)
        l_ref[:] = jnp.zeros_like(l_ref)
        acc_ref[:] = jnp.zeros_like(acc_ref)

    q = q_ref[0, 0]  # (T_blk, D)
    k = k_ref[0, 0]  # (S_blk, D)
    logits = jax.lax.dot_general(
        q, k,
        dimension_numbers=(((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    ) * scale  # (T_blk, S_blk)
    logits += bias_ref[0]  # (1, S_blk) broadcasts over T_blk

    m_prev = m_ref[:, :1]  # (T_blk, 1)
    l_prev = l_ref[:, :1]
    m_cur = jnp.max(logits, axis=-1, keepdims=True)
    m_new = jnp.maximum(m_prev, m_cur)
    alpha = jnp.exp(m_prev - m_new)
    p = jnp.exp(logits - m_new)  # (T_blk, S_blk)

    l_new = alpha * l_prev + jnp.sum(p, axis=-1, keepdims=True)
    pv = jax.lax.dot_general(
        p.astype(v_ref.dtype), v_ref[0, 0],
        dimension_numbers=(((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )  # (T_blk, D)
    acc_ref[:] = acc_ref[:] * alpha + pv
    m_ref[:] = jnp.broadcast_to(m_new, m_ref.shape)
    l_ref[:] = jnp.broadcast_to(l_new, l_ref.shape)

    @pl.when(s_idx == pl.num_programs(3) - 1)
    def _finish():
        out_ref[0, 0] = (acc_ref[:] / l_ref[:, :1]).astype(out_ref.dtype)


@functools.partial(jax.jit, static_argnames=("t_blk", "s_blk", "interpret"))
def _fused_attention_fwd_impl(
    q: Array, k: Array, v: Array, bias: Array,
    t_blk: int, s_blk: int, interpret: bool,
) -> Array:
    """(B, H, T, D) q against (B, H, S, D) k/v with (B, S) additive bias.
    ``t_blk``/``s_blk`` must divide T/S (the wrapper guarantees it)."""
    b, h, t, d = q.shape
    s = k.shape[2]
    scale = d**-0.5
    grid = (b, h, t // t_blk, s // s_blk)

    bias = bias[:, None, :]  # (B, 1, S)
    kernel = pl.pallas_call(
        functools.partial(_attention_kernel, scale=scale),
        grid=grid,
        in_specs=[
            # (B, 1, S) so the block's trailing dims satisfy TPU tiling
            pl.BlockSpec((1, 1, s_blk), lambda bi, hi, ti, si: (bi, 0, si)),
            pl.BlockSpec((1, 1, t_blk, d), lambda bi, hi, ti, si: (bi, hi, ti, 0)),
            pl.BlockSpec((1, 1, s_blk, d), lambda bi, hi, ti, si: (bi, hi, si, 0)),
            pl.BlockSpec((1, 1, s_blk, d), lambda bi, hi, ti, si: (bi, hi, si, 0)),
        ],
        out_specs=pl.BlockSpec((1, 1, t_blk, d), lambda bi, hi, ti, si: (bi, hi, ti, 0)),
        out_shape=jax.ShapeDtypeStruct((b, h, t, d), q.dtype),
        scratch_shapes=[
            pltpu.VMEM((t_blk, _LANES), jnp.float32),  # running max
            pltpu.VMEM((t_blk, _LANES), jnp.float32),  # running denominator
            pltpu.VMEM((t_blk, d), jnp.float32),  # PV accumulator
        ],
        compiler_params=pltpu.CompilerParams(
            # batch/head/query-block grid steps are independent; only the KV
            # axis carries the softmax recurrence
            dimension_semantics=("parallel", "parallel", "parallel", "arbitrary"),
        ),
        interpret=interpret,
    )
    return kernel(bias, q, k, v)


def _reference_attention(q, k, v, bias):
    """XLA attention over (B, H, T, D) — the backward-pass recompute.

    Masking uses ``where`` on the (non-differentiable) mask recovered from the
    bias, exactly like the production XLA path (``ops.attention``): masked
    positions contribute zero gradient to q/k — in particular a fully padded
    row yields dq = dk = 0, not gradients through its uniform softmax.
    """
    d = q.shape[-1]
    logits = jnp.einsum(
        "bhtd,bhsd->bhts", q * (d**-0.5), k, preferred_element_type=jnp.float32
    )
    masked = (bias < 0.5 * MASK_VALUE)[:, None, None, :]  # True = masked out
    logits = jnp.where(masked, jnp.finfo(logits.dtype).min, logits)
    probs = jax.nn.softmax(logits, axis=-1).astype(v.dtype)
    return jnp.einsum("bhts,bhsd->bhtd", probs, v)


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6))
def _fused_attention(q, k, v, bias, t_blk, s_blk, interpret):
    return _fused_attention_fwd_impl(q, k, v, bias, t_blk, s_blk, interpret)


def _fwd(q, k, v, bias, t_blk, s_blk, interpret):
    out = _fused_attention_fwd_impl(q, k, v, bias, t_blk, s_blk, interpret)
    return out, (q, k, v, bias)


def _bwd(t_blk, s_blk, interpret, residuals, g):
    q, k, v, bias = residuals
    _, vjp = jax.vjp(_reference_attention, q, k, v, bias)
    dq, dk, dv, _ = vjp(g)
    return dq, dk, dv, jnp.zeros_like(bias)


_fused_attention.defvjp(_fwd, _bwd)


def fused_attention(
    q: Array,
    k: Array,
    v: Array,
    pad_mask: Optional[Array] = None,
    kv_block_size: int = DEFAULT_KV_BLOCK,
    q_block_size: int = DEFAULT_Q_BLOCK,
    interpret: Optional[bool] = None,
) -> Array:
    """Fused multi-head attention over (B, T, H, D) q and (B, S, H, D) k/v.

    ``pad_mask``: optional (B, S) bool, True = key position masked out (the
    torch ``key_padding_mask`` convention). Off-TPU backends run the kernel in
    interpreter mode (slow — for tests), overridable via ``interpret``.
    """
    if q.ndim != 4 or k.ndim != 4 or v.ndim != 4:
        raise ValueError(f"expected (B, T/S, H, D) tensors, got {q.shape=} {k.shape=}")
    if interpret is None:
        interpret = jax.default_backend() != "tpu"

    b, t, h, d = q.shape
    s = k.shape[1]
    if pad_mask is None:
        bias = jnp.zeros((b, s), jnp.float32)
    else:
        bias = jnp.where(pad_mask, MASK_VALUE, 0.0).astype(jnp.float32)

    # heads-major layout so each (b, h) grid step reads contiguous KV rows
    q = jnp.transpose(q, (0, 2, 1, 3))
    k = jnp.transpose(k, (0, 2, 1, 3))
    v = jnp.transpose(v, (0, 2, 1, 3))

    # Stream the KV axis in blocks. Compiled TPU blocks must be lane-aligned
    # (a multiple of 128, or the full dim); when S has no aligned divisor, pad
    # it up to a block multiple with PAD_BIAS keys (excluded from the softmax
    # even on fully-masked rows).
    alignment = 1 if interpret else _LANES
    s_blk = _kv_block_size(s, kv_block_size, alignment)
    if s_blk == 0:
        if s <= 4 * kv_block_size:
            s_blk = s  # full-dim blocks are always tiling-legal; skip padding
        else:
            block = max(kv_block_size - kv_block_size % alignment, alignment)
            s_pad = -s % block
            k = jnp.pad(k, ((0, 0), (0, 0), (0, s_pad), (0, 0)))
            v = jnp.pad(v, ((0, 0), (0, 0), (0, s_pad), (0, 0)))
            bias = jnp.pad(bias, ((0, 0), (0, s_pad)), constant_values=PAD_BIAS)
            s_blk = block

    # Block the query axis too: a fully resident query block (plus its f32
    # accumulator and double-buffered output) blows the VMEM scoped limit once
    # T reaches a few thousand (e.g. dense flow decoder queries). Padded query
    # rows attend normally and are sliced off after.
    t_pad = 0
    t_blk = _kv_block_size(t, q_block_size, alignment)
    if t_blk == 0:
        if t <= 2 * q_block_size:
            t_blk = t
        else:
            t_blk = max(q_block_size - q_block_size % alignment, alignment)
            t_pad = -t % t_blk
            q = jnp.pad(q, ((0, 0), (0, 0), (0, t_pad), (0, 0)))

    out = _fused_attention(q, k, v, bias, t_blk, s_blk, interpret)
    if t_pad:
        out = out[:, :, :t]
    return jnp.transpose(out, (0, 2, 1, 3))
