"""Fused linear + cross-entropy Pallas kernel (TPU): the classifier head
matmul and the softmax CE in ONE kernel, so the (rows, vocab) logits tensor
never exists in HBM — forward or backward.

Motivation (device-trace measurement, PERF.md round 3): on the flagship MLM
config the unfused head complex — vocab matmul, CE reductions, softmax-grad
matmuls — costs ~1.4 ms of a 10.4 ms step, nearly all of it streaming the
206 MB (64, 160, 10003) bf16 logits tensor at HBM peak (~5 passes ≈ 1 GB of
traffic per step). The XLA chunked variant (``losses.fused_linear_ce_integer``)
already avoids the materialization but serializes 10-20 skinny matmul
dispatches (measured slower, PERF.md negative result #7). This kernel runs
the same online-logsumexp recurrence INSIDE one ``pallas_call`` — the vocab
axis is the innermost sequential grid dimension, per-block logits live only
in VMEM, and the MXU stays on one stream of (rows × vocab-block) matmuls.

Layout notes:

- grid ``(R/r_blk, V/v_blk)``, vocab innermost: running max ``m``, sum ``s``
  and the picked label logit ``ll`` live in VMEM scratch across vocab blocks
  (flash-attention's recurrence applied to a classifier head).
- the label pick needs no gather: each block compares its global column iota
  to the row's label and sums the single hit — a VPU-friendly masked
  reduction.
- backward recomputes per-block probabilities from the saved row logsumexp
  and fuses the softmax gradient into both transposed matmuls: a dx kernel
  (vocab sequential) and a dw/db kernel (rows sequential) — the same
  two-kernel split as the flash-attention backward in ``pallas_attention``.
- vocab is padded to a block multiple with ``bias = PAD_BIAS`` columns
  (exp → 0 against any live logit; labels never point at padding).

Sharding: this kernel is a single-device op. Under tensor parallelism the
vocab projection shards over the ``model`` axis and the UNFUSED path (whose
collectives GSPMD manages) remains the default; the fused head is the
single-chip / long-decode memory-and-bandwidth lever (``make_mlm_steps``
``fused_head=``).

Reference behavior replaced: the ``(B, 512, vocab)`` logits + CE identified
as the reference's memory hot spot (SURVEY.md §3.1, reference
``lightning.py:131-134``).
"""

from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# the TPUCompilerParams -> CompilerParams rename landed in newer jax; alias
# whichever spelling this build ships
_CompilerParams = getattr(pltpu, "CompilerParams", None) or pltpu.TPUCompilerParams

Array = jax.Array

_LANES = 128
# Test hook (tests/test_pallas_ce.py fuzz): force the compiled sublane
# alignment in interpret-mode runs so CPU property tests exercise the same
# row-block padding rule hardware takes (see the matching hook in
# pallas_attention.py). None = derive from ``interpret``.
_TEST_ALIGNMENT = None
# Finite stand-ins (see pallas_attention): PAD_BIAS marks kernel-added vocab
# padding; exp(PAD_BIAS - anything_live) underflows to exactly 0.
MASK_VALUE = -1e30
PAD_BIAS = 2.0 * MASK_VALUE

DEFAULT_R_BLOCK = 512
DEFAULT_V_BLOCK = 1024


def _dot(a, b, contract):
    precision = (jax.lax.Precision.HIGHEST
                 if a.dtype == jnp.float32 and b.dtype == jnp.float32 else None)
    return jax.lax.dot_general(
        a, b,
        dimension_numbers=(((contract[0],), (contract[1],)), ((), ())),
        preferred_element_type=jnp.float32,
        precision=precision,
    )


def _block_logits(x_ref, w_ref, b_ref):
    """(r_blk, v_blk) f32 logits for this grid step: x @ w + bias.

    The weight block is cast to the feature dtype in VMEM: the matmul runs
    in the compute dtype (matching the unfused path's promote_dtype) while
    the weight stays f32 in HBM so its COTANGENT keeps f32 precision."""
    x = x_ref[:]
    logits = _dot(x, w_ref[:].astype(x.dtype), (1, 0))
    return logits + b_ref[0][None, :]  # (1, v_blk) broadcasts over rows


def _fwd_kernel(labels_ref, x_ref, w_ref, b_ref, loss_ref, lse_ref,
                m_ref, s_ref, ll_ref, *, v_blk: int):
    v_idx = pl.program_id(1)

    @pl.when(v_idx == 0)
    def _init():
        m_ref[:] = jnp.full_like(m_ref, MASK_VALUE)
        s_ref[:] = jnp.zeros_like(s_ref)
        ll_ref[:] = jnp.zeros_like(ll_ref)

    logits = _block_logits(x_ref, w_ref, b_ref)  # (r_blk, v_blk) f32

    m_prev = m_ref[:, :1]
    m_cur = jnp.max(logits, axis=-1, keepdims=True)
    m_new = jnp.maximum(m_prev, m_cur)
    alpha = jnp.exp(m_prev - m_new)
    s_new = alpha * s_ref[:, :1] + jnp.sum(
        jnp.exp(logits - m_new), axis=-1, keepdims=True
    )

    # label pick: one masked reduction instead of a gather
    label = labels_ref[:, :1]  # (r_blk, 1) int32
    col = jax.lax.broadcasted_iota(jnp.int32, logits.shape, 1) + v_idx * v_blk
    picked = jnp.sum(jnp.where(col == label, logits, 0.0), axis=-1,
                     keepdims=True)

    m_ref[:] = jnp.broadcast_to(m_new, m_ref.shape)
    s_ref[:] = jnp.broadcast_to(s_new, s_ref.shape)
    ll_ref[:] = ll_ref[:] + jnp.broadcast_to(picked, ll_ref.shape)

    @pl.when(v_idx == pl.num_programs(1) - 1)
    def _finish():
        lse = m_ref[:, :1] + jnp.log(s_ref[:, :1])
        loss_ref[:] = jnp.broadcast_to(lse - ll_ref[:, :1], loss_ref.shape)
        lse_ref[:] = jnp.broadcast_to(lse, lse_ref.shape)


def _bwd_probs_grad(labels_ref, x_ref, w_ref, b_ref, lse_ref, g_ref, v_idx,
                    v_blk: int):
    """Recompute this block's softmax-grad ``d = (p − onehot(label))·g``."""
    logits = _block_logits(x_ref, w_ref, b_ref)
    p = jnp.exp(logits - lse_ref[:, :1])
    label = labels_ref[:, :1]
    col = jax.lax.broadcasted_iota(jnp.int32, logits.shape, 1) + v_idx * v_blk
    onehot = (col == label).astype(jnp.float32)
    return (p - onehot) * g_ref[:, :1]


def _bwd_dx_kernel(labels_ref, x_ref, w_ref, b_ref, lse_ref, g_ref,
                   dx_ref, acc_ref, *, v_blk: int):
    v_idx = pl.program_id(1)

    @pl.when(v_idx == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)

    d = _bwd_probs_grad(labels_ref, x_ref, w_ref, b_ref, lse_ref, g_ref,
                        v_idx, v_blk)
    d = d.astype(x_ref.dtype)  # softmax grad in the compute dtype (as unfused)
    acc_ref[:] += _dot(d, w_ref[:].astype(d.dtype), (1, 1))  # (r_blk, C)

    @pl.when(v_idx == pl.num_programs(1) - 1)
    def _finish():
        dx_ref[:] = acc_ref[:].astype(dx_ref.dtype)


def _bwd_dw_kernel(labels_ref, x_ref, w_ref, b_ref, lse_ref, g_ref,
                   dw_ref, db_ref, dw_acc, db_acc, *, v_blk: int):
    r_idx = pl.program_id(1)

    @pl.when(r_idx == 0)
    def _init():
        dw_acc[:] = jnp.zeros_like(dw_acc)
        db_acc[:] = jnp.zeros_like(db_acc)

    v_idx = pl.program_id(0)
    d = _bwd_probs_grad(labels_ref, x_ref, w_ref, b_ref, lse_ref, g_ref,
                        v_idx, v_blk)
    db_acc[:] += jnp.sum(d, axis=0, keepdims=True)  # (1, v_blk) f32
    d = d.astype(x_ref.dtype)
    dw_acc[:] += _dot(x_ref[:], d, (0, 0))  # (C, v_blk), f32 accumulation

    @pl.when(r_idx == pl.num_programs(1) - 1)
    def _finish():
        dw_ref[:] = dw_acc[:].astype(dw_ref.dtype)
        db_ref[:] = db_acc[:].astype(db_ref.dtype)


def _pad_inputs(kernel: Array, bias: Array, v_blk: int):
    v = kernel.shape[-1]
    pad = -v % v_blk
    if pad:
        kernel = jnp.pad(kernel, ((0, 0), (0, pad)))
        bias = jnp.pad(bias, (0, pad), constant_values=PAD_BIAS)
    return kernel, bias


@functools.partial(
    jax.jit, static_argnames=("r_blk", "v_blk", "interpret")
)
def _fused_ce_fwd_impl(
    x: Array, w: Array, b: Array, labels: Array,
    r_blk: int, v_blk: int, interpret: bool,
) -> Tuple[Array, Array]:
    r, c = x.shape
    v = w.shape[1]
    grid = (r // r_blk, v // v_blk)
    labels_b = jnp.broadcast_to(
        labels.astype(jnp.int32)[:, None], (r, _LANES)
    )
    lane_spec = pl.BlockSpec((r_blk, _LANES), lambda ri, vi: (ri, 0))
    loss, lse = pl.pallas_call(
        functools.partial(_fwd_kernel, v_blk=v_blk),
        grid=grid,
        in_specs=[
            lane_spec,  # labels
            pl.BlockSpec((r_blk, c), lambda ri, vi: (ri, 0)),     # x
            pl.BlockSpec((c, v_blk), lambda ri, vi: (0, vi)),     # w
            pl.BlockSpec((1, v_blk), lambda ri, vi: (0, vi)),     # bias
        ],
        out_specs=(lane_spec, lane_spec),
        out_shape=(
            jax.ShapeDtypeStruct((r, _LANES), jnp.float32),  # per-row loss
            jax.ShapeDtypeStruct((r, _LANES), jnp.float32),  # lse (residual)
        ),
        scratch_shapes=[
            pltpu.VMEM((r_blk, _LANES), jnp.float32),  # running max
            pltpu.VMEM((r_blk, _LANES), jnp.float32),  # running sum
            pltpu.VMEM((r_blk, _LANES), jnp.float32),  # label logit
        ],
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "arbitrary"),
        ),
        interpret=interpret,
    )(labels_b, x, w, b[None, :])
    return loss[:, 0], lse


@functools.partial(
    jax.jit, static_argnames=("r_blk", "v_blk", "interpret")
)
def _fused_ce_bwd_impl(
    x: Array, w: Array, b: Array, labels: Array, lse: Array, g: Array,
    r_blk: int, v_blk: int, interpret: bool,
):
    r, c = x.shape
    v = w.shape[1]
    labels_b = jnp.broadcast_to(
        labels.astype(jnp.int32)[:, None], (r, _LANES)
    )
    g_b = jnp.broadcast_to(g.astype(jnp.float32)[:, None], (r, _LANES))

    lane_spec = pl.BlockSpec((r_blk, _LANES), lambda ri, vi: (ri, 0))
    dx = pl.pallas_call(
        functools.partial(_bwd_dx_kernel, v_blk=v_blk),
        grid=(r // r_blk, v // v_blk),  # vocab sequential
        in_specs=[
            lane_spec,
            pl.BlockSpec((r_blk, c), lambda ri, vi: (ri, 0)),
            pl.BlockSpec((c, v_blk), lambda ri, vi: (0, vi)),
            pl.BlockSpec((1, v_blk), lambda ri, vi: (0, vi)),
            lane_spec,
            lane_spec,
        ],
        out_specs=pl.BlockSpec((r_blk, c), lambda ri, vi: (ri, 0)),
        out_shape=jax.ShapeDtypeStruct((r, c), x.dtype),
        scratch_shapes=[pltpu.VMEM((r_blk, c), jnp.float32)],
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "arbitrary"),
        ),
        interpret=interpret,
    )(labels_b, x, w, b[None, :], lse, g_b)

    # dw/db: rows sequential (same index maps, swapped grid positions)
    lane_spec2 = pl.BlockSpec((r_blk, _LANES), lambda vi, ri: (ri, 0))
    dw, db = pl.pallas_call(
        functools.partial(_bwd_dw_kernel, v_blk=v_blk),
        grid=(v // v_blk, r // r_blk),
        in_specs=[
            lane_spec2,
            pl.BlockSpec((r_blk, c), lambda vi, ri: (ri, 0)),
            pl.BlockSpec((c, v_blk), lambda vi, ri: (0, vi)),
            pl.BlockSpec((1, v_blk), lambda vi, ri: (0, vi)),
            lane_spec2,
            lane_spec2,
        ],
        out_specs=(
            pl.BlockSpec((c, v_blk), lambda vi, ri: (0, vi)),
            pl.BlockSpec((1, v_blk), lambda vi, ri: (0, vi)),
        ),
        out_shape=(
            jax.ShapeDtypeStruct((c, v), jnp.float32),
            jax.ShapeDtypeStruct((1, v), jnp.float32),
        ),
        scratch_shapes=[
            pltpu.VMEM((c, v_blk), jnp.float32),
            pltpu.VMEM((1, v_blk), jnp.float32),
        ],
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "arbitrary"),
        ),
        interpret=interpret,
    )(labels_b, x, w, b[None, :], lse, g_b)
    return dx, dw, db[0]


def _row_block(r: int, requested: int, interpret: bool) -> int:
    """Row-block size for R rows: the requested block, shrunk (aligned) only
    when R itself is smaller. Rows are PADDED up to a block multiple by the
    caller — never the reverse (a smaller exact-divisor block): awkward row
    counts otherwise explode the sequential grid. Measured at seq-131072 MLM
    (R = 39328 = 32·1229, 1229 prime): the largest aligned divisor is 32,
    giving a 12,290-step grid and 16.6 ms of a 38 ms step; padding 96 dead
    rows keeps the 512-row block and a 770-step grid instead."""
    align = _TEST_ALIGNMENT or (1 if interpret else 8)  # f32 sublane tile
    requested = max(align, requested - requested % align)
    return min(requested, -(-r // align) * align)


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6))
def _fused_ce(x, w, b, labels, r_blk, v_blk, interpret):
    loss, _ = _fused_ce_fwd_impl(x, w, b, labels, r_blk, v_blk, interpret)
    return loss


def _fused_ce_fwd(x, w, b, labels, r_blk, v_blk, interpret):
    loss, lse = _fused_ce_fwd_impl(x, w, b, labels, r_blk, v_blk, interpret)
    return loss, (x, w, b, labels, lse)


def _fused_ce_bwd(r_blk, v_blk, interpret, res, g):
    x, w, b, labels, lse = res
    dx, dw, db = _fused_ce_bwd_impl(
        x, w, b, labels, lse, g, r_blk, v_blk, interpret
    )
    import numpy as np

    return (
        dx,
        dw.astype(w.dtype),
        db.astype(b.dtype),
        np.zeros(labels.shape, jax.dtypes.float0),
    )


_fused_ce.defvjp(_fused_ce_fwd, _fused_ce_bwd)


def pallas_linear_ce_integer(
    features: Array,
    kernel: Array,
    bias: Array,
    labels: Array,
    r_block_size: int = DEFAULT_R_BLOCK,
    v_block_size: int = DEFAULT_V_BLOCK,
    interpret: bool | None = None,
) -> Array:
    """Per-position CE of ``features @ kernel + bias`` vs integer ``labels``
    as one fused Pallas kernel — the (..., V) logits never reach HBM.

    features: (..., C); kernel: (C, V); bias: (V,); labels: (...) int.
    Returns f32 per-position losses shaped like ``labels``. Gradients flow to
    features/kernel/bias (flash-style recomputation; see module docstring).
    Off-TPU backends run in interpreter mode (slow — tests only).
    """
    if features.shape[:-1] != labels.shape:
        raise ValueError(
            f"features {features.shape} and labels {labels.shape} disagree"
        )
    if kernel.shape[0] != features.shape[-1] or kernel.shape[1] != bias.shape[0]:
        raise ValueError(
            f"kernel {kernel.shape} does not match features "
            f"{features.shape} / bias {bias.shape}"
        )
    if interpret is None:
        interpret = jax.default_backend() != "tpu"

    lead = features.shape[:-1]
    c = features.shape[-1]
    x = features.reshape(-1, c)
    lab = labels.reshape(-1)
    r = x.shape[0]

    w, b = _pad_inputs(kernel, bias, v_block_size)
    v_blk = v_block_size  # _pad_inputs made V a (>= 1) multiple of it
    r_blk = _row_block(r, r_block_size, interpret)
    r_pad = -r % r_blk
    if r_pad:
        # dead rows: label 0, zero features. Their per-row losses are sliced
        # off below, so their loss cotangent is exactly zero — the recomputed
        # softmax grad ``(p - onehot)·g`` vanishes and dw/db stay exact; the
        # padded dx rows are discarded by the same slice.
        x = jnp.pad(x, ((0, r_pad), (0, 0)))
        lab = jnp.pad(lab, (0, r_pad))

    loss = _fused_ce(x, w, b.astype(jnp.float32), lab, r_blk, v_blk, interpret)
    if r_pad:
        loss = loss[:r]
    return loss.reshape(lead)
