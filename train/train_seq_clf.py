#!/usr/bin/env python
"""IMDB sequence classification (reference ``train/train_seq_clf.py`` CLI surface)."""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from perceiver_io_tpu.cli.train_seq_clf import main

if __name__ == "__main__":
    main()
