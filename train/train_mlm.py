#!/usr/bin/env python
"""MLM pretraining (reference ``train/train_mlm.py`` CLI surface)."""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from perceiver_io_tpu.cli.train_mlm import main

if __name__ == "__main__":
    main()
