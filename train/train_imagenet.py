#!/usr/bin/env python
"""ImageNet-1k classification (Perceiver-paper config; extends the reference's
image path beyond MNIST — BASELINE.md tracked config)."""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from perceiver_io_tpu.cli.train_imagenet import main

if __name__ == "__main__":
    main()
