#!/usr/bin/env python
"""Multimodal audio/video autoencoding + classification (framework extension)."""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from perceiver_io_tpu.cli.train_multimodal import main

if __name__ == "__main__":
    main()
