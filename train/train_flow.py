#!/usr/bin/env python
"""Optical-flow training (framework extension; Sintel layout or synthetic)."""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from perceiver_io_tpu.cli.train_flow import main

if __name__ == "__main__":
    main()
