#!/usr/bin/env python
"""Same-process interleaved A/B: continuous-batching arena decode vs r18
per-session chains, at concurrency, with admit/retire churn mid-sweep.

The claim under test (PERF.md §Continuous batching): Perceiver-AR decode is
weight-stream-bound, so packing every active stream's step into ONE batched
dispatch amortizes the per-dispatch cost (weights on TPU, dispatch/launch
overhead on CPU) across the batch — aggregate tokens/s should scale with
concurrency instead of flat-lining. Both arms serve the IDENTICAL stream
schedule (same prefixes, budgets, sampling, stagger); the position-folded
sampling keys make the token streams bit-identical across arms, which the
record asserts (``tokens_match``) — this is a PERF A/B with a built-in
correctness pin, not two unrelated runs.

Measurement discipline (PERF.md): the two arms run INTERLEAVED in one
process (B, A, A, B per pair — order-alternated against drift), never
cross-session; the verdict is the per-pair speedup median. Streams launch
on a bounded worker pool sized BELOW the stream count, so later streams are
admitted as earlier ones retire — membership churns mid-sweep (continuous
batching, not a fixed cohort).

Emits exactly ONE JSON line on stdout; progress rides stderr.
``--dry`` declares the record keys without touching any backend.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from perceiver_io_tpu.utils.jsonline import emit_json_line  # noqa: E402

RECORD_KEYS = (
    "metric", "dry", "backend", "streams", "concurrency", "chunk", "slots",
    "pairs", "mean_new", "max_new_cap", "prefix_lens", "temperature",
    "top_k", "quantize",
    "batched_tokens_per_s", "sequential_tokens_per_s",
    "speedup", "speedup_median", "tokens_match",
    "admitted", "retired", "slot_occupancy_mean", "steps_per_dispatch_mean",
    "per_pair",
)


def _log(msg: str) -> None:
    print(f"decode_batching_bench: {msg}", file=sys.stderr, flush=True)


def _schedule(args, vocab: int, max_seq_len: int):
    """The deterministic stream schedule both arms replay: (prefix,
    max_new, stagger_s) per stream. Budgets vary (short and long mixed) so
    retirements free slots while later arrivals are still queued."""
    import numpy as np

    rng = np.random.default_rng(args.seed)
    plens = [int(p) for p in args.prefix_lens.split(",")]
    sched = []
    for i in range(args.streams):
        plen = int(rng.choice(plens))
        prefix = [int(t) for t in rng.integers(3, vocab, plen)]
        max_new = int(min(1 + rng.geometric(1.0 / args.mean_new),
                          args.max_new_cap,
                          max_seq_len - plen - 1))
        stagger = float(i % 4) * args.stagger_s
        sched.append((prefix, max_new, stagger))
    return sched


def _run_arm(gen, sched, sampling, concurrency: int):
    """Replay the schedule against one engine on a FIXED worker pool of
    ``concurrency`` threads pulling from an arrival queue; returns
    (wall_s, tokens_total, streams_tokens). The pool bound < len(sched)
    forces mid-sweep admit/retire in the batched arm, and reusing workers
    keeps per-stream thread-spawn cost out of both arms' walls."""
    import queue as _queue

    results = [None] * len(sched)
    errors = []
    work: "_queue.SimpleQueue" = _queue.SimpleQueue()

    def worker():
        while True:
            item = work.get()
            if item is None:
                return
            i, prefix, max_new = item
            try:
                toks, _ = gen.generate(prefix, max_new, sampling)
                results[i] = toks
            except Exception as e:  # pragma: no cover - in the record
                errors.append(f"{type(e).__name__}: {e}")

    threads = [threading.Thread(target=worker, name=f"ab-worker-{w}",
                                daemon=True) for w in range(concurrency)]
    t0 = time.monotonic()
    for t in threads:
        t.start()
    for i, (prefix, max_new, stagger) in enumerate(sched):
        target = t0 + stagger
        now = time.monotonic()
        if now < target:
            time.sleep(target - now)
        work.put((i, list(prefix), max_new))
    for _ in threads:
        work.put(None)
    for t in threads:
        t.join()
    wall = time.monotonic() - t0
    if errors:
        raise RuntimeError(f"{len(errors)} streams failed: {errors[0]}")
    return wall, sum(len(r) for r in results), results


def run(args) -> int:
    if args.dry:
        emit_json_line({
            "metric": "decode_batching_ab", "dry": True, "backend": None,
            "record_keys": list(RECORD_KEYS),
        })
        return 0
    from perceiver_io_tpu.utils.platform import ensure_cpu_only, probe_backend

    if args.cpu:
        ensure_cpu_only()
    import jax
    import numpy as np

    from perceiver_io_tpu.models.presets import tiny_ar
    from perceiver_io_tpu.inference.batching import ContinuousBatcher
    from perceiver_io_tpu.inference.generate import (
        ARGenerator,
        SamplingConfig,
    )

    model = tiny_ar()
    max_seq_len = 64
    ids0 = np.zeros((1, max_seq_len), np.int32)
    params = model.init(
        {"params": jax.random.key(0)}, ids0, ids0 == 0)["params"]
    sampling = SamplingConfig(temperature=args.temperature,
                              top_k=args.top_k, seed=args.seed)

    quantize = None if args.quantize == "none" else args.quantize
    seq = ARGenerator(model, params, max_seq_len=max_seq_len,
                      chunk=args.chunk, quantize=quantize, name="ab_seq")
    # max_slots pinned to slots: arena growth is the right policy on TPU
    # (a marginal slot rides the same weight stream) but on CPU every slot
    # costs linear compute, so the A/B holds capacity fixed and lets the
    # admission queue keep the arena full instead.
    bat = ContinuousBatcher(model, params, max_seq_len=max_seq_len,
                            chunk=args.chunk, slots=args.slots,
                            max_slots=args.slots, quantize=quantize,
                            name="ab_bat")
    sched = _schedule(args, vocab=int(model.input_adapter.vocab_size),
                      max_seq_len=max_seq_len)
    _log(f"{len(sched)} streams, concurrency {args.concurrency}, "
         f"chunk {args.chunk}, slots {args.slots}, {args.pairs} pairs")
    # warm both arms on the schedule itself (compiles + first-touch), then
    # measure — an unwarmed arm's compile wall would swamp the A/B
    _run_arm(seq, sched, sampling, args.concurrency)
    _run_arm(bat, sched, sampling, args.concurrency)

    per_pair = []
    tokens_match = True
    for p in range(args.pairs):
        # order-alternated (B,A then A,B) so drift cancels per pair
        order = (("bat", "seq") if p % 2 == 0 else ("seq", "bat"))
        walls = {}
        toks = {}
        for arm in order:
            gen = bat if arm == "bat" else seq
            wall, total, results = _run_arm(gen, sched, sampling,
                                            args.concurrency)
            walls[arm] = wall
            toks[arm] = (total, results)
        tokens_match = tokens_match and toks["bat"][1] == toks["seq"][1]
        pair = {
            "batched_tokens_per_s": round(toks["bat"][0] / walls["bat"], 2),
            "sequential_tokens_per_s": round(
                toks["seq"][0] / walls["seq"], 2),
            "order": "->".join(order),
        }
        pair["speedup"] = round(pair["batched_tokens_per_s"]
                                / pair["sequential_tokens_per_s"], 3)
        per_pair.append(pair)
        _log(f"pair {p}: batched {pair['batched_tokens_per_s']} tok/s, "
             f"sequential {pair['sequential_tokens_per_s']} tok/s "
             f"({pair['speedup']}x), match={tokens_match}")
    stats = bat.stats()
    speedups = sorted(p["speedup"] for p in per_pair)
    record = {
        "metric": "decode_batching_ab", "dry": False,
        "backend": probe_backend().backend,
        "streams": len(sched), "concurrency": args.concurrency,
        "chunk": args.chunk, "slots": args.slots, "pairs": args.pairs,
        "mean_new": args.mean_new, "max_new_cap": args.max_new_cap,
        "prefix_lens": args.prefix_lens,
        "temperature": args.temperature, "top_k": args.top_k,
        "quantize": args.quantize,
        "batched_tokens_per_s": per_pair[-1]["batched_tokens_per_s"],
        "sequential_tokens_per_s": per_pair[-1]["sequential_tokens_per_s"],
        "speedup": per_pair[-1]["speedup"],
        "speedup_median": speedups[len(speedups) // 2],
        "tokens_match": tokens_match,
        "admitted": stats["admitted"], "retired": stats["retired"],
        "slot_occupancy_mean": stats["slot_occupancy_mean"],
        "steps_per_dispatch_mean": stats["steps_per_dispatch_mean"],
        "per_pair": per_pair,
    }
    bat.close()
    emit_json_line(record)
    return 0


def main() -> None:
    p = argparse.ArgumentParser(
        description="interleaved A/B: continuous-batching arena decode vs "
                    "per-session chains (tiny preset)")
    p.add_argument("--cpu", action="store_true",
                   help="pin the CPU backend before jax initializes")
    p.add_argument("--dry", action="store_true",
                   help="emit the record schema without touching a backend")
    p.add_argument("--streams", type=int, default=128,
                   help="streams per arm replay (> concurrency: membership "
                        "churns mid-sweep)")
    p.add_argument("--concurrency", type=int, default=40,
                   help="stream worker pool bound (= concurrent sessions); "
                        "kept above slots so the admission queue holds the "
                        "arena at full occupancy")
    p.add_argument("--chunk", type=int, default=4)
    p.add_argument("--slots", type=int, default=16,
                   help="arena slots per prefill width (batched arm)")
    p.add_argument("--pairs", type=int, default=3,
                   help="order-alternated A/B pairs (median speedup wins)")
    p.add_argument("--mean_new", type=int, default=24,
                   help="mean geometric continuation budget (pre-cap)")
    p.add_argument("--max_new_cap", type=int, default=12,
                   help="max_tokens-style budget cap; with the default "
                        "prefix band this keeps every stream inside its "
                        "prefill episode (no width crossing)")
    p.add_argument("--prefix_lens", default="2,3,4",
                   help="prompt lengths; the defaults land every stream in "
                        "the width-16 episode band so the arena packs "
                        "instead of scattering across widths")
    p.add_argument("--stagger_s", type=float, default=0.002,
                   help="arrival stagger between launch cohorts")
    p.add_argument("--temperature", type=float, default=0.8)
    p.add_argument("--top_k", type=int, default=16)
    p.add_argument("--quantize", choices=("none", "int8", "int4"),
                   default="none",
                   help="weight-only quantization for BOTH arms (the A/B "
                        "stays apples-to-apples; sequential==batched token "
                        "identity must hold per mode — tests/test_batching)")
    p.add_argument("--seed", type=int, default=0)
    raise SystemExit(run(p.parse_args()))


if __name__ == "__main__":
    main()
