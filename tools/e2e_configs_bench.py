"""Developer tool: reproduce PERF.md's end-to-end config table.

Times one full train step (fwd+bwd+optimizer, donated state, honest sync —
see PERF.md's measurement discipline) for each BASELINE.md-tracked config on
the current backend. Usage:

    python tools/e2e_configs_bench.py [config ...]   # default: all

Configs: mlm, seqclf, mnist, imagenet, imagenet8h, flow, multimodal.
"""

from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from perceiver_io_tpu.utils.platform import probe_backend

import jax
import jax.numpy as jnp
import numpy as np

import perceiver_io_tpu as pit
from perceiver_io_tpu.training import (
    OptimizerConfig,
    TrainState,
    make_classifier_steps,
    make_flow_steps,
    make_mlm_steps,
    make_multimodal_steps,
    make_optimizer,
    mlm_gather_capacity,
)

STEPS = int(os.environ.get("PIT_BENCH_STEPS", "10"))
DTYPE = jnp.bfloat16
# Force one attention impl across every config (e.g. 'xla' so XLA cost
# analysis sees ALL the flops — Pallas custom-calls count zero there; see
# tools/hbm_roofline.py's MFU method). Default: each config's own choice.
ATTN_IMPL = os.environ.get("PIT_E2E_ATTN")
rng = np.random.default_rng(0)


def _image_classifier(image_shape, num_classes, latents, channels, blocks,
                      cross_heads, self_heads, bands):
    attn = ATTN_IMPL or "auto"
    return pit.PerceiverIO(
        encoder=pit.PerceiverEncoder(
            input_adapter=pit.ImageInputAdapter(
                image_shape=image_shape, num_frequency_bands=bands, dtype=DTYPE
            ),
            latent_shape=(latents, channels),
            num_layers=1,
            num_cross_attention_heads=cross_heads,
            num_self_attention_heads=self_heads,
            num_self_attention_layers_per_block=blocks,
            dtype=DTYPE,
            attn_impl=attn,
        ),
        decoder=pit.PerceiverDecoder(
            output_adapter=pit.ClassificationOutputAdapter(
                num_classes=num_classes, num_output_channels=channels, dtype=DTYPE
            ),
            latent_shape=(latents, channels),
            num_cross_attention_heads=cross_heads,
            dtype=DTYPE,
            attn_impl=attn,
        ),
    )


def _mlm_config(model_factory, batch_size: int, default_head: str,
                seq: int = 512):
    """Shared MLM bench recipe (synthetic batch, gather decode, PIT_E2E_HEAD
    override: 'pallas'|'xla'|'none' — 'none' also feeds hbm_roofline's
    MFU-numerator build, where cost analysis must see the head's flops;
    PIT_E2E_DEC_ATTN overrides the DECODER attention impl separately —
    the gather-decode cross is a many-queries/few-keys shape that can
    prefer a different path than the encoder's long-KV stream)."""
    vocab, b = 10003, batch_size
    model = model_factory(dtype=DTYPE, attn_impl=ATTN_IMPL or "xla",
                          max_seq_len=seq,
                          decoder_attn_impl=os.environ.get("PIT_E2E_DEC_ATTN"))
    batch = {
        "token_ids": jnp.asarray(rng.integers(3, vocab, (b, seq)).astype(np.int32)),
        "pad_mask": jnp.zeros((b, seq), bool),
    }
    variables = model.init(
        {"params": jax.random.key(0), "masking": jax.random.key(1)},
        batch["token_ids"], batch["pad_mask"],
    )
    head = os.environ.get("PIT_E2E_HEAD", default_head)
    fused_head = {"pallas": "pallas", "xla": True, "none": False}[head]
    train_step, _, _ = make_mlm_steps(
        model, loss_gather_capacity=mlm_gather_capacity(seq),
        fused_head=fused_head,
    )
    return variables, train_step, batch, b


def config_mlm():
    """Flagship IMDB MLM (512 seq, 256x64 latents, 3x6 layers, batch 64).
    Matches bench.py's defaults (attn_impl='xla', gather decode, fused
    flash-CE head on TPU)."""
    from perceiver_io_tpu.models.presets import flagship_mlm

    default_head = "pallas" if probe_backend().backend == "tpu" else "none"
    return _mlm_config(flagship_mlm, 64, default_head)


def config_mlm_tpu():
    """The MLM recipe at TPU-native widths (C=512, head depth 128 — the
    ``flagship_tpu_mlm`` preset; everything else identical to config_mlm).
    PIT_MLM_TPU_BATCH overrides the batch (default 64, the reference's —
    b128 measured WORSE: 130.0 ms = 34.0% MFU vs b64's 53.6%). The UNFUSED
    head is the default here (roofline A/B, r4: unfused 41.26 ms / 53.6%
    MFU vs flash-CE 42.08 / 52.6% — the K=512-deep head matmuls are
    MXU-efficient, so saving the logits traffic no longer pays, unlike the
    d=16 flagship where the kernel is +6.1%)."""
    from perceiver_io_tpu.models.presets import flagship_tpu_mlm

    b = int(os.environ.get("PIT_MLM_TPU_BATCH", "64"))
    seq = int(os.environ.get("PIT_MLM_TPU_SEQ", "512"))
    return _mlm_config(flagship_tpu_mlm, b, "none", seq=seq)


def config_seqclf():
    """IMDB sequence classification (the transfer target: same text encoder
    as MLM, classification decoder; reference train_seq_clf.py defaults —
    batch 128, 64x64 latents, 1 decoder cross-attention head,
    reference ``train_seq_clf.py:56-68``)."""
    vocab, seq, b = 10003, 512, 128
    attn = ATTN_IMPL or "xla"
    model = pit.PerceiverIO(
        encoder=pit.PerceiverEncoder(
            input_adapter=pit.TextInputAdapter(
                vocab_size=vocab, max_seq_len=seq, num_channels=64, dtype=DTYPE
            ),
            latent_shape=(64, 64),
            num_layers=3,
            num_self_attention_layers_per_block=6,
            dtype=DTYPE,
            attn_impl=attn,
        ),
        decoder=pit.PerceiverDecoder(
            output_adapter=pit.ClassificationOutputAdapter(
                num_classes=2, num_output_channels=64, dtype=DTYPE
            ),
            latent_shape=(64, 64),
            num_cross_attention_heads=1,
            dtype=DTYPE,
            attn_impl=attn,
        ),
    )
    batch = {
        "token_ids": jnp.asarray(rng.integers(3, vocab, (b, seq)).astype(np.int32)),
        "pad_mask": jnp.zeros((b, seq), bool),
        "label": jnp.asarray(rng.integers(0, 2, b).astype(np.int32)),
    }
    variables = model.init(
        {"params": jax.random.key(0)}, batch["token_ids"],
        pad_mask=batch["pad_mask"],
    )
    train_step, _ = make_classifier_steps(model, input_kind="text")
    return variables, train_step, batch, b


def config_mnist():
    """MNIST recipe (28x28, 32x128 latents, 3 self-attn, batch 128)."""
    b = 128
    model = _image_classifier((28, 28, 1), 10, 32, 128, 3, 4, 4, 32)
    batch = {
        "image": jnp.asarray(rng.normal(0, 1, (b, 28, 28, 1)), jnp.float32),
        "label": jnp.asarray(rng.integers(0, 10, b).astype(np.int32)),
    }
    variables = model.init({"params": jax.random.key(0)}, batch["image"][:1])
    train_step, _ = make_classifier_steps(model, input_kind="image")
    return variables, train_step, batch, b


def _imagenet(cross_heads):
    b = 8
    model = _image_classifier((224, 224, 3), 1000, 512, 1024, 6, cross_heads, 8, 64)
    batch = {
        "image": jnp.asarray(rng.normal(0, 1, (b, 224, 224, 3)), jnp.float32),
        "label": jnp.asarray(rng.integers(0, 1000, b).astype(np.int32)),
    }
    variables = model.init({"params": jax.random.key(0)}, batch["image"][:1])
    train_step, _ = make_classifier_steps(model, input_kind="image")
    return variables, train_step, batch, b


def config_imagenet():
    """ImageNet-1k paper config (224^2, 512x1024 latents, 1-head cross)."""
    return _imagenet(1)


def config_imagenet8h():
    """ImageNet-1k, 8-head cross variant (the fused-kernel showcase)."""
    return _imagenet(8)


def config_flow():
    """Sintel optical flow (368x496, 2048x512 latents, dense 2D queries)."""
    from perceiver_io_tpu.models.flow import build_optical_flow_model

    b = int(os.environ.get("PIT_FLOW_BATCH", "1"))
    model = build_optical_flow_model(dtype=DTYPE, attn_impl=ATTN_IMPL or "auto")
    batch = {
        "frames": jnp.asarray(rng.normal(0, 1, (b, 2, 368, 496, 3)), jnp.float32),
        "flow": jnp.asarray(rng.normal(0, 1, (b, 368, 496, 2)), jnp.float32),
    }
    variables = model.init({"params": jax.random.key(0)}, batch["frames"][:1])
    train_step, _ = make_flow_steps(model)
    return variables, train_step, batch, b


def config_multimodal():
    """Kinetics-style AV autoencoding (16x224^2 video + audio, 784x512).

    Defaults are the r4 measured-best (roofline sweep, device trace):
    batch 8 (b2 79.2 → b4 86.4 → b8 88.8 ex/s; b16 regresses to 85.7),
    remat OFF (recompute cost > saved traffic at this depth: 28.5 vs
    30.8 ms at b2/auto), attn 'xla' (the area-rule kernel routing LOSES,
    30.8 ms vs xla's 27.7 at b2 — overlap dilution, PERF.md negative (11)).
    PIT_MM_BATCH / PIT_MM_REMAT=1 / PIT_MM_PATCH_LOSS=1 (patch-space video
    reconstruction loss — exact, skips the un-patchify transposes) override."""
    from perceiver_io_tpu.models.multimodal import build_multimodal_autoencoder

    b = int(os.environ.get("PIT_MM_BATCH", "8"))
    video_shape = (16, 224, 224, 3)
    model = build_multimodal_autoencoder(
        video_shape=video_shape, num_audio_samples=30720, dtype=DTYPE,
        remat=os.environ.get("PIT_MM_REMAT", "0") != "0",
        attn_impl=ATTN_IMPL or "xla",
        video_patch_loss=os.environ.get("PIT_MM_PATCH_LOSS", "0") != "0",
    )
    batch = {
        "video": jnp.asarray(rng.normal(0, 1, (b, *video_shape)), jnp.float32),
        "audio": jnp.asarray(rng.normal(0, 1, (b, 30720, 1)), jnp.float32),
        "label": jnp.asarray(rng.integers(0, 700, b).astype(np.int32)),
    }
    variables = model.init(
        {"params": jax.random.key(0)},
        {"video": batch["video"][:1], "audio": batch["audio"][:1]},
    )
    train_step, _ = make_multimodal_steps(model)
    return variables, train_step, batch, b


CONFIGS = {
    "mlm": config_mlm,
    "mlm_tpu": config_mlm_tpu,
    "seqclf": config_seqclf,
    "mnist": config_mnist,
    "imagenet": config_imagenet,
    "imagenet8h": config_imagenet8h,
    "flow": config_flow,
    "multimodal": config_multimodal,
}


def run(name: str) -> None:
    from perceiver_io_tpu.utils import profiling
    from perceiver_io_tpu.utils.benchmarking import time_train_step

    variables, train_step, batch, batch_size = CONFIGS[name]()
    tx, _ = make_optimizer(OptimizerConfig(learning_rate=1e-3))
    state = TrainState.create(variables["params"], tx, jax.random.key(2))
    # ONE jit wrapper: the cost analysis below compiles it (before the state
    # is donated), and the timing loop reuses the same executable
    jitted = jax.jit(train_step, donate_argnums=(0,))
    flops = (profiling.compiled_flops(jitted, state, batch)
             if profiling.device_peak_flops() is not None else None)
    seconds, _ = time_train_step(
        train_step, state, batch, STEPS, windows=3, jitted=jitted
    )

    mfu_str = ""
    if flops:
        u = profiling.mfu(flops, seconds)
        if u is not None:
            mfu_str = f"   MFU {100 * u:5.1f}%"
    print(f"{name:12s} {seconds * 1e3:9.2f} ms/step   "
          f"{batch_size / seconds:8.1f} ex/s{mfu_str}", file=sys.stderr)


def main():
    names = sys.argv[1:] or list(CONFIGS)
    unknown = [n for n in names if n not in CONFIGS]
    if unknown:
        raise SystemExit(f"unknown configs {unknown}; pick from {sorted(CONFIGS)}")
    print(f"device: {probe_backend().device_kind}, {STEPS} steps per config", file=sys.stderr)
    for name in names:
        run(name)


if __name__ == "__main__":
    main()
