"""Bisect the flow-b8 remote-compile failure + measure 2×b4 grad accumulation
(VERDICT r4 item 5; PERF.md negative (12)).

r4 recorded: flow at batch 8 kills the remote compiler (HTTP 500,
``tpu_compile_helper subprocess exit code 1``, NO scoped-vmem message — b4
and every other config compile in the same session). This tool narrows the
trigger by compiling b8 variants that each remove one suspect, then measures
gradient accumulation (2 microbatches of 4, one optimizer step — the
MFU-equivalent effective-b8 stand-in) with the device-trace statistic.

Variants (each a compile attempt; OOM/HTTP-500 is an ANSWER, not a flake —
CLAUDE.md):
  b8-fwd       forward only (no grad): is the backward the trigger?
  b8-xla       attn_impl=xla (no Pallas kernels): are the kernels involved?
  b8-remat     encoder remat on: does shrinking live activations fix it?
  b8-blocks    kernel blocks halved (kv 256, q 256): VMEM-shaped trigger?
  b6           batch 6: where between 4 and 8 does it die?
  b8           the full failing program (control)
  accum2x4     lax.scan over 2 microbatches of b4, summed grads, one update
               — compiles at b4's footprint, trains at effective batch 8

Usage: ``timeout 3600 python tools/flow_b8_bisect.py [variant ...]``
"""

from __future__ import annotations

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import jax
import jax.numpy as jnp
import numpy as np

from perceiver_io_tpu.models.flow import build_optical_flow_model, end_point_error
from perceiver_io_tpu.training import (
    OptimizerConfig,
    TrainState,
    make_optimizer,
)

DTYPE = jnp.bfloat16
rng = np.random.default_rng(0)


def _batch(b: int):
    return {
        "frames": jnp.asarray(rng.normal(0, 1, (b, 2, 368, 496, 3)), jnp.float32),
        "flow": jnp.asarray(rng.normal(0, 1, (b, 368, 496, 2)), jnp.float32),
    }


def _model(attn="auto", remat=False, kv_block=None, q_block=None):
    kwargs = {}
    if kv_block is not None or q_block is not None:
        # build_optical_flow_model has no block knobs; halved blocks are
        # injected via the resolution hook below instead
        pass
    return build_optical_flow_model(dtype=DTYPE, attn_impl=attn, remat=remat,
                                    **kwargs)


def _try_compile(name, fn, *args) -> str:
    t0 = time.perf_counter()
    try:
        lowered = jax.jit(fn).lower(*args)
        lowered.compile()
        dt = time.perf_counter() - t0
        return f"{name}: COMPILES ({dt:.0f} s)"
    except Exception as e:
        msg = str(e).replace("\n", " ")[:180]
        return f"{name}: FAIL {type(e).__name__}: {msg}"


def _loss_fn(model):
    def loss(params, batch):
        pred = model.apply({"params": params}, batch["frames"],
                           deterministic=True)
        return end_point_error(pred, batch["flow"])

    return loss


def main() -> None:
    only = set(sys.argv[1:])

    def want(name):
        return not only or name in only

    model = _model()
    init_b = _batch(1)
    variables = model.init({"params": jax.random.key(0)}, init_b["frames"])
    params = variables["params"]
    loss = _loss_fn(model)

    if want("b8-fwd"):
        b8 = _batch(8)
        print(_try_compile(
            "b8-fwd", lambda p, fr: model.apply({"params": p}, fr,
                                                deterministic=True),
            params, b8["frames"]), flush=True, file=sys.stderr)
    if want("b8-xla"):
        mx = _model(attn="xla")
        lx = _loss_fn(mx)
        print(_try_compile("b8-xla (grad)", jax.grad(lx), params, _batch(8)),
              flush=True, file=sys.stderr)
    if want("b8-remat"):
        mr = _model(remat=True)
        lr = _loss_fn(mr)
        print(_try_compile("b8-remat (grad)", jax.grad(lr), params, _batch(8)),
              flush=True, file=sys.stderr)
    if want("b8-blocks"):
        import perceiver_io_tpu.ops.pallas_attention as pa

        orig_kv, orig_q = pa.DEFAULT_KV_BLOCK, pa.DEFAULT_Q_BLOCK
        pa.DEFAULT_KV_BLOCK, pa.DEFAULT_Q_BLOCK = 256, 256
        try:
            print(_try_compile("b8-blocks kv256/q256 (grad)", jax.grad(loss),
                               params, _batch(8)), flush=True, file=sys.stderr)
        finally:
            pa.DEFAULT_KV_BLOCK, pa.DEFAULT_Q_BLOCK = orig_kv, orig_q
    if want("b6"):
        print(_try_compile("b6 (grad)", jax.grad(loss), params, _batch(6)),
              flush=True, file=sys.stderr)
    if want("b8"):
        print(_try_compile("b8 control (grad)", jax.grad(loss), params,
                           _batch(8)), flush=True, file=sys.stderr)

    if want("accum2x4"):
        # effective batch 8 at b4's compile footprint: scan 2 microbatches,
        # mean the grads, ONE optimizer update. Device-trace measured.
        tx, _ = make_optimizer(OptimizerConfig(learning_rate=1e-3))
        state = TrainState.create(params, tx, jax.random.key(2))
        big = _batch(8)
        stacked = jax.tree.map(
            lambda x: x.reshape(2, 4, *x.shape[1:]), big)

        def accum_step(state, stacked):
            def body(acc, micro):
                l, g = jax.value_and_grad(loss)(state.params, micro)
                return jax.tree.map(jnp.add, acc,
                                    jax.tree.map(lambda x: x / 2.0, g)), l

            zero = jax.tree.map(jnp.zeros_like, state.params)
            grads, losses = jax.lax.scan(body, zero, stacked)
            return state.apply_gradients(grads), losses.mean()

        jitted = jax.jit(accum_step, donate_argnums=(0,))
        res = _try_compile("accum2x4 (train step)", accum_step, state, stacked)
        print(res, flush=True, file=sys.stderr)
        if "COMPILES" in res:
            import tempfile

            from perceiver_io_tpu.utils import xplane

            state, l = jitted(state, stacked)
            float(l)
            td = tempfile.mkdtemp(prefix="flow_accum_")
            with jax.profiler.trace(td):
                for i in range(8):
                    with jax.profiler.StepTraceAnnotation("s", step_num=i):
                        state, l = jitted(state, stacked)
                float(l)
            sec, n = xplane.device_step_seconds(td, skip_first=2)
            print(f"accum2x4 device step: {sec * 1e3:.2f} ms "
                  f"(= {sec * 1e3 / 8:.2f} ms/example, {8 / sec:.2f} ex/s, "
                  f"{n} windows)", flush=True, file=sys.stderr)


if __name__ == "__main__":
    main()
