"""Real-chip Pallas × SPMD check: run the fused kernel through a sharded
train step on an actual TPU mesh.

The CPU test suite proves the composition in interpreter mode
(``tests/test_sharding.py``); this tool proves the COMPILED kernel partitions
and executes under mesh shardings on hardware — a 1-device mesh with
``shard_seq=True`` (and dp/tp/sp factors when more chips are present),
``attn_impl='pallas'`` end to end, long-context shapes so the streaming
kernel path is the one exercised.

Usage: ``timeout 300 python tools/tpu_pallas_spmd_check.py [--seq 8192]``
Prints one summary line per configuration; non-zero exit on failure.
"""

from __future__ import annotations

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from perceiver_io_tpu.utils.platform import probe_backend

import numpy as np


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument("--seq", type=int, default=8192)
    parser.add_argument("--batch", type=int, default=4)
    parser.add_argument("--steps", type=int, default=3)
    args = parser.parse_args()

    import jax
    import jax.numpy as jnp

    from perceiver_io_tpu.models.presets import flagship_mlm
    from perceiver_io_tpu.parallel import make_mesh, make_sharded_train_step
    from perceiver_io_tpu.training import (
        OptimizerConfig,
        TrainState,
        make_mlm_steps,
        make_optimizer,
        mlm_gather_capacity,
    )

    n = probe_backend().device_count
    print(f"backend={probe_backend().backend} devices={n}", file=sys.stderr)

    vocab, seq = 10003, args.seq
    model = flagship_mlm(
        vocab_size=vocab, max_seq_len=seq, num_latents=256, num_channels=64,
        dtype=jnp.bfloat16, attn_impl="pallas",
    )
    rng = np.random.default_rng(0)
    batch = {
        "token_ids": jnp.asarray(
            rng.integers(3, vocab, (args.batch, seq)).astype(np.int32)),
        "pad_mask": jnp.zeros((args.batch, seq), dtype=bool),
    }
    variables = model.init(
        {"params": jax.random.key(0), "masking": jax.random.key(1)},
        batch["token_ids"], batch["pad_mask"],
    )
    tx, sched = make_optimizer(OptimizerConfig(learning_rate=1e-3))
    train_step, _, _ = make_mlm_steps(
        model, sched, loss_gather_capacity=mlm_gather_capacity(seq)
    )

    # every dp/tp/sp factorization the device count allows, always with the
    # seq axis present (shard_seq=True is the long-context claim under test)
    tp = 2 if n % 2 == 0 else 1
    sp = 2 if n % (tp * 2) == 0 else 1
    configs = [(n // (tp * sp), tp, sp)] if n > 1 else [(1, 1, 1)]
    for dp, tp, sp in configs:
        mesh = make_mesh(dp=dp, tp=tp, sp=sp)
        state = TrainState.create(variables["params"], tx, jax.random.key(2))
        step, sstate, bshard = make_sharded_train_step(
            train_step, mesh, state, batch, shard_seq=True
        )
        placed = jax.device_put(batch, bshard)
        loss = None
        for _ in range(args.steps):
            sstate, metrics = step(sstate, placed)
            loss = float(metrics["loss"])  # host fetch = the honest sync
        assert np.isfinite(loss), f"non-finite loss {loss}"
        print(
            f"OK mesh(data={dp}, model={tp}, seq={sp}) seq={seq} "
            f"attn=pallas loss={loss:.4f}", file=sys.stderr)


if __name__ == "__main__":
    main()
