"""pitlint CLI: the repo-invariant static pass, one JSON line on stdout.

Usage::

    python tools/lint.py                  # full pass + sharding cross-check
    python tools/lint.py --changed        # only `git diff --name-only` files
    python tools/lint.py path/to/file.py  # explicit paths
    python tools/lint.py --write-baseline # re-absorb current findings

Exit 0 iff zero NON-BASELINED findings (and the cross-check passes); the
single stdout line reports counts by rule. Per-finding detail rides stderr.
CPU-only by construction (``ensure_cpu_only`` runs before jax can
initialize any backend — safe with the tunnel dark).
"""

from __future__ import annotations

import argparse
import os
import subprocess
import sys

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, ROOT)

from perceiver_io_tpu.utils.platform import ensure_cpu_only  # noqa: E402

ensure_cpu_only()

from perceiver_io_tpu.analysis import core  # noqa: E402
from perceiver_io_tpu.utils.jsonline import emit_json_line, log  # noqa: E402

# scope lives in analysis/core.py — ONE definition shared with the tier-1
# test so the local loop, CI, and the baseline can never disagree
DEFAULT_TARGETS = core.DEFAULT_TARGETS
TEST_FAULT_TARGETS = core.TEST_FAULT_TARGETS
DOC_TARGETS = core.DOC_TARGETS

# the cross-check matters only when these move; --changed runs skip it
# otherwise so the local loop never pays the jax import
CROSSCHECK_TRIGGERS = ("perceiver_io_tpu/parallel/sharding.py",
                       "perceiver_io_tpu/models/")


def changed_files() -> list:
    """Tracked changes vs HEAD plus untracked files — a brand-new tool with
    violations must not slip past the fast local loop unseen."""
    names: list = []
    for cmd in (["git", "diff", "--name-only", "HEAD"],
                ["git", "ls-files", "--others", "--exclude-standard"]):
        out = subprocess.run(
            cmd, cwd=ROOT, capture_output=True, text=True, check=False,
        ).stdout
        names.extend(l.strip() for l in out.splitlines() if l.strip())
    return sorted(set(names))


def scan_docs(paths) -> list:
    from perceiver_io_tpu.analysis.rules_faults import FaultSiteRule

    rule = FaultSiteRule()
    findings = []
    for rel in paths:
        path = os.path.join(ROOT, rel)
        if os.path.exists(path):
            with open(path, encoding="utf-8") as f:
                findings.extend(rule.check_text(rel, f.read()))
    return findings


def main() -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("paths", nargs="*",
                        help=f"files/dirs to lint (default: "
                             f"{' '.join(DEFAULT_TARGETS)})")
    parser.add_argument("--changed", action="store_true",
                        help="lint only files changed vs HEAD plus "
                             "untracked files (fast local loop)")
    parser.add_argument("--baseline", default=core.DEFAULT_BASELINE,
                        help="baseline-suppression file")
    parser.add_argument("--write-baseline", action="store_true",
                        help="absorb every current finding into the baseline "
                             "(then exits 0)")
    parser.add_argument("--no-crosscheck", action="store_true",
                        help="skip the sharding-rules × presets audit")
    args = parser.parse_args()

    fault_only_targets: list = []
    # full_scope: whether this invocation covers everything the baseline
    # covers — stale-entry detection (and --write-baseline pruning) is only
    # meaningful then; a partial scan would misread every entry for an
    # unscanned file as paid-down debt
    full_scope = not args.changed and not args.paths
    if args.changed:
        changed = changed_files()
        rels = [f for f in changed if f.endswith(".py")
                and os.path.exists(os.path.join(ROOT, f))]
        targets = [os.path.join(ROOT, f) for f in rels
                   if f.startswith(("perceiver_io_tpu/", "tools/"))
                   or f == "bench.py"]
        # tests/ carries PIT_FAULTS drill specs but legitimately prints and
        # reads wall clocks: fault-site rule only (same split as CI)
        fault_only_targets = [os.path.join(ROOT, f) for f in rels
                              if f.startswith("tests/")]
        run_crosscheck = not args.no_crosscheck and any(
            f.startswith(CROSSCHECK_TRIGGERS) for f in rels)
        doc_targets = [f for f in changed if f.endswith(".md")
                       and os.path.exists(os.path.join(ROOT, f))]
    elif args.paths:
        targets = [os.path.abspath(p) for p in args.paths]
        run_crosscheck = not args.no_crosscheck
        doc_targets = []
    else:
        targets = [os.path.join(ROOT, t) for t in DEFAULT_TARGETS]
        fault_only_targets = [os.path.join(ROOT, t)
                              for t in TEST_FAULT_TARGETS]
        run_crosscheck = not args.no_crosscheck
        doc_targets = list(DOC_TARGETS)

    # ONE tree walk: materialize the file lists, then feed them to the
    # scanner (iter_py_files passes file paths through unchanged)
    files = list(core.iter_py_files(targets))
    fault_only_files = list(core.iter_py_files(fault_only_targets))
    scanned = len(files) + len(fault_only_files)
    findings = core.scan_paths(files, root=ROOT) if files else []
    if fault_only_files:
        from perceiver_io_tpu.analysis.rules_faults import FaultSiteRule

        findings.extend(core.scan_paths(
            fault_only_files, rules=[FaultSiteRule()], root=ROOT))
    findings.extend(scan_docs(doc_targets))
    # repo hygiene, every invocation (one cheap walk): orphan bytecode must
    # never keep a deleted module importable — it is a property of the TREE,
    # not of any changed file, so --changed runs check it too
    findings.extend(core.scan_orphan_bytecode(
        ROOT, targets=(*DEFAULT_TARGETS, *TEST_FAULT_TARGETS)))
    findings.sort(key=lambda f: (f.path, f.line, f.rule))
    if run_crosscheck:
        from perceiver_io_tpu.analysis.crosscheck import audit_sharding_rules

        findings.extend(audit_sharding_rules())

    baseline = core.Baseline.load(args.baseline)
    if args.write_baseline:
        for f in findings:
            baseline.keys.setdefault(f.key(), "absorbed at baseline write")
        if full_scope:
            # pruning needs the full picture: on a partial scan every entry
            # for an unscanned file would look paid-down and be deleted
            for stale in baseline.stale_keys(findings):
                del baseline.keys[stale]
        else:
            log("lint: partial scan — baseline entries absorbed, none "
                "pruned (run without --changed/paths to prune)")
        baseline.save(args.baseline)
        log(f"lint: baseline rewritten with {len(baseline.keys)} entries "
            f"-> {args.baseline}")

    new, baselined = baseline.split(findings)
    stale = baseline.stale_keys(findings) if full_scope else []

    by_rule: dict = {}
    for f in findings:
        by_rule[f.rule] = by_rule.get(f.rule, 0) + 1
    for f in new:
        log(f"lint: NEW {f.render()}")
    for key in stale:
        log(f"lint: stale baseline entry (debt paid — prune it): {key}")

    ok = not new and not stale
    emit_json_line({
        "tool": "pitlint",
        "files": scanned,
        "findings_total": len(findings),
        "by_rule": dict(sorted(by_rule.items())),
        "baselined": len(baselined),
        "new": len(new),
        "stale_baseline": len(stale),
        "crosscheck": bool(run_crosscheck),
        "ok": ok,
    })
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
