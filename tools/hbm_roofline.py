"""Hardware-trace roofline for any BASELINE config: device-measured step
time, achieved HBM bandwidth, and TRACE-MEASURED MFU.

Captures a ``jax.profiler`` trace of one full train step on the real TPU,
parses the xplane (via ``perceiver_io_tpu.utils.xplane`` — the tensorboard-
plugin converter is incompatible with this TF build), and reports:

- device-measured step time (from the trace's Steps line — immune to the
  tunneled-backend timing lies PERF.md documents),
- achieved HBM bytes/s vs the device's own advertised peak, plus on-chip
  (VMEM) bytes/s,
- **trace-measured MFU**: model FLOPs ÷ (device step time × peak). The
  numerator comes from XLA cost analysis of the SAME config compiled with
  ``attn_impl='xla'`` (identical math, no custom calls) — because cost
  analysis counts ZERO flops for Pallas custom-calls, summing per-op trace
  flops would under-report exactly the configs whose hot ops run in the
  kernels (the PERF.md caveat this tool closes; VERDICT r2 item 4). The
  denominator is hardware-measured, so Pallas time is fully counted.
- a per-component table (duration, HBM/VMEM bandwidth, TF/s) so the binding
  resource of each phase is visible. (Per-op TF/s shows 0 for Pallas
  custom-calls — cost-analysis metadata, trust the aggregate MFU.)

Byte counts come from XLA's per-op cost analysis embedded in the trace
(``memory_access_breakdown``); durations are hardware-measured. This is the
same bytes-modeled/time-measured definition the TensorBoard profiler's
"memory BW utilization" uses. Memory-space code 1 is HBM, 3 is on-chip
(verified empirically: space-3 aggregate bandwidth exceeds the HBM peak
severalfold, and known-HBM-resident tensors — the vocab embedding table,
optimizer state — report space 1).

Usage::

    timeout 900 python tools/hbm_roofline.py [--config mlm|imagenet|imagenet8h|flow|mnist|multimodal]
                                             [--steps 10] [--components 12]
                                             [--trace-dir DIR]  # re-analyze
"""

from __future__ import annotations

import argparse
import os
import sys
import tempfile
from collections import defaultdict

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

HBM_SPACE, ONCHIP_SPACE = 1, 3


def _varint(buf: bytes, i: int):
    r = 0
    s = 0
    while True:
        b = buf[i]
        i += 1
        r |= (b & 0x7F) << s
        if not b & 0x80:
            return r, i
        s += 7


def parse_memory_breakdown(buf: bytes):
    """Decode the repeated {operation_type, memory_space, bytes} submessages
    of the ``memory_access_breakdown`` stat."""
    out = []
    i = 0
    while i < len(buf):
        tag, i = _varint(buf, i)
        if tag != 0x0A:
            break
        ln, i = _varint(buf, i)
        sub = buf[i : i + ln]
        i += ln
        j = 0
        op = space = nbytes = 0
        while j < len(sub):
            t, j = _varint(sub, j)
            v, j = _varint(sub, j)
            if t == 0x08:
                op = v
            elif t == 0x10:
                space = v
            elif t == 0x18:
                nbytes = v
        out.append((op, space, nbytes))
    return out


def _build(config: str):
    """(state, jitted_step, batch, batch_size) for a named e2e config."""
    import jax

    from e2e_configs_bench import CONFIGS
    from perceiver_io_tpu.training import (
        OptimizerConfig,
        TrainState,
        make_optimizer,
    )

    variables, train_step, batch, batch_size = CONFIGS[config]()
    tx, _ = make_optimizer(OptimizerConfig(learning_rate=1e-3))
    state = TrainState.create(variables["params"], tx, jax.random.key(2))
    return state, jax.jit(train_step, donate_argnums=(0,)), batch, batch_size


def model_flops_per_step(config: str) -> float | None:
    """Cost-analysis FLOPs of the config compiled with attn_impl='xla'.

    Runs in a SUBPROCESS because the attention impl is baked in at model
    construction via the PIT_E2E_ATTN env, which this process has already
    read."""
    import json
    import subprocess

    tools_dir = os.path.dirname(os.path.abspath(__file__))
    code = (
        "import os, sys, json\n"
        f"sys.path.insert(0, {tools_dir!r})\n"
        f"sys.path.insert(0, {os.path.dirname(tools_dir)!r})\n"
        "os.environ['PIT_E2E_ATTN'] = 'xla'\n"
        "os.environ['PIT_E2E_HEAD'] = 'none'\n"  # count the head's flops too\n
        "import jax\n"
        "from e2e_configs_bench import CONFIGS\n"
        "from perceiver_io_tpu.training import (OptimizerConfig, TrainState,\n"
        "                                       make_optimizer)\n"
        "from perceiver_io_tpu.utils import profiling\n"
        f"variables, train_step, batch, _ = CONFIGS[{config!r}]()\n"
        "tx, _ = make_optimizer(OptimizerConfig(learning_rate=1e-3))\n"
        "state = TrainState.create(variables['params'], tx, jax.random.key(2))\n"
        "jitted = jax.jit(train_step, donate_argnums=(0,))\n"
        "flops = profiling.compiled_flops(jitted, state, batch)\n"
        "print(json.dumps({'flops': flops}))\n"
    )
    try:
        out = subprocess.run(
            [sys.executable, "-c", code], capture_output=True, text=True,
            timeout=560, check=True,
        )
        return json.loads(out.stdout.strip().splitlines()[-1])["flops"]
    except Exception as e:
        print(f"(flops subprocess failed: {e}; MFU omitted)", file=sys.stderr)
        return None


def capture_trace(trace_dir: str, config: str, steps: int) -> int:
    """Run + trace the config's train step; returns the batch size."""
    state, jitted, batch, batch_size = _build(config)

    import jax

    state, m = jitted(state, batch)  # compile + warm
    float(m["loss"])
    jax.profiler.start_trace(trace_dir)
    for _ in range(steps):
        state, m = jitted(state, batch)
    float(m["loss"])
    jax.profiler.stop_trace()
    return batch_size


def analyze(trace_dir: str, n_components: int, batch_size: int | None,
            flops_per_step: float | None) -> dict:
    from perceiver_io_tpu.utils.xplane import load_tpu_plane, step_windows

    tpu = load_tpu_plane(trace_dir)
    names = {k: v.name for k, v in tpu.stat_metadata.items()}

    peaks = {}
    for s in tpu.stats:
        peaks[names.get(s.metadata_id)] = s.double_value
    peak_hbm = peaks.get("peak_hbm_bw_gigabytes_per_second") or 819.0
    peak_tf = peaks.get("peak_teraflops_per_second") or 197.0

    windows = step_windows(tpu)
    windows = windows[2:] if len(windows) > 4 else windows  # steady state
    n_steps = len(windows)
    step_s = sum(b - a for a, b in windows) / 1e12 / n_steps
    # robust capability estimate on a time-shared chip (see
    # utils.xplane.device_step_seconds): lower quartile of per-step durations
    durs = sorted(b - a for a, b in windows)
    step_s_lq = durs[len(durs) // 4] / 1e12

    meta = {}
    for mid, em in tpu.event_metadata.items():
        st = {names.get(s.metadata_id): s for s in em.stats}
        if "memory_access_breakdown" not in st:
            continue
        brk = parse_memory_breakdown(st["memory_access_breakdown"].bytes_value)
        hbm = sum(b for _, sp, b in brk if sp == HBM_SPACE)
        onchip = sum(b for _, sp, b in brk if sp == ONCHIP_SPACE)
        flops = st["flops"].int64_value if "flops" in st else 0
        src = st["tf_op"].str_value if "tf_op" in st else ""
        key = (
            src.split("jvp(")[-1].split(":")[0][:64]
            if src else em.name.split(" = ")[0][:40]
        )
        meta[mid] = (hbm, onchip, flops, key)

    ops_line = [l for l in tpu.lines if l.name == "XLA Ops"][0]
    tot_hbm = tot_onchip = tot_flops = 0
    comp = defaultdict(lambda: [0, 0, 0, 0])
    for e in ops_line.events:
        if not any(a <= e.offset_ps < b for a, b in windows):
            continue
        m = meta.get(e.metadata_id)
        if m is None:
            continue
        hbm, onchip, flops, key = m
        tot_hbm += hbm
        tot_onchip += onchip
        tot_flops += flops
        row = comp[key]
        row[0] += e.duration_ps
        row[1] += hbm
        row[2] += onchip
        row[3] += flops

    result = {
        "step_ms": step_s * 1e3,
        "step_ms_lower_quartile": step_s_lq * 1e3,
        "hbm_gb_per_step": tot_hbm / n_steps / 1e9,
        "hbm_gb_s": tot_hbm / n_steps / step_s / 1e9,
        "hbm_peak_gb_s": peak_hbm,
        "hbm_util": tot_hbm / n_steps / step_s / 1e9 / peak_hbm,
        "onchip_gb_s": tot_onchip / n_steps / step_s / 1e9,
        "trace_op_tf_s": tot_flops / n_steps / step_s / 1e12,
    }
    if batch_size:
        result["examples_per_sec"] = batch_size / step_s
    if flops_per_step:
        result["model_tf_per_step"] = flops_per_step / 1e12
        result["mfu"] = flops_per_step / step_s / 1e12 / peak_tf
        result["mfu_lower_quartile_step"] = (
            flops_per_step / step_s_lq / 1e12 / peak_tf
        )

    print(
        f"device step: {result['step_ms']:.3f} ms mean / "
        f"{result['step_ms_lower_quartile']:.3f} ms lower-quartile"
        + (f" ({result['examples_per_sec']:.1f} ex/s)" if batch_size else ""), file=sys.stderr)
    print(
        f"HBM: {result['hbm_gb_per_step']:.2f} GB/step -> "
        f"{result['hbm_gb_s']:.0f} GB/s = {result['hbm_util']*100:.1f}% of "
        f"{peak_hbm:.0f} GB/s peak; on-chip {result['onchip_gb_s']:.0f} GB/s", file=sys.stderr)
    if "mfu" in result:
        print(
            f"MFU (trace-measured): {result['mfu']*100:.1f}% mean / "
            f"{result['mfu_lower_quartile_step']*100:.1f}% lower-quartile "
            f"({result['model_tf_per_step']:.2f} TF/step vs {peak_tf:.0f} "
            f"TF/s peak)", file=sys.stderr)
    print(
        f"(per-op trace flops sum: {result['trace_op_tf_s']:.1f} TF/s — "
        f"undercounts Pallas custom-calls)", file=sys.stderr)
    print(f"\n{'ms':>7} {'HBM GB/s':>8} {'chip GB/s':>9} {'TF/s':>6}  component", file=sys.stderr)
    rows = sorted(comp.items(), key=lambda kv: -kv[1][0])[:n_components]
    for key, (d, h, o, f) in rows:
        sec = d / 1e12 / n_steps
        if sec <= 0:
            continue
        print(
            f"{sec*1e3:7.3f} {h/n_steps/sec/1e9:8.0f} "
            f"{o/n_steps/sec/1e9:9.0f} {f/n_steps/sec/1e12:6.2f}  {key[:66]}", file=sys.stderr)
    return result


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument("--config", default=None,
                        help="e2e config name (see tools/e2e_configs_bench.py); "
                             "default mlm when capturing. With --trace-dir it "
                             "must be passed explicitly for MFU — the trace "
                             "doesn't record which config produced it, and a "
                             "mismatched numerator would report a confidently "
                             "wrong MFU")
    parser.add_argument("--steps", type=int, default=10)
    parser.add_argument("--components", type=int, default=12)
    parser.add_argument("--batch-size", type=int, default=None,
                        help="with --trace-dir: batch size for ex/s")
    parser.add_argument("--no-mfu", action="store_true",
                        help="skip the flops subprocess (faster)")
    parser.add_argument("--flops", type=float, default=None,
                        help="MFU numerator in FLOPs/step, bypassing the "
                             "cost-analysis subprocess — for re-runs where "
                             "the numerator is already known (it is shape-"
                             "stable per config), or when the subprocess's "
                             "compile window is squeezed by a busy chip "
                             "(the multimodal numerator compile alone can "
                             "exceed it)")
    parser.add_argument("--trace-dir", default=None,
                        help="analyze an existing trace instead of capturing")
    args = parser.parse_args()

    from perceiver_io_tpu.aot import maybe_enable_cache_from_env

    maybe_enable_cache_from_env()  # PIT_COMPILE_CACHE opt-in (stderr only)
    os.environ.setdefault("PROTOCOL_BUFFERS_PYTHON_IMPLEMENTATION", "python")

    config = args.config
    if config is None:
        if args.trace_dir is not None:
            if args.flops is None:
                print("(--trace-dir without --config: MFU omitted — pass "
                      "the config that produced the trace, or --flops)", file=sys.stderr)
        else:
            config = "mlm"

    flops = args.flops
    if flops is not None:
        print(f"(MFU numerator: {flops / 1e12:.2f} TF/step, caller-supplied)", file=sys.stderr)
    elif config is not None and not args.no_mfu:
        flops = model_flops_per_step(config)
        if flops:
            print(f"(MFU numerator: {config} config, "
                  f"{flops / 1e12:.2f} TF/step from XLA cost analysis)", file=sys.stderr)
    trace_dir = args.trace_dir
    batch_size = args.batch_size
    if trace_dir is None:
        trace_dir = tempfile.mkdtemp(prefix=f"hbm_roofline_{config}_")
        print(f"capturing {args.steps}-step {config} trace to {trace_dir} ...", file=sys.stderr)
        batch_size = capture_trace(trace_dir, config, args.steps)
    analyze(trace_dir, args.components, batch_size, flops)


if __name__ == "__main__":
    main()
