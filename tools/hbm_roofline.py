"""Measure the flagship-MLM step's achieved HBM bandwidth / MXU utilization
from a device profile (the roofline evidence VERDICT r1 asked for).

Captures a ``jax.profiler`` trace of the bench train step on the real TPU,
parses the xplane directly (the tensorboard-plugin converter is incompatible
with this TF build), and reports:

- device-measured step time (from the trace's Steps line — immune to the
  tunneled-backend timing lies PERF.md documents),
- achieved HBM bytes/s vs the device's own advertised peak, plus MXU TF/s
  and on-chip (VMEM) bytes/s,
- a per-component table (duration, HBM/VMEM bandwidth, TF/s) so the binding
  resource of each phase is visible.

Byte counts come from XLA's per-op cost analysis embedded in the trace
(``memory_access_breakdown``); durations are hardware-measured. This is the
same bytes-modeled/time-measured definition the TensorBoard profiler's
"memory BW utilization" uses. Memory-space code 1 is HBM, 3 is on-chip
(verified empirically: space-3 aggregate bandwidth exceeds the HBM peak
severalfold, and known-HBM-resident tensors — the vocab embedding table,
optimizer state — report space 1).

Usage: ``timeout 600 python tools/hbm_roofline.py [--steps 10] [--components 12]``
"""

from __future__ import annotations

import argparse
import glob
import os
import tempfile
from collections import defaultdict

HBM_SPACE, ONCHIP_SPACE = 1, 3


def _varint(buf: bytes, i: int):
    r = 0
    s = 0
    while True:
        b = buf[i]
        i += 1
        r |= (b & 0x7F) << s
        if not b & 0x80:
            return r, i
        s += 7


def parse_memory_breakdown(buf: bytes):
    """Decode the repeated {operation_type, memory_space, bytes} submessages
    of the ``memory_access_breakdown`` stat."""
    out = []
    i = 0
    while i < len(buf):
        tag, i = _varint(buf, i)
        if tag != 0x0A:
            break
        ln, i = _varint(buf, i)
        sub = buf[i : i + ln]
        i += ln
        j = 0
        op = space = nbytes = 0
        while j < len(sub):
            t, j = _varint(sub, j)
            v, j = _varint(sub, j)
            if t == 0x08:
                op = v
            elif t == 0x10:
                space = v
            elif t == 0x18:
                nbytes = v
        out.append((op, space, nbytes))
    return out


def capture_trace(trace_dir: str, steps: int) -> None:
    import jax
    import jax.numpy as jnp
    import numpy as np

    from perceiver_io_tpu.models.presets import flagship_mlm
    from perceiver_io_tpu.training import (
        OptimizerConfig,
        TrainState,
        make_mlm_steps,
        make_optimizer,
        mlm_gather_capacity,
    )

    vocab, seq = 10003, 512
    model = flagship_mlm(
        vocab_size=vocab, max_seq_len=seq, num_latents=256, num_channels=64,
        dtype=jnp.bfloat16, attn_impl="xla",
    )
    rng = np.random.default_rng(0)
    batch = {
        "token_ids": jnp.asarray(
            rng.integers(3, vocab, (64, seq)).astype(np.int32)),
        "pad_mask": jnp.zeros((64, seq), dtype=bool),
    }
    variables = model.init(
        {"params": jax.random.key(0), "masking": jax.random.key(1)},
        batch["token_ids"], batch["pad_mask"],
    )
    tx, sched = make_optimizer(OptimizerConfig(learning_rate=1e-3))
    state = TrainState.create(variables["params"], tx, jax.random.key(2))
    train_step, _, _ = make_mlm_steps(
        model, sched, loss_gather_capacity=mlm_gather_capacity(seq),
        fused_head=False,
    )
    step = jax.jit(train_step, donate_argnums=(0,))
    state, m = step(state, batch)  # compile + warm
    float(m["loss"])
    jax.profiler.start_trace(trace_dir)
    for _ in range(steps):
        state, m = step(state, batch)
    float(m["loss"])
    jax.profiler.stop_trace()


def analyze(trace_dir: str, n_components: int) -> dict:
    from tensorflow.tsl.profiler.protobuf import xplane_pb2

    paths = glob.glob(
        os.path.join(trace_dir, "plugins", "profile", "*", "*.xplane.pb")
    )
    if not paths:
        raise FileNotFoundError(f"no xplane.pb under {trace_dir}")
    xs = xplane_pb2.XSpace()
    with open(sorted(paths)[-1], "rb") as f:
        xs.ParseFromString(f.read())
    tpu_planes = [p for p in xs.planes if "/device:TPU" in p.name and p.lines]
    if not tpu_planes:
        raise RuntimeError("no TPU device plane in trace (ran on CPU?)")
    tpu = tpu_planes[0]
    names = {k: v.name for k, v in tpu.stat_metadata.items()}

    peaks = {}
    for s in tpu.stats:
        peaks[names.get(s.metadata_id)] = s.double_value
    peak_hbm = peaks.get("peak_hbm_bw_gigabytes_per_second") or 819.0
    peak_tf = peaks.get("peak_teraflops_per_second") or 197.0

    step_line = [l for l in tpu.lines if l.name == "Steps"][0]
    windows = [
        (e.offset_ps, e.offset_ps + e.duration_ps) for e in step_line.events
    ]
    windows = windows[2:] if len(windows) > 4 else windows  # steady state
    n_steps = len(windows)
    step_s = sum(b - a for a, b in windows) / 1e12 / n_steps

    meta = {}
    for mid, em in tpu.event_metadata.items():
        st = {names.get(s.metadata_id): s for s in em.stats}
        if "memory_access_breakdown" not in st:
            continue
        brk = parse_memory_breakdown(st["memory_access_breakdown"].bytes_value)
        hbm = sum(b for _, sp, b in brk if sp == HBM_SPACE)
        onchip = sum(b for _, sp, b in brk if sp == ONCHIP_SPACE)
        flops = st["flops"].int64_value if "flops" in st else 0
        src = st["tf_op"].str_value if "tf_op" in st else ""
        key = (
            src.split("jvp(")[-1].split(":")[0][:64]
            if src else em.name.split(" = ")[0][:40]
        )
        meta[mid] = (hbm, onchip, flops, key)

    ops_line = [l for l in tpu.lines if l.name == "XLA Ops"][0]
    tot_hbm = tot_onchip = tot_flops = 0
    comp = defaultdict(lambda: [0, 0, 0, 0])
    for e in ops_line.events:
        if not any(a <= e.offset_ps < b for a, b in windows):
            continue
        m = meta.get(e.metadata_id)
        if m is None:
            continue
        hbm, onchip, flops, key = m
        tot_hbm += hbm
        tot_onchip += onchip
        tot_flops += flops
        row = comp[key]
        row[0] += e.duration_ps
        row[1] += hbm
        row[2] += onchip
        row[3] += flops

    result = {
        "step_ms": step_s * 1e3,
        "tokens_per_sec": 64 * 512 / step_s,
        "hbm_gb_per_step": tot_hbm / n_steps / 1e9,
        "hbm_gb_s": tot_hbm / n_steps / step_s / 1e9,
        "hbm_peak_gb_s": peak_hbm,
        "hbm_util": tot_hbm / n_steps / step_s / 1e9 / peak_hbm,
        "onchip_gb_s": tot_onchip / n_steps / step_s / 1e9,
        "tf_s": tot_flops / n_steps / step_s / 1e12,
        "mxu_util": tot_flops / n_steps / step_s / 1e12 / peak_tf,
    }

    print(
        f"device step: {result['step_ms']:.3f} ms "
        f"({result['tokens_per_sec']/1e6:.2f}M tokens/s/chip)"
    )
    print(
        f"HBM: {result['hbm_gb_per_step']:.2f} GB/step -> "
        f"{result['hbm_gb_s']:.0f} GB/s = {result['hbm_util']*100:.1f}% of "
        f"{peak_hbm:.0f} GB/s peak"
    )
    print(
        f"MXU: {result['tf_s']:.1f} TF/s = {result['mxu_util']*100:.1f}% of "
        f"{peak_tf:.0f} TF/s peak; on-chip {result['onchip_gb_s']:.0f} GB/s"
    )
    print(f"\n{'ms':>7} {'HBM GB/s':>8} {'chip GB/s':>9} {'TF/s':>6}  component")
    rows = sorted(comp.items(), key=lambda kv: -kv[1][0])[:n_components]
    for key, (d, h, o, f) in rows:
        sec = d / 1e12 / n_steps
        if sec <= 0:
            continue
        print(
            f"{sec*1e3:7.3f} {h/n_steps/sec/1e9:8.0f} "
            f"{o/n_steps/sec/1e9:9.0f} {f/n_steps/sec/1e12:6.2f}  {key[:66]}"
        )
    return result


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument("--steps", type=int, default=10)
    parser.add_argument("--components", type=int, default=12)
    parser.add_argument("--trace-dir", default=None,
                        help="analyze an existing trace instead of capturing")
    args = parser.parse_args()
    os.environ.setdefault("PROTOCOL_BUFFERS_PYTHON_IMPLEMENTATION", "python")

    trace_dir = args.trace_dir
    if trace_dir is None:
        trace_dir = tempfile.mkdtemp(prefix="hbm_roofline_")
        print(f"capturing {args.steps}-step trace to {trace_dir} ...")
        capture_trace(trace_dir, args.steps)
    analyze(trace_dir, args.components)


if __name__ == "__main__":
    main()
