#!/usr/bin/env python
"""Decode-scheduler flight-recorder analysis: attribute idle slot-rounds.

The continuous-batching dispatcher records every scheduler round into a
bounded ring (``perceiver_io_tpu.inference.batching.DecodeFlightRecorder``)
and spools it to the event log as ``decode_flight_batch`` events (plus
``decode_flight_dump`` on watchdog stall / SIGTERM). This tool replays
those packed rows through the one row grammar (``parse_flight_row``) and
answers the post-mortem question the recorder exists for: *when arena
slots sat idle, why* — every idle slot-round attributed to a cause from
``FLIGHT_CAUSES`` (``no_pending | width_mismatch | arena_full |
draining``), plus eviction reasons, arena growth, and admission-queue
high-water marks.

Modes:

- ``--events FILE``: offline analysis of an events JSONL (the
  ``--events_jsonl`` file a replica / cli.serve run wrote).
- ``--drill``: in-process CPU drill — runs a tiny continuous batcher
  through mixed-width traffic, a drain, and a mid-stream kill, spools its
  flight ring to a temp event log, and analyzes that log through the SAME
  offline path. The acceptance gate rides this: ``attribution_frac`` must
  be >= 0.95 and the kill must land as an ``E|killed`` row.

Emits exactly ONE JSON line on stdout; progress rides stderr.
``--dry`` declares the record keys without touching any backend.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import threading
import time
from typing import Any, Dict, List

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from perceiver_io_tpu.utils.jsonline import emit_json_line  # noqa: E402

RECORD_KEYS = (
    "metric", "dry", "mode", "engines", "rounds", "slot_rounds",
    "idle_slot_rounds", "attributed", "attribution_frac", "causes",
    "evicts", "grows", "admits", "retires", "pending_max", "batches",
    "dumps", "dump_reasons", "drill",
)


def _log(msg: str) -> None:
    print(f"decode_flight: {msg}", file=sys.stderr, flush=True)


def analyze_rows(rows_by_engine: Dict[str, List[str]],
                 batches: int = 0, dumps: int = 0,
                 dump_reasons: List[str] = ()) -> Dict[str, Any]:
    """Aggregate parsed flight rows into the attribution record (shared by
    ``--events`` and ``--drill``; the dedup key for dump-replayed rows is
    the round sequence number, so a ring tail re-emitted by a dump never
    double-counts)."""
    from perceiver_io_tpu.inference.batching import parse_flight_row

    agg = {
        "rounds": 0, "slot_rounds": 0, "idle_slot_rounds": 0,
        "attributed": 0, "causes": {}, "evicts": {}, "grows": 0,
        "admits": 0, "retires": 0, "pending_max": 0,
    }
    for engine, rows in rows_by_engine.items():
        seen_rounds = set()
        seen_other = set()
        for row in rows:
            rec = parse_flight_row(row)
            if rec["kind"] == "round":
                if rec["seq"] in seen_rounds:
                    continue
                seen_rounds.add(rec["seq"])
                agg["rounds"] += 1
                agg["admits"] += rec["admits"]
                agg["retires"] += rec["retires"]
                agg["pending_max"] = max(agg["pending_max"], rec["pending"])
                for arena in rec["arenas"]:
                    agg["slot_rounds"] += arena["slots"]
                    agg["idle_slot_rounds"] += (arena["slots"]
                                                - arena["active"])
                    for cause, n in arena["causes"].items():
                        agg["causes"][cause] = (
                            agg["causes"].get(cause, 0) + n)
                        agg["attributed"] += n
            elif rec["kind"] == "evict":
                if row in seen_other:
                    continue
                seen_other.add(row)
                agg["evicts"][rec["reason"]] = (
                    agg["evicts"].get(rec["reason"], 0) + 1)
            elif rec["kind"] == "grow":
                if row in seen_other:
                    continue
                seen_other.add(row)
                agg["grows"] += 1
    idle = agg["idle_slot_rounds"]
    agg["attribution_frac"] = (round(agg["attributed"] / idle, 4)
                               if idle else 1.0)
    agg["engines"] = sorted(rows_by_engine)
    agg["batches"] = batches
    agg["dumps"] = dumps
    agg["dump_reasons"] = sorted(set(dump_reasons))
    return agg


def analyze_events(path: str) -> Dict[str, Any]:
    """Pull every ``decode_flight_batch`` / ``decode_flight_dump`` event
    out of an events JSONL and aggregate their rows per engine."""
    rows_by_engine: Dict[str, List[str]] = {}
    batches = dumps = 0
    dump_reasons: List[str] = []
    with open(path) as fh:
        for line in fh:
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except json.JSONDecodeError:
                continue  # a torn tail line must not kill the post-mortem
            kind = rec.get("event")
            if kind not in ("decode_flight_batch", "decode_flight_dump"):
                continue
            engine = rec.get("engine", "?")
            parts = rec.get("parts") or ""
            rows = [r for r in parts.split(";") if r]
            rows_by_engine.setdefault(engine, []).extend(rows)
            if kind == "decode_flight_batch":
                batches += 1
            else:
                dumps += 1
                dump_reasons.append(rec.get("reason", "?"))
    return analyze_rows(rows_by_engine, batches=batches, dumps=dumps,
                        dump_reasons=dump_reasons)


def run_drill(events_path: str) -> Dict[str, Any]:
    """The in-process cause-coverage drill (CPU): mixed-width traffic on a
    2-slot arena (no_pending + width_mismatch rounds), then a mid-stream
    close (a ``killed`` eviction + ``draining`` attribution), spooled to
    ``events_path`` and analyzed offline like any crash artifact."""
    import jax
    import numpy as np

    import perceiver_io_tpu.obs as obs
    from perceiver_io_tpu.inference.batching import ContinuousBatcher
    from perceiver_io_tpu.inference.generate import SamplingConfig
    from perceiver_io_tpu.models.presets import tiny_ar

    obs.configure_event_log(events_path)
    model = tiny_ar()
    max_seq_len = 64
    ids0 = np.zeros((1, max_seq_len), np.int32)
    params = model.init({"params": jax.random.key(0)}, ids0,
                        ids0 == 0)["params"]
    gen = ContinuousBatcher(model, params, max_seq_len=max_seq_len,
                            chunk=4, slots=2, max_slots=4,
                            name="flight-drill",
                            registry=obs.MetricsRegistry())
    sampling = SamplingConfig()
    rng = np.random.default_rng(0)

    def stream(plen: int, max_new: int):
        prefix = [int(t) for t in rng.integers(3, 100, plen)]
        return gen.generate(prefix, max_new, sampling)

    drill: Dict[str, Any] = {}
    try:
        # phase 1 — short prefixes, more streams than slots: admission
        # churn, then a tail of no_pending rounds as the queue drains
        _log("drill phase 1: 4 short-width streams on 2 slots")
        threads = [threading.Thread(target=stream, args=(4, 8), daemon=True)
                   for _ in range(4)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        # phase 2 — two prefix populations planning different episode
        # widths (tiny_ar: 4 tokens -> width 16, 40 tokens -> width 46),
        # with the long-width arena OVERSUBSCRIBED (6 streams on <= 4
        # slots): while the queue holds only long-width work, the short-
        # width arena's idle slots attribute width_mismatch
        _log("drill phase 2: mixed widths, long-width arena oversubscribed")
        threads = ([threading.Thread(target=stream, args=(40, 12),
                                     daemon=True) for _ in range(6)]
                   + [threading.Thread(target=stream, args=(4, 4),
                                       daemon=True)])
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        # phase 3 — the kill: a long stream dies mid-flight when the
        # engine closes under it (the replica-killed-mid-stream drill)
        _log("drill phase 3: close the engine under a live stream")
        killed_err: List[str] = []

        def doomed():
            try:
                stream(4, 400)
            except Exception as e:
                killed_err.append(type(e).__name__)

        t = threading.Thread(target=doomed, daemon=True)
        t.start()
        time.sleep(0.3)  # let it bind a slot and decode a few chunks
        gen.close()
        t.join(timeout=10)
        drill["killed_stream_error"] = (killed_err[0] if killed_err
                                        else None)
        drill["summary_in_process"] = gen.flight.summary()
    finally:
        try:
            gen.close()
        except Exception:
            pass
        obs.configure_event_log(None)  # flush + close the JSONL
    rec = analyze_events(events_path)
    rec["drill"] = drill
    return rec


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    mode = ap.add_mutually_exclusive_group()
    mode.add_argument("--events", metavar="FILE",
                      help="analyze decode_flight_* events in this JSONL")
    mode.add_argument("--drill", action="store_true",
                      help="run the in-process CPU cause-coverage drill")
    mode.add_argument("--dry", action="store_true",
                      help="declare the record keys; no backend")
    ap.add_argument("--drill_events", default=None, metavar="FILE",
                    help="drill mode: write the drill's event log here "
                         "(default: a temp file, removed after)")
    args = ap.parse_args(argv)

    if args.dry:
        emit_json_line({"metric": "decode_flight", "dry": True,
                        "record_keys": list(RECORD_KEYS)})
        return 0
    if args.events:
        rec = analyze_events(args.events)
        rec.update(metric="decode_flight", dry=False, mode="events",
                   drill=None)
        emit_json_line(rec)
        return 0
    if args.drill:
        from perceiver_io_tpu.utils.platform import ensure_cpu_only

        ensure_cpu_only()  # the drill is a scheduler test, never a TPU job
        import tempfile

        path = args.drill_events
        cleanup = path is None
        if path is None:
            fd, path = tempfile.mkstemp(suffix=".jsonl",
                                        prefix="decode-flight-drill-")
            os.close(fd)
        try:
            rec = run_drill(path)
        finally:
            if cleanup:
                try:
                    os.unlink(path)
                except OSError:
                    pass
        rec.update(metric="decode_flight", dry=False, mode="drill")
        emit_json_line(rec)
        return 0
    ap.error("pick one of --events FILE, --drill, --dry")
    return 2


if __name__ == "__main__":
    sys.exit(main())
