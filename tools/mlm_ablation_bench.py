"""Developer tool: where does the flagship MLM step's time go?

Times config ablations of the train step with the honest sync discipline
(PERF.md): chain donated state, fetch the loss scalar, subtract a 1-iter run.
Each row removes one component, so deltas attribute time to components:

  full         the bench step (3 layers x 6 self-attn, gather decode)
  full-decode  all 512 positions decoded (reference-shaped CE)
  no-decode    loss on latent mean instead of decoder+CE
  no-self      blocks of 1 self-attention layer (delta = the 15 removed layers)
  one-layer    num_layers=1 (no shared-layer recurrence)
  fwd-only     no backward/optimizer (forward + loss only)
"""

from __future__ import annotations

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from perceiver_io_tpu.utils.platform import probe_backend

import jax
import jax.numpy as jnp
import numpy as np

import perceiver_io_tpu as pit
from perceiver_io_tpu.ops.masking import TextMasking
from perceiver_io_tpu.training import (
    OptimizerConfig,
    TrainState,
    make_mlm_steps,
    make_optimizer,
    mlm_gather_capacity,
)

VOCAB, SEQ, NLAT, C = 10003, 512, 256, 64
BATCH = int(os.environ.get("PIT_BENCH_BATCH", "64"))
STEPS = int(os.environ.get("PIT_BENCH_STEPS", "20"))


def build(num_layers=3, blocks=6, attn_impl="xla"):
    latent_shape = (NLAT, C)
    return pit.PerceiverMLM(
        encoder=pit.PerceiverEncoder(
            input_adapter=pit.TextInputAdapter(
                vocab_size=VOCAB, max_seq_len=SEQ, num_channels=C,
                dtype=jnp.bfloat16,
            ),
            latent_shape=latent_shape,
            num_layers=num_layers,
            num_self_attention_layers_per_block=blocks,
            dtype=jnp.bfloat16,
            attn_impl=attn_impl,
        ),
        decoder=pit.PerceiverDecoder(
            output_adapter=pit.TextOutputAdapter(
                vocab_size=VOCAB, max_seq_len=SEQ, num_output_channels=C,
                dtype=jnp.bfloat16,
            ),
            latent_shape=latent_shape,
            dtype=jnp.bfloat16,
            attn_impl=attn_impl,
        ),
        masking=TextMasking(vocab_size=VOCAB, unk_token_id=1, mask_token_id=2,
                            num_special_tokens=3),
    )


def batch():
    rng = np.random.default_rng(0)
    return {
        "token_ids": jnp.asarray(rng.integers(3, VOCAB, (BATCH, SEQ)).astype(np.int32)),
        "pad_mask": jnp.zeros((BATCH, SEQ), dtype=bool),
    }


def time_step(step, state, b) -> float:
    for _ in range(3):
        state, metrics = step(state, b)
    float(metrics["loss"])

    def timed(n):
        nonlocal state
        t0 = time.perf_counter()
        for _ in range(n):
            state, metrics = step(state, b)
        float(metrics["loss"])
        return time.perf_counter() - t0

    t_one = timed(1)
    return (timed(STEPS + 1) - t_one) / STEPS


def standard(model, gather=True):
    b = batch()
    variables = model.init(
        {"params": jax.random.key(0), "masking": jax.random.key(1)},
        b["token_ids"], b["pad_mask"],
    )
    tx, schedule = make_optimizer(OptimizerConfig(learning_rate=1e-3))
    state = TrainState.create(variables["params"], tx, jax.random.key(2))
    cap = mlm_gather_capacity(SEQ) if gather else None
    train_step, _, _ = make_mlm_steps(model, schedule, loss_gather_capacity=cap)
    return jax.jit(train_step, donate_argnums=(0,)), state, b


def no_decode_variant():
    """Loss = mean(latent²) — everything except decoder+CE."""
    model = build()
    b = batch()
    variables = model.init(
        {"params": jax.random.key(0), "masking": jax.random.key(1)},
        b["token_ids"], b["pad_mask"],
    )
    tx, _ = make_optimizer(OptimizerConfig(learning_rate=1e-3))
    state = TrainState.create(variables["params"], tx, jax.random.key(2))

    def loss_fn(params, bb, rngs):
        latent = model.encoder.apply(
            {"params": params["encoder"]}, bb["token_ids"], bb["pad_mask"],
            rngs=rngs, deterministic=False,
        )
        return jnp.mean(jnp.square(latent.astype(jnp.float32)))

    def train_step(state, bb):
        rngs = state.step_rngs("masking", "dropout")
        loss, grads = jax.value_and_grad(loss_fn)(state.params, bb, rngs)
        return state.apply_gradients(grads), {"loss": loss}

    return jax.jit(train_step, donate_argnums=(0,)), state, b


def fwd_only_variant():
    model = build()
    b = batch()
    variables = model.init(
        {"params": jax.random.key(0), "masking": jax.random.key(1)},
        b["token_ids"], b["pad_mask"],
    )
    tx, _ = make_optimizer(OptimizerConfig(learning_rate=1e-3))
    state = TrainState.create(variables["params"], tx, jax.random.key(2))
    cap = mlm_gather_capacity(SEQ)

    def train_step(state, bb):
        rngs = state.step_rngs("masking", "dropout")
        logits, labels = model.apply(
            {"params": state.params}, bb["token_ids"], bb["pad_mask"],
            rngs=rngs, deterministic=False, loss_gather_capacity=cap,
        )
        from perceiver_io_tpu.training.losses import cross_entropy_with_ignore
        loss = cross_entropy_with_ignore(logits, labels)
        # thread params through the carry so nothing is dead code
        return state.replace(step=state.step + 1), {"loss": loss}

    return jax.jit(train_step, donate_argnums=(0,)), state, b


def main():
    print(f"device: {probe_backend().device_kind}, batch {BATCH}, {STEPS} steps", file=sys.stderr)
    rows = [
        ("full (bench default)", standard(build())),
        ("full-decode (no gather)", standard(build(), gather=False)),
        ("no-decode (encoder only)", no_decode_variant()),
        ("no-self-attn (blocks=1)", standard(build(blocks=1))),
        ("one-layer (no recurrence)", standard(build(num_layers=1))),
        ("fwd-only (no bwd/opt)", fwd_only_variant()),
    ]
    for name, (step, state, b) in rows:
        ms = time_step(step, state, b) * 1e3
        toks = BATCH * SEQ / (ms / 1e3)
        print(f"{name:28s} {ms:8.2f} ms/step   {toks/1e6:6.2f}M tokens/s", file=sys.stderr)


if __name__ == "__main__":
    main()
