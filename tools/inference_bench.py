"""Inference/serving-layer hardware bench (VERDICT r4 item 6).

The serving layer (``inference/predictor.py`` bucketed ``Predictor``,
``inference/mlm.py`` ``fill_masks`` gathered decode, ``inference/export.py``
StableHLO export) is a beyond-the-reference capability (the reference has no
serve/export path — SURVEY.md §3.4), so the bar is internal consistency:
every capability claim carries hardware numbers. This tool measures, on the
real chip:

1. ``fill_masks`` end-to-end latency at batch 1 / 8 / 64 — the HOST medians
   (what a caller of this process sees: tokenize, dispatch, the tunnel
   round-trip, top-k decode) AND the device-trace per-call compute time
   (lower-quartile per-step device window — the tunnel-insensitive
   statistic, CLAUDE.md measurement discipline).
2. Bucket-padding overhead on the gathered-decode forward (the realistic
   serving path — small outputs): a 5-text request padded to the 8-bucket vs
   a native 8-text request (same compiled program) vs a dedicated
   exact-shape jit at 5 (what bucketing trades away to keep steady-state
   serving recompile-free).
3. Exported-StableHLO vs live-jit dispatch on the same forward: steady-state
   per-call latency and device time, plus each path's time-to-first-result
   (the artifact's ahead-of-time selling point).

Sync discipline: device completion is forced by fetching a SCALAR slice of
every output leaf (``block_until_ready`` lies on the tunneled backend and
unconsumed dispatches get DCE'd — PERF.md). ``fill_masks``/``Predictor``
already fetch their numpy results, which is the same honest sync.

Prints a human table and ONE final JSON summary line on stdout (this is a
tools/ bench — bench.py's one-line stdout contract is untouched).

``--engine`` (VERDICT r5 weak #5/#6 closed): the SERVING-ENGINE bench — an
interleaved same-process A/B of the continuous micro-batcher
(``inference/engine.py``) against naive per-request ``Predictor`` dispatch on
a batch-1 request stream, plus request-latency percentiles per batch bucket
and (on TPU) per-micro-batch device-trace percentiles. Emits exactly ONE
JSON line on stdout (human progress goes to stderr) so the driver can track
an inference trajectory alongside ``bench.py``. ``--cpu`` pins the run to
the CPU backend via ``ensure_cpu_only()`` BEFORE jax initializes — tier-1
exercises the full path offline with ``--preset tiny``.

Usage::

    timeout 1800 python tools/inference_bench.py [--trace-dir DIR]
                                                 [--dtype float32|bfloat16]
    timeout 1800 python tools/inference_bench.py --engine [--cpu]
        [--preset auto|tiny|flagship] [--requests N] [--rounds R]
"""

from __future__ import annotations

import argparse
import json
import os
import statistics
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from perceiver_io_tpu.utils.jsonline import emit_json_line
from perceiver_io_tpu.utils.platform import probe_backend

# NOTE: jax is imported inside main() AFTER --cpu is handled —
# utils.platform.ensure_cpu_only must run before any backend initializes.
import numpy as np


def _consume(out) -> None:
    """Honest completion: a scalar slice of each output leaf is computed
    on-device (dependent on the full result) and fetched to the host."""
    import jax

    for leaf in jax.tree_util.tree_leaves(out):
        idx = (0,) * getattr(leaf, "ndim", 0)
        np.asarray(leaf[idx] if idx else leaf)


def _median_latency(fn, reps: int = 20, warmup: int = 3) -> float:
    """Median host wall-clock seconds per call. Serving latency: the tunnel
    round-trip is part of what a caller experiences — no subtraction; the
    device trace carries the compute truth alongside."""
    for _ in range(warmup):
        fn()
    times = []
    for _ in range(reps):
        t0 = time.perf_counter()
        fn()
        times.append(time.perf_counter() - t0)
    return statistics.median(times)


def _device_per_call(fn, trace_dir: str, calls: int = 12):
    """Lower-quartile device seconds per call, each call wrapped in a
    StepTraceAnnotation so the xplane Steps line carries per-call windows.
    Returns None off-TPU or when the trace has no device plane — the host
    medians still stand on their own."""
    import jax

    from perceiver_io_tpu.utils import xplane

    fn()  # compiled before tracing
    try:
        with jax.profiler.trace(trace_dir):
            for i in range(calls):
                with jax.profiler.StepTraceAnnotation("serve", step_num=i):
                    fn()
        sec, _ = xplane.device_step_seconds(trace_dir, skip_first=2)
        return sec
    except Exception as e:
        print(f"  (device trace unavailable: {type(e).__name__}: "
              f"{str(e)[:80]})", file=sys.stderr)
        return None


def _ms(sec) -> str:
    return f"{sec * 1e3:.3f}" if sec is not None else "—"


def _build_predictor(dtype_name: str):
    """Flagship-shaped MLM + a real first-party tokenizer over a synthetic
    Zipf corpus (zero-egress environment: no downloads)."""
    import jax
    import jax.numpy as jnp

    from perceiver_io_tpu.data.tokenizer import (
        create_tokenizer,
        train_tokenizer,
    )
    from perceiver_io_tpu.inference.mlm import MLMPredictor
    from perceiver_io_tpu.models.presets import flagship_mlm

    rng = np.random.default_rng(0)
    # enough word TYPES that the trainer actually reaches the full 10003
    # vocab (the head cost scales with vocab — keep it representative)
    words = [f"w{i}" for i in range(16000)]
    probs = 1.0 / np.arange(1, len(words) + 1)
    probs /= probs.sum()
    corpus = [
        " ".join(rng.choice(words, size=150, p=probs)) for _ in range(1200)
    ]
    tokenizer = create_tokenizer()
    train_tokenizer(tokenizer, corpus, vocab_size=10003)
    vocab = tokenizer.get_vocab_size()

    max_seq_len = 512
    dtype = {"float32": jnp.float32, "bfloat16": jnp.bfloat16}[dtype_name]
    model = flagship_mlm(
        vocab_size=vocab, max_seq_len=max_seq_len, dtype=dtype,
        attn_impl="auto",
    )
    ids = np.zeros((1, max_seq_len), np.int32)
    variables = model.init(
        {"params": jax.random.key(0), "masking": jax.random.key(1)},
        ids, ids == 0,
    )
    predictor = MLMPredictor(
        model, variables["params"], tokenizer, max_seq_len, max_batch=64
    )
    texts = [
        f"the {tokenizer.id_to_token(10 + i)} movie was [MASK] and the plot "
        "felt [MASK] overall" for i in range(64)
    ]
    return predictor, texts, model, variables["params"], vocab, max_seq_len


def _build_engine_model(tiny: bool, dtype_name: str):
    """Model + tokenizer for the engine A/B: flagship-shaped on TPU, a
    scaled-down twin for the CPU (tier-1) run — same code path, minutes not
    hours."""
    import jax
    import jax.numpy as jnp

    from perceiver_io_tpu.data.tokenizer import create_tokenizer, train_tokenizer
    from perceiver_io_tpu.models.presets import flagship_mlm, tiny_mlm

    rng = np.random.default_rng(0)
    n_words, vocab_target, doc_words, docs = (
        (800, 503, 40, 200) if tiny else (16000, 10003, 150, 1200)
    )
    words = [f"w{i}" for i in range(n_words)]
    probs = 1.0 / np.arange(1, len(words) + 1)
    probs /= probs.sum()
    corpus = [
        " ".join(rng.choice(words, size=doc_words, p=probs))
        for _ in range(docs)
    ]
    tokenizer = create_tokenizer()
    train_tokenizer(tokenizer, corpus, vocab_size=vocab_target)
    dtype = {"float32": jnp.float32, "bfloat16": jnp.bfloat16}[dtype_name]
    build = tiny_mlm if tiny else flagship_mlm
    max_seq_len = 64 if tiny else 512
    model = build(
        vocab_size=tokenizer.get_vocab_size(), max_seq_len=max_seq_len,
        dtype=dtype, attn_impl="auto",
    )
    ids = np.zeros((1, max_seq_len), np.int32)
    variables = model.init(
        {"params": jax.random.key(0), "masking": jax.random.key(1)},
        ids, ids == 0,
    )
    return model, variables["params"], tokenizer, max_seq_len


def _percentiles(values) -> dict:
    v = sorted(values)
    pick = lambda q: v[min(len(v) - 1, int(q * len(v)))]
    return {"p50_ms": round(pick(0.50) * 1e3, 3),
            "p95_ms": round(pick(0.95) * 1e3, 3)}


def _engine_mode(args) -> None:
    """Interleaved engine-vs-naive A/B on a batch-1 request stream.

    Both arms run the identical gathered serving forward; the engine's only
    edge is what it claims — coalescing the stream into bucketed
    micro-batches with pipelined dispatch. Same process, alternating rounds
    (the tunnel's ±2x session swing cancels; PERF.md discipline)."""
    import jax

    from perceiver_io_tpu.inference import Predictor, ServingEngine
    from perceiver_io_tpu.inference.mlm import encode_masked_texts

    log = lambda *a: print(*a, file=sys.stderr)
    backend = probe_backend().backend
    tiny = args.preset == "tiny" or (args.preset == "auto" and backend != "tpu")
    log(f"backend: {backend}; preset {'tiny' if tiny else 'flagship'}; "
        f"dtype {args.dtype}; {args.requests} requests x {args.rounds} rounds")
    model, params, tokenizer, max_seq_len = _build_engine_model(
        tiny, args.dtype
    )

    # batch-1 request stream: every text carries two [MASK] slots (the
    # fill-mask serving shape), identical signature so the A/B isolates
    # batching — width bucketing has its own tests/bench
    texts = [
        f"the {tokenizer.id_to_token(10 + (i % 40))} movie was [MASK] and "
        f"felt [MASK] overall" for i in range(args.requests)
    ]
    ids, pad = encode_masked_texts(tokenizer, texts, max_seq_len)
    positions = np.zeros((len(texts), 2), np.int32)
    mask_id = tokenizer.token_to_id("[MASK]")
    for i in range(len(texts)):
        positions[i] = np.nonzero(ids[i] == mask_id)[0][:2]
    requests = [
        (ids[i: i + 1], pad[i: i + 1], positions[i: i + 1])
        for i in range(len(texts))
    ]

    def gathered_apply(p, token_ids, pad_mask, pos):
        logits, _ = model.apply(
            {"params": p}, token_ids, pad_mask, masking=False,
            deterministic=True, positions=pos,
        )
        return logits

    naive = Predictor(gathered_apply, params, max_batch=args.max_batch)
    engine = ServingEngine(
        gathered_apply, params, max_batch=args.max_batch,
        max_delay_ms=args.max_delay_ms, name="engine_bench",
        compute_dtype="bfloat16" if args.dtype == "bfloat16" else None,
    )
    # both arms compile everything they will use before any timing
    engine.warmup(*requests[0])
    naive(*requests[0])
    log(f"warmed {engine.num_programs} engine bucket programs")

    def naive_round() -> float:
        t0 = time.perf_counter()
        for r in requests:
            naive(*r)
        return time.perf_counter() - t0

    def engine_round() -> float:
        t0 = time.perf_counter()
        futures = [engine.submit(*r) for r in requests]
        for f in futures:
            f.result()
        return time.perf_counter() - t0

    naive_round()  # one unmeasured round each: steady-state caches
    engine_round()
    naive_s, engine_s = [], []
    for r in range(args.rounds):  # interleaved: A, B, A, B ...
        naive_s.append(naive_round())
        engine_s.append(engine_round())
        log(f"round {r}: naive {naive_s[-1]:.3f}s engine {engine_s[-1]:.3f}s")
    n_med, e_med = statistics.median(naive_s), statistics.median(engine_s)

    n = args.requests
    stats = engine.stats()  # locked deep-copied snapshot
    results = {
        "mode": "engine", "backend": backend, "dtype": args.dtype,
        "preset": "tiny" if tiny else "flagship",
        "requests": n, "rounds": args.rounds,
        "max_batch": args.max_batch, "seq_len": max_seq_len,
        "naive_requests_per_s": round(n / n_med, 2),
        "engine_requests_per_s": round(n / e_med, 2),
        "engine_tokens_per_s": round(n * max_seq_len / e_med, 1),
        "speedup": round(n_med / e_med, 3),
        "batches": stats["batches"],
        "mean_rows_per_batch": round(
            stats["rows"] / max(stats["batches"], 1), 2),
    }
    for bucket, lats in sorted(stats["latency_s_by_bucket"].items()):
        for k, v in _percentiles(lats).items():
            results[f"bucket{bucket}_{k}"] = v

    # device-trace per-micro-batch percentiles (TPU): the tunnel-insensitive
    # latency statistic — each engine dispatch is a StepTraceAnnotation step
    if backend == "tpu":
        try:
            from perceiver_io_tpu.utils import xplane

            trace_dir = args.trace_dir or tempfile.mkdtemp(
                prefix="engine_bench_")
            with jax.profiler.trace(trace_dir):
                engine_round()
            windows = xplane.step_windows(xplane.load_tpu_plane(trace_dir))
            durations = [(b - a) / 1e12 for a, b in windows]
            if durations:
                for k, v in _percentiles(durations).items():
                    results[f"device_batch_{k}"] = v
        except Exception as e:
            log(f"(device trace unavailable: {type(e).__name__}: "
                f"{str(e)[:80]})")

    engine.close()
    emit_json_line(results)


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument("--trace-dir", default=None,
                        help="keep traces here instead of a temp dir")
    parser.add_argument("--dtype", default="float32",
                        choices=["float32", "bfloat16"],
                        help="serving dtype (float32 = the from_checkpoint "
                             "golden-parity default)")
    parser.add_argument("--engine", action="store_true",
                        help="serving-engine A/B mode: ONE JSON line on "
                             "stdout, progress on stderr")
    parser.add_argument("--cpu", action="store_true",
                        help="pin to the CPU backend (ensure_cpu_only before "
                             "jax initializes) — the offline/tier-1 mode")
    parser.add_argument("--preset", choices=["auto", "tiny", "flagship"],
                        default="auto",
                        help="engine-mode model size: auto = flagship on "
                             "TPU, tiny elsewhere")
    parser.add_argument("--requests", type=int, default=64,
                        help="engine mode: batch-1 requests per round")
    parser.add_argument("--rounds", type=int, default=4,
                        help="engine mode: interleaved A/B rounds")
    parser.add_argument("--max_batch", type=int, default=32,
                        help="engine mode: micro-batch cap")
    parser.add_argument("--max_delay_ms", type=float, default=0.0,
                        help="engine mode: batch-formation hold")
    args = parser.parse_args()

    if args.cpu:
        from perceiver_io_tpu.utils.platform import ensure_cpu_only

        ensure_cpu_only()
    from perceiver_io_tpu.aot import maybe_enable_cache_from_env

    maybe_enable_cache_from_env()  # PIT_COMPILE_CACHE opt-in (stderr only)
    import jax

    if args.engine:
        _engine_mode(args)
        return

    backend = probe_backend().backend
    print(f"backend: {backend}; dtype {args.dtype}", file=sys.stderr)
    predictor, texts, model, params, vocab, max_seq_len = _build_predictor(
        args.dtype
    )
    results: dict = {"backend": backend, "dtype": args.dtype, "vocab": vocab}
    trace_root = args.trace_dir or tempfile.mkdtemp(prefix="inference_bench_")

    # 1) fill_masks latency/throughput ------------------------------------
    print("\nfill_masks (2 [MASK] per text, k=5):", file=sys.stderr)
    print(f"{'batch':>6} {'host ms/call':>13} {'device ms/call':>15} "
          f"{'texts/s (host)':>15}", file=sys.stderr)
    for n in (1, 8, 64):
        batch = texts[:n]
        host = _median_latency(lambda: predictor.fill_masks(batch, k=5))
        dev = _device_per_call(
            lambda: predictor.fill_masks(batch, k=5),
            os.path.join(trace_root, f"fill{n}"),
        )
        print(f"{n:>6} {host * 1e3:>13.2f} {_ms(dev):>15} "
              f"{n / host:>15.1f}", file=sys.stderr)
        results[f"fill_masks_b{n}_host_ms"] = round(host * 1e3, 3)
        if dev is not None:
            results[f"fill_masks_b{n}_device_ms"] = round(dev * 1e3, 4)

    # 2) bucket-padding overhead (gathered forward: small outputs) --------
    from perceiver_io_tpu.inference.mlm import encode_masked_texts

    ids5, pad5 = encode_masked_texts(
        predictor.tokenizer, texts[:5], max_seq_len)
    ids8, pad8 = encode_masked_texts(
        predictor.tokenizer, texts[:8], max_seq_len)
    pos5 = np.tile(np.arange(8, dtype=np.int32), (5, 1))
    pos8 = np.tile(np.arange(8, dtype=np.int32), (8, 1))

    gathered = predictor._gathered  # the Predictor fill_masks dispatches

    def exact_apply(p, token_ids, pad_mask, positions):
        return model.apply(
            {"params": p}, token_ids, pad_mask, masking=False,
            deterministic=True, positions=positions,
        )

    exact5 = jax.jit(exact_apply)

    host_b5 = _median_latency(lambda: gathered(ids5, pad5, pos5))
    host_b8 = _median_latency(lambda: gathered(ids8, pad8, pos8))
    host_exact5 = _median_latency(
        lambda: _consume(exact5(params, ids5, pad5, pos5)))
    dev_b5 = _device_per_call(
        lambda: gathered(ids5, pad5, pos5),
        os.path.join(trace_root, "bucket5"))
    dev_exact5 = _device_per_call(
        lambda: _consume(exact5(params, ids5, pad5, pos5)),
        os.path.join(trace_root, "exact5"))
    print("\nbucket padding (5 texts -> 8-bucket, gathered decode):", file=sys.stderr)
    print(f"  bucketed@5   host {host_b5 * 1e3:7.2f} ms   device "
          f"{_ms(dev_b5)} ms", file=sys.stderr)
    print(f"  native@8     host {host_b8 * 1e3:7.2f} ms", file=sys.stderr)
    print(f"  exact-jit@5  host {host_exact5 * 1e3:7.2f} ms   device "
          f"{_ms(dev_exact5)} ms", file=sys.stderr)
    results.update(
        bucket5_host_ms=round(host_b5 * 1e3, 3),
        native8_host_ms=round(host_b8 * 1e3, 3),
        exact5_host_ms=round(host_exact5 * 1e3, 3),
    )
    if dev_b5 is not None:
        results["bucket5_device_ms"] = round(dev_b5 * 1e3, 4)
    if dev_exact5 is not None:
        results["exact5_device_ms"] = round(dev_exact5 * 1e3, 4)

    # 3) exported StableHLO vs live jit (gathered forward, b8) ------------
    from perceiver_io_tpu.inference.export import export_fn, load_exported

    art = os.path.join(trace_root, "mlm.stablehlo")
    # ONE definition of the gathered serving forward for export/live/exact —
    # positions must stay an ARGUMENT of the exported callable (it varies per
    # request; export_forward's *inputs splat would collide with the model's
    # positional `masking`), and params are baked via partial for the
    # self-contained-artifact semantics
    import functools

    gathered_fn = functools.partial(exact_apply, params)

    t0 = time.perf_counter()
    export_fn(gathered_fn, (ids8, pad8, pos8), path=art)
    export_s = time.perf_counter() - t0

    t0 = time.perf_counter()
    exported_call = load_exported(art)
    _consume(exported_call(ids8, pad8, pos8))
    exported_first_s = time.perf_counter() - t0

    live = jax.jit(gathered_fn)
    t0 = time.perf_counter()
    _consume(live(ids8, pad8, pos8))
    live_first_s = time.perf_counter() - t0

    host_exported = _median_latency(
        lambda: _consume(exported_call(ids8, pad8, pos8)))
    host_live = _median_latency(lambda: _consume(live(ids8, pad8, pos8)))
    dev_exported = _device_per_call(
        lambda: _consume(exported_call(ids8, pad8, pos8)),
        os.path.join(trace_root, "exported"))
    dev_live = _device_per_call(
        lambda: _consume(live(ids8, pad8, pos8)),
        os.path.join(trace_root, "livejit"))
    size_mb = os.path.getsize(art) / 1e6
    print(f"\nStableHLO export (b8 gathered forward, artifact "
          f"{size_mb:.1f} MB, export took {export_s:.1f} s):", file=sys.stderr)
    print(f"  exported  first-result {exported_first_s:6.1f} s   steady "
          f"host {host_exported * 1e3:7.2f} ms   device "
          f"{_ms(dev_exported)} ms", file=sys.stderr)
    print(f"  live jit  first-result {live_first_s:6.1f} s   steady "
          f"host {host_live * 1e3:7.2f} ms   device {_ms(dev_live)} ms", file=sys.stderr)
    results.update(
        export_artifact_mb=round(size_mb, 2),
        export_s=round(export_s, 2),
        exported_first_result_s=round(exported_first_s, 2),
        live_first_result_s=round(live_first_s, 2),
        exported_steady_host_ms=round(host_exported * 1e3, 3),
        live_steady_host_ms=round(host_live * 1e3, 3),
    )
    if dev_exported is not None:
        results["exported_device_ms"] = round(dev_exported * 1e3, 4)
    if dev_live is not None:
        results["live_device_ms"] = round(dev_live * 1e3, 4)

    print(file=sys.stderr)
    emit_json_line(results)


if __name__ == "__main__":
    main()
