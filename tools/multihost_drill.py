"""Measure multi-host recovery walls on the CPU sim. One JSON line (stdout).

Two drills, one record contract (progress on stderr, PIT-CONTRACT):

- **restart-the-world** (default, r19): launch a supervised
  ``--spawn_hosts 2`` MLM run, SIGKILL one rank after the first committed
  checkpoint, and time every phase the supervisor performs — detection,
  teardown, relaunch, back-to-training (first post-restart metrics row).
- **elastic** (``--elastic``, r23): spawn the 5-process elastic pool
  (``tests/elastic_worker.py``), kill one rank mid-epoch, and read the
  walls the survivors report — in-process resize (decision→resume),
  buddy-mirror restore bytes, hot-spare join — plus the zero-loss
  accounting: ``steps_lost`` (global steps not covered by any survivor)
  and the parity verdict (identical per-step losses and final state
  digests across the post-resize world).

``--paired`` runs BOTH arms in this one process (restart first) and emits
their same-process ``speedup`` — the A/B discipline PERF.md requires for
host-clock walls on the tunnel. ``--dry`` declares the record keys
without touching any backend.

The numbers feed PERF.md §Multi-host recovery / §Elastic training. They
are CPU-sim walls — the restart arm is dominated by the jit re-compile of
the restarted world — but the PHASE STRUCTURE is the product being
measured: how long a child death leaves the fleet idle before training
resumes, with no human in the loop.

Usage::

    python tools/multihost_drill.py [--steps 10] [--delay 0.4]
        [--workdir DIR] [--elastic] [--paired] [--dry]
"""

from __future__ import annotations

import argparse
import json
import os
import signal
import subprocess
import sys
import tempfile
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from perceiver_io_tpu.utils.jsonline import emit_json_line  # noqa: E402

# the one-line record's key set, declared for --dry (bench_compare reads
# these; keep in sync with FLOOR_CLASSES' r23 elastic entries there)
KEYS = (
    "metric", "dry", "mode", "ok", "rc", "steps", "delay_s",
    # restart arm (r19)
    "kill_to_restart_decision_s", "kill_to_relaunch_s",
    "kill_to_training_again_s", "total_wall_s", "resumed_from", "final_step",
    # elastic arm (r23)
    "pool", "die_rank", "die_at", "resize_wall_s", "grow_wall_s",
    "join_wall_s", "buddy_restore_bytes", "steps_lost", "parity",
    # --paired
    "restart_baseline_s", "speedup",
)


def _pid_of_rank(rank: int, marker: str = "train_mlm"):
    for pid in os.listdir("/proc"):
        if not pid.isdigit():
            continue
        try:
            with open(f"/proc/{pid}/cmdline", "rb") as f:
                argv = f.read().decode(errors="replace").split("\0")
        except OSError:
            continue
        if (marker in " ".join(argv) and "--process_id" in argv
                and argv[argv.index("--process_id") + 1] == str(rank)):
            return int(pid)
    return None


def _losses(logdir: str):
    """Per-step train_loss across every version dir, last write wins (a
    resumed run appends into the same metrics.jsonl)."""
    import glob

    rows = {}
    for path in sorted(glob.glob(
            os.path.join(logdir, "mlm", "version_*", "metrics.jsonl"))):
        with open(path) as f:
            for line in f:
                row = json.loads(line)
                if "train_loss" in row:
                    rows[row["step"]] = row["train_loss"]
    return rows


def wait_for(predicate, timeout_s, poll_s=0.05):
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        value = predicate()
        if value:
            return value
        time.sleep(poll_s)
    return None


def run_restart(args, workdir) -> dict:
    """The r19 arm: supervised world restart after a SIGKILL. Returns the
    record fragment (``ok`` + the kill_to_* walls)."""
    logdir = os.path.join(workdir, "logs")
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env["JAX_PLATFORMS"] = "cpu"
    env["PIT_FAULTS"] = (
        f"trainer.collective:slow@every:1@delay:{args.delay}")
    cmd = [
        sys.executable, os.path.join(REPO, "train", "train_mlm.py"),
        "--spawn_hosts", "2", "--spawn_attempts", "3",
        "--synthetic", "--synthetic_size", "64", "--batch_size", "16",
        "--max_seq_len", "32", "--vocab_size", "90", "--num_latents", "8",
        "--num_latent_channels", "16", "--num_encoder_layers", "2",
        "--num_self_attention_layers_per_block", "1",
        "--num_cross_attention_heads", "2",
        "--num_self_attention_heads", "2", "--dtype", "float32",
        "--log_every_n_steps", "1", "--max_steps", str(args.steps),
        "--eval_every_n_steps", "2", "--max_to_keep", "3",
        "--step_timeout_s", str(args.step_timeout_s),
        "--logdir", logdir, "--root", os.path.join(workdir, "cache"),
    ]
    from perceiver_io_tpu.cli.common import _newest_resumable_run

    err_path = os.path.join(workdir, "launcher.err")
    t0 = time.monotonic()
    proc = subprocess.Popen(
        cmd, env=env, stdout=subprocess.DEVNULL, stderr=open(err_path, "w"))

    record = {"ok": False, "steps": args.steps, "delay_s": args.delay}
    try:
        resumable = wait_for(
            lambda: _newest_resumable_run(logdir, "mlm"), timeout_s=240)
        if not resumable:
            record["error"] = "no committed checkpoint before kill window"
            proc.kill()
            return record
        victim = wait_for(lambda: _pid_of_rank(1), timeout_s=30)
        if victim is None:
            record["error"] = "rank-1 process not found to kill"
            proc.kill()
            return record
        pre_kill_steps = len(_losses(logdir))
        t_kill = time.monotonic()
        os.kill(victim, signal.SIGKILL)
        print(f"[drill] killed rank 1 (pid {victim}) at "
              f"t+{t_kill - t0:.1f}s", file=sys.stderr)

        def stderr_has(marker):
            with open(err_path) as f:
                return marker in f.read()

        restarted = wait_for(
            lambda: stderr_has("restarting all 2 hosts"), timeout_s=120)
        t_restart_decision = time.monotonic()
        relaunched = wait_for(
            lambda: open(err_path).read().count("launched 2 processes") >= 2,
            timeout_s=120)
        t_relaunch = time.monotonic()
        training_again = wait_for(
            lambda: len(_losses(logdir)) > pre_kill_steps, timeout_s=240)
        t_training = time.monotonic()
        proc.wait(timeout=480)
        t_done = time.monotonic()
        losses = _losses(logdir)
        record.update(
            ok=(proc.returncode == 0 and bool(restarted) and bool(relaunched)
                and bool(training_again)
                and len(losses) >= args.steps),
            rc=proc.returncode,
            kill_to_restart_decision_s=round(t_restart_decision - t_kill, 3),
            kill_to_relaunch_s=round(t_relaunch - t_kill, 3),
            kill_to_training_again_s=round(t_training - t_kill, 3),
            total_wall_s=round(t_done - t0, 3),
            resumed_from=str(resumable),
            final_step=max(losses) if losses else 0,
        )
    finally:
        if proc.poll() is None:
            proc.kill()
    return record


def run_elastic(args, workdir) -> dict:
    """The r23 arm: 4→3→4 in-process resize. Spawns the 5-process elastic
    pool and reduces the per-rank JSONs to the one-record walls."""
    from perceiver_io_tpu.cli.common import _pick_coordinator_port

    port = _pick_coordinator_port()
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env["JAX_PLATFORMS"] = "cpu"
    worker = os.path.join(REPO, "tests", "elastic_worker.py")
    procs = []
    for rank in range(args.pool):
        log = open(os.path.join(workdir, f"elastic_r{rank}.log"), "w")
        procs.append(subprocess.Popen(
            [sys.executable, worker, "--rank", str(rank),
             "--pool", str(args.pool), "--port", str(port),
             "--workdir", workdir, "--steps", str(args.steps),
             "--die_rank", str(args.die_rank), "--die_at", str(args.die_at)],
            env=env, stdout=log, stderr=log))
    print(f"[drill] elastic pool of {args.pool} up (coordinator "
          f"localhost:{port}); rank {args.die_rank} dies at step "
          f"{args.die_at}", file=sys.stderr)
    record = {"ok": False, "pool": args.pool, "steps": args.steps,
              "die_rank": args.die_rank, "die_at": args.die_at}
    deadline = time.monotonic() + args.elastic_timeout_s
    rcs = []
    for p in procs:
        try:
            rcs.append(p.wait(timeout=max(1.0, deadline - time.monotonic())))
        except subprocess.TimeoutExpired:
            p.kill()
            rcs.append(None)
    # the deliberately-killed rank exits 1; every other rank must exit 0
    bad = [rc if rc is not None else -1
           for i, rc in enumerate(rcs) if i != args.die_rank and rc != 0]
    record["rc"] = bad[0] if bad else 0
    reports = {}
    for rank in range(args.pool):
        path = os.path.join(workdir, f"rank{rank}_elastic.json")
        if os.path.exists(path):
            with open(path) as f:
                reports[rank] = json.load(f)
    survivors = [r for r, rep in reports.items()
                 if r != args.die_rank and "final_step" in rep]
    if not survivors:
        record["error"] = "no surviving rank reported"
        return record
    # zero-loss accounting: every global step covered, identical losses
    covered = {}
    parity = "ok"
    for r in survivors:
        for step, loss in reports[r]["losses"].items():
            if step in covered and abs(covered[step] - loss) > 1e-6 * (
                    abs(loss) + 1e-12):
                parity = "divergent_losses"
            covered[step] = loss
    steps_lost = args.steps - len(covered)
    digests = {reports[r].get("final_digest") for r in survivors}
    if len(digests) != 1 or None in digests:
        parity = "divergent_digest"
    resize = [reports[r]["walls"].get("decision_to_resume_s")
              for r in survivors if "decision_to_resume_s"
              in reports[r]["walls"]]
    grow = [reports[r]["walls"].get("grow_s") for r in survivors
            if "grow_s" in reports[r]["walls"]]
    join = [reports[r]["walls"].get("join_s") for r in reports
            if "join_s" in reports[r]["walls"]]
    restored_bytes = [e["bytes"] for r in survivors
                      for e in reports[r]["events"]
                      if e.get("kind") == "mirror_restored" and "bytes" in e]
    record.update(
        ok=(steps_lost == 0 and parity == "ok" and bool(resize)
            and bool(restored_bytes) and not bad),
        resize_wall_s=round(max(resize), 3) if resize else None,
        grow_wall_s=round(max(grow), 3) if grow else None,
        join_wall_s=round(max(join), 3) if join else None,
        buddy_restore_bytes=max(restored_bytes) if restored_bytes else 0,
        steps_lost=steps_lost,
        parity=parity,
    )
    return record


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--steps", type=int, default=10)
    parser.add_argument("--delay", type=float, default=0.4,
                        help="injected per-step throttle for the restart arm "
                             "(widens the kill window; the recovery phases "
                             "measured are step-rate independent)")
    parser.add_argument("--workdir", default=None)
    parser.add_argument("--step_timeout_s", type=float, default=8.0)
    parser.add_argument("--elastic", action="store_true",
                        help="run the r23 in-process-resize drill instead "
                             "of the r19 restart-the-world drill")
    parser.add_argument("--paired", action="store_true",
                        help="run BOTH arms in this process (restart, then "
                             "elastic) and emit their same-process speedup")
    parser.add_argument("--pool", type=int, default=5)
    parser.add_argument("--die_rank", type=int, default=3)
    parser.add_argument("--die_at", type=int, default=4)
    parser.add_argument("--elastic_timeout_s", type=float, default=240.0)
    parser.add_argument("--dry", action="store_true",
                        help="declare the record keys without running "
                             "anything (stdout-contract check)")
    args = parser.parse_args(argv)
    if args.paired:
        args.elastic = True
        args.steps = max(args.steps, 12)

    if args.dry:
        record = {k: None for k in KEYS}
        record.update(metric="multihost_drill", dry=True,
                      mode="elastic" if args.elastic else "restart")
        emit_json_line(record)
        return 0

    workdir = args.workdir or tempfile.mkdtemp(prefix="multihost_drill_")
    record = {"metric": "multihost_drill", "dry": False,
              "mode": "elastic" if args.elastic else "restart"}
    baseline = None
    if args.paired or not args.elastic:
        restart_dir = os.path.join(workdir, "restart_arm")
        os.makedirs(restart_dir, exist_ok=True)
        rec = run_restart(args, restart_dir)
        baseline = rec.get("kill_to_training_again_s")
        record.update(rec)
    if args.elastic:
        elastic_dir = os.path.join(workdir, "elastic_arm")
        os.makedirs(elastic_dir, exist_ok=True)
        rec = run_elastic(args, elastic_dir)
        if args.paired:
            rec["restart_baseline_s"] = baseline
            if baseline and rec.get("resize_wall_s"):
                rec["speedup"] = round(baseline / rec["resize_wall_s"], 3)
            rec["ok"] = bool(rec.get("ok")) and bool(record.get("ok"))
        record.update(rec)
    emit_json_line(record)
    return 0 if record.get("ok") else 1


if __name__ == "__main__":
    raise SystemExit(main())
