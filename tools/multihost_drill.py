"""Measure the restart-the-world recovery wall on the 2-process CPU sim.

The r19 chaos drill, instrumented: launch a supervised ``--spawn_hosts 2``
MLM run, SIGKILL one rank after the first committed checkpoint, and time
every phase of the recovery the supervisor performs — detection (child
death observed), teardown (surviving world reaped), relaunch, and
back-to-training (first post-restart metrics row). One JSON line on
stdout; progress on stderr (PIT-CONTRACT).

The numbers feed PERF.md §Multi-host recovery. They are CPU-sim walls —
dominated by the jit re-compile of the restarted world (a real pod with a
persistent compilation cache pays the restore + data fast-forward only) —
but the PHASE STRUCTURE is the product being measured: how long a child
death leaves the fleet idle before training resumes, with no human in the
loop.

Usage::

    python tools/multihost_drill.py [--steps 10] [--delay 0.4]
        [--workdir DIR]
"""

from __future__ import annotations

import argparse
import json
import os
import signal
import subprocess
import sys
import tempfile
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from perceiver_io_tpu.utils.jsonline import emit_json_line  # noqa: E402


def _pid_of_rank(rank: int, marker: str = "train_mlm"):
    for pid in os.listdir("/proc"):
        if not pid.isdigit():
            continue
        try:
            with open(f"/proc/{pid}/cmdline", "rb") as f:
                argv = f.read().decode(errors="replace").split("\0")
        except OSError:
            continue
        if (marker in " ".join(argv) and "--process_id" in argv
                and argv[argv.index("--process_id") + 1] == str(rank)):
            return int(pid)
    return None


def _losses(logdir: str):
    """Per-step train_loss across every version dir, last write wins (a
    resumed run appends into the same metrics.jsonl)."""
    import glob

    rows = {}
    for path in sorted(glob.glob(
            os.path.join(logdir, "mlm", "version_*", "metrics.jsonl"))):
        with open(path) as f:
            for line in f:
                row = json.loads(line)
                if "train_loss" in row:
                    rows[row["step"]] = row["train_loss"]
    return rows


def wait_for(predicate, timeout_s, poll_s=0.05):
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        value = predicate()
        if value:
            return value
        time.sleep(poll_s)
    return None


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--steps", type=int, default=10)
    parser.add_argument("--delay", type=float, default=0.4,
                        help="injected per-step throttle (widens the kill "
                             "window; subtracted from nothing — the recovery "
                             "phases measured are step-rate independent)")
    parser.add_argument("--workdir", default=None)
    parser.add_argument("--step_timeout_s", type=float, default=8.0)
    args = parser.parse_args(argv)

    workdir = args.workdir or tempfile.mkdtemp(prefix="multihost_drill_")
    logdir = os.path.join(workdir, "logs")
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env["JAX_PLATFORMS"] = "cpu"
    env["PIT_FAULTS"] = (
        f"trainer.collective:slow@every:1@delay:{args.delay}")
    cmd = [
        sys.executable, os.path.join(REPO, "train", "train_mlm.py"),
        "--spawn_hosts", "2", "--spawn_attempts", "3",
        "--synthetic", "--synthetic_size", "64", "--batch_size", "16",
        "--max_seq_len", "32", "--vocab_size", "90", "--num_latents", "8",
        "--num_latent_channels", "16", "--num_encoder_layers", "2",
        "--num_self_attention_layers_per_block", "1",
        "--num_cross_attention_heads", "2",
        "--num_self_attention_heads", "2", "--dtype", "float32",
        "--log_every_n_steps", "1", "--max_steps", str(args.steps),
        "--eval_every_n_steps", "2", "--max_to_keep", "3",
        "--step_timeout_s", str(args.step_timeout_s),
        "--logdir", logdir, "--root", os.path.join(workdir, "cache"),
    ]
    from perceiver_io_tpu.cli.common import _newest_resumable_run

    err_path = os.path.join(workdir, "launcher.err")
    t0 = time.monotonic()
    proc = subprocess.Popen(
        cmd, env=env, stdout=subprocess.DEVNULL, stderr=open(err_path, "w"))

    record = {"ok": False, "steps": args.steps, "delay_s": args.delay}
    try:
        resumable = wait_for(
            lambda: _newest_resumable_run(logdir, "mlm"), timeout_s=240)
        if not resumable:
            record["error"] = "no committed checkpoint before kill window"
            emit_json_line(record)
            proc.kill()
            return 1
        victim = wait_for(lambda: _pid_of_rank(1), timeout_s=30)
        if victim is None:
            record["error"] = "rank-1 process not found to kill"
            emit_json_line(record)
            proc.kill()
            return 1
        pre_kill_steps = len(_losses(logdir))
        t_kill = time.monotonic()
        os.kill(victim, signal.SIGKILL)
        print(f"[drill] killed rank 1 (pid {victim}) at "
              f"t+{t_kill - t0:.1f}s", file=sys.stderr)

        def stderr_has(marker):
            with open(err_path) as f:
                return marker in f.read()

        restarted = wait_for(
            lambda: stderr_has("restarting all 2 hosts"), timeout_s=120)
        t_restart_decision = time.monotonic()
        relaunched = wait_for(
            lambda: open(err_path).read().count("launched 2 processes") >= 2,
            timeout_s=120)
        t_relaunch = time.monotonic()
        training_again = wait_for(
            lambda: len(_losses(logdir)) > pre_kill_steps, timeout_s=240)
        t_training = time.monotonic()
        proc.wait(timeout=480)
        t_done = time.monotonic()
        losses = _losses(logdir)
        record.update(
            ok=(proc.returncode == 0 and bool(restarted) and bool(relaunched)
                and bool(training_again)
                and len(losses) >= args.steps),
            rc=proc.returncode,
            kill_to_restart_decision_s=round(t_restart_decision - t_kill, 3),
            kill_to_relaunch_s=round(t_relaunch - t_kill, 3),
            kill_to_training_again_s=round(t_training - t_kill, 3),
            total_wall_s=round(t_done - t0, 3),
            resumed_from=str(resumable),
            final_step=max(losses) if losses else 0,
        )
    finally:
        if proc.poll() is None:
            proc.kill()
    emit_json_line(record)
    return 0 if record.get("ok") else 1


if __name__ == "__main__":
    raise SystemExit(main())
