"""Train→serve deployment-loop bench: swap cadence, per-swap latency blip,
and zero-loss across N gated swaps under open-loop traffic.

The deploy subsystem's claim is that online model refresh is FREE from the
traffic's point of view: a publication is admission-gated off the serving
path, the hot-swap installs between micro-batches (fleet mode: one replica
at a time), the compiled programs and AOT warm pools carry over, and no
accepted request is ever lost to a swap. This bench measures that claim:

- a publisher publishes ``--swaps`` checkpoints on a ``--publish_every_s``
  cadence (each a slightly-perturbed copy of the serving tree, so the
  admission gate's quality bound passes);
- the deployment loop (``perceiver_io_tpu.deploy.ModelDeployer``) gates and
  hot-swaps each one into a live engine (default) or a ``--replicas N``
  router fleet (in-process replicas, ``Router.rolling_update``);
- an open-loop Poisson arrival stream (``--rate_factor`` × a calibrated
  closed-loop capacity) runs throughout; every completion is stamped;
- the record attributes p99 latency to ±``--blip_window_s`` windows around
  each swap vs steady state (``deploy.swap_window_stats`` — the same
  methodology ``load_bench --publish_every_s`` rides), reports per-swap
  gate/swap wall seconds and the swap cadence actually sustained, and
  pins ``lost_accepted`` (accepted-but-failed requests) which MUST be 0.

Emits exactly ONE JSON line on stdout (progress on stderr). ``--cpu`` pins
the CPU backend before jax initializes (tier-1 offline mode, tiny preset);
``--dry`` emits the record schema without touching a backend. Real-TPU runs
ride the PERF.md §r10 pending queue.

Usage::

    timeout 1800 python tools/deploy_bench.py --cpu [--swaps 4]
        [--publish_every_s 1.0] [--rate_factor 0.4] [--replicas 3]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile
import threading
import time
from typing import List, Optional, Tuple

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from perceiver_io_tpu.utils.jsonline import emit_json_line
from perceiver_io_tpu.utils.platform import probe_backend

import numpy as np

RECORD_KEYS = (
    "metric", "dry", "backend", "preset", "mode", "replicas",
    "swaps_requested", "publishes", "swaps", "rejects", "rollbacks",
    "lost_accepted", "offered_rps", "achieved_rps", "completed", "failed",
    "shed", "swap_cadence_s", "gate_ms_mean", "swap_ms_mean", "per_swap",
    "p99_steady_ms", "p99_swap_ms", "blip_ratio", "blip_window_s",
)
PER_SWAP_KEYS = ("step", "action", "gate_ms", "swap_ms", "p99_ms", "n_window")


def _log(*a) -> None:
    print(*a, file=sys.stderr, flush=True)


def main() -> None:
    parser = argparse.ArgumentParser(
        description="deployment-loop bench: gated swaps under open-loop load")
    parser.add_argument("--cpu", action="store_true",
                        help="pin to the CPU backend (ensure_cpu_only before "
                             "jax initializes) — the offline/tier-1 mode")
    parser.add_argument("--dry", action="store_true",
                        help="emit the record schema (one JSON line) without "
                             "touching any backend")
    parser.add_argument("--preset", choices=["auto", "tiny", "flagship"],
                        default="auto")
    parser.add_argument("--swaps", type=int, default=4,
                        help="checkpoint publications to push through the "
                             "loop")
    parser.add_argument("--publish_every_s", type=float, default=1.0,
                        help="publication cadence (the loop's poll rides at "
                             "a quarter of it)")
    parser.add_argument("--rate_factor", type=float, default=0.4,
                        help="offered rate as a fraction of the calibrated "
                             "closed-loop capacity (below the knee: the blip "
                             "must not hide in saturation queueing)")
    parser.add_argument("--blip_window_s", type=float, default=0.5,
                        help="half-width of the per-swap attribution window")
    parser.add_argument("--replicas", type=int, default=0,
                        help="run the fleet mode: a router over N in-process "
                             "replicas, swaps rolling one replica at a time "
                             "(0 = single engine hot-swap)")
    parser.add_argument("--bake_s", type=float, default=0.2,
                        help="post-swap bake window per swap (per replica in "
                             "fleet mode)")
    parser.add_argument("--max_batch", type=int, default=8)
    parser.add_argument("--calibration_waves", type=int, default=2)
    parser.add_argument("--calibration_wave_size", type=int, default=16)
    parser.add_argument("--timeout_s", type=float, default=120.0,
                        help="bound on waiting for the loop to process all "
                             "publications")
    parser.add_argument("--seed", type=int, default=0)
    args = parser.parse_args()

    if args.dry:
        record = {k: None for k in RECORD_KEYS}
        record.update(metric="deploy_bench", dry=True,
                      record_keys=list(RECORD_KEYS),
                      per_swap_keys=list(PER_SWAP_KEYS), per_swap=[])
        emit_json_line(record)
        return

    if args.cpu:
        from perceiver_io_tpu.utils.platform import ensure_cpu_only

        ensure_cpu_only()
    from perceiver_io_tpu.aot import maybe_enable_cache_from_env

    maybe_enable_cache_from_env()  # PIT_COMPILE_CACHE opt-in (stderr only)
    import jax

    import perceiver_io_tpu.deploy as deploy
    import perceiver_io_tpu.obs as obs
    from perceiver_io_tpu.inference import ServingEngine
    from perceiver_io_tpu.models.presets import flagship_mlm, tiny_mlm

    backend = probe_backend().backend
    tiny = args.preset == "tiny" or (args.preset == "auto" and backend != "tpu")
    vocab = 503 if tiny else 10003
    max_seq_len = 64 if tiny else 512
    registry = obs.get_registry()
    mode = "fleet" if args.replicas > 0 else "engine"
    _log(f"backend: {backend}; preset {'tiny' if tiny else 'flagship'}; "
         f"mode {mode}"
         + (f" x{args.replicas}" if args.replicas else "")
         + f"; {args.swaps} swaps every {args.publish_every_s}s")

    build = tiny_mlm if tiny else flagship_mlm
    model = build(vocab_size=vocab, max_seq_len=max_seq_len)
    ids0 = np.zeros((1, max_seq_len), np.int32)
    params = model.init(
        {"params": jax.random.key(args.seed),
         "masking": jax.random.key(args.seed + 1)},
        ids0, ids0 == 0,
    )["params"]

    def gathered_apply(p, token_ids, pad_mask, pos):
        logits, _ = model.apply(
            {"params": p}, token_ids, pad_mask, masking=False,
            deterministic=True, positions=pos,
        )
        return logits

    rng = np.random.default_rng(args.seed)
    reqs = []
    for _ in range(32):
        ids = rng.integers(3, vocab, size=(1, max_seq_len),
                           dtype=np.int64).astype(np.int32)
        reqs.append((ids, np.zeros((1, max_seq_len), bool),
                     np.array([[1, 2]], np.int32)))

    # -- serving surface -----------------------------------------------------
    engines: List[ServingEngine] = []
    local_replicas = []
    router = None
    if args.replicas > 0:
        from perceiver_io_tpu.serving import LocalReplica, ReplicaApp, Router

        def pub_factory(spec):
            if spec.get("kind") != "publication":
                raise ValueError(f"bench replica got spec {spec!r}")
            return deploy.load_publication(spec["path"])[0]

        for i in range(args.replicas):
            eng = ServingEngine(gathered_apply, params,
                                max_batch=args.max_batch,
                                name=f"db_r{i}", registry=registry)
            eng.warmup(*reqs[0])
            engines.append(eng)
            app = ReplicaApp({"infer": eng}, params,
                             params_factory=pub_factory, name=f"r{i}",
                             registry=registry)
            local_replicas.append(LocalReplica(app))
        router = Router(local_replicas, name="deploy_bench",
                        registry=registry, scrape_interval_s=0.1)
        router.refresh()
        submit = lambda req: router.submit(*req)
        target = deploy.RouterSwapTarget(router, bake_s=args.bake_s,
                                         poll_s=0.02)
    else:
        eng = ServingEngine(gathered_apply, params, max_batch=args.max_batch,
                            name="deploy_bench", registry=registry)
        eng.warmup(*reqs[0])
        engines.append(eng)
        submit = lambda req: eng.submit(*req)
        target = deploy.EngineSwapTarget(eng, params, bake_s=args.bake_s,
                                         poll_s=0.02)
    _log(f"warmed {mode} serving surface")

    # -- deployment loop -----------------------------------------------------
    publish_dir = tempfile.mkdtemp(prefix="deploy_bench_pub_")
    gate = deploy.AdmissionGate(gathered_apply, reqs[0], params,
                                quality_tol=0.5, registry=registry,
                                name="deploy_bench")
    swap_times: List[float] = []

    def on_deployed(rec):
        if rec["action"] == "swapped":
            # the INTERVAL from install start to bake end: a fleet roll
            # spans seconds, and the early replicas' installs must not be
            # misattributed to steady state
            swap_times.append((rec["t_swap"], rec["t_done"]))
        _log(f"deploy: step {rec['step']} {rec['action']}"
             + (f" ({rec['reason']})" if rec.get("reason") else "")
             + f" gate {rec.get('gate_s', 0):.3f}s"
               f" swap {rec.get('swap_s', 0):.3f}s")

    deployer = deploy.ModelDeployer(
        publish_dir, gate, target, poll_s=max(args.publish_every_s / 4, 0.05),
        registry=registry, name="deploy_bench", on_deployed=on_deployed,
    ).start()

    # -- calibration (closed loop) -------------------------------------------
    lat0: List[float] = []
    cal_rates = []
    for _ in range(args.calibration_waves):
        t0 = time.monotonic()
        futs = [(submit(reqs[i % len(reqs)]), time.monotonic())
                for i in range(args.calibration_wave_size)]
        for f, ts in futs:
            f.result(timeout=300)
            lat0.append(time.monotonic() - ts)
        cal_rates.append(args.calibration_wave_size
                         / (time.monotonic() - t0))
    cal_rps = sorted(cal_rates)[len(cal_rates) // 2]
    rate = max(args.rate_factor * cal_rps, 1.0)
    _log(f"calibrated ~{cal_rps:.1f} req/s closed-loop; offering "
         f"{rate:.1f} req/s open-loop")

    # -- open-loop traffic + publications ------------------------------------
    completions: List[Tuple[float, float]] = []
    failed: List[str] = []
    shed = [0]
    stop = threading.Event()

    def traffic():
        from perceiver_io_tpu.resilience import (
            BreakerOpen,
            DeadlineExceeded,
            RejectedError,
        )

        i = 0
        next_at = time.monotonic()
        outstanding = []
        while not stop.is_set():
            now = time.monotonic()
            if now < next_at:
                time.sleep(min(next_at - now, 0.01))
                continue
            next_at += float(rng.exponential(1.0 / rate))
            try:
                outstanding.append((submit(reqs[i % len(reqs)]), now))
            except (RejectedError, DeadlineExceeded, BreakerOpen):
                shed[0] += 1
            except Exception as e:
                # anything else killing the traffic thread silently would
                # make the zero-loss verdict pass vacuously — count it
                failed.append(type(e).__name__)
            i += 1
            # resolve ready futures without blocking arrivals
            still = []
            for fut, ts in outstanding:
                if fut.done():
                    try:
                        fut.result(0)
                        completions.append((time.monotonic(),
                                            time.monotonic() - ts))
                    except Exception as e:
                        failed.append(type(e).__name__)
                else:
                    still.append((fut, ts))
            outstanding = still
        for fut, ts in outstanding:  # drain the tail
            try:
                fut.result(timeout=60)
                completions.append((time.monotonic(),
                                    time.monotonic() - ts))
            except Exception as e:
                failed.append(type(e).__name__)

    t_traffic = threading.Thread(target=traffic, daemon=True)
    t_traffic.start()
    t_start = time.monotonic()
    publishes = 0
    for i in range(1, args.swaps + 1):
        time.sleep(args.publish_every_s)
        scale = 1.0 + 1e-3 * i  # perturbed same-regime tree: gate passes
        tree = jax.tree.map(
            lambda x: x * scale
            if np.issubdtype(np.asarray(x).dtype, np.floating) else x,
            params)
        deploy.publish_params(publish_dir, i * 10, tree,
                              {"val_loss": 1.0 - 1e-3 * i})
        publishes += 1
    deadline = time.monotonic() + args.timeout_s
    while (len(deployer.history) < publishes
           and time.monotonic() < deadline):
        time.sleep(0.05)
    time.sleep(args.blip_window_s)  # let the last window fill
    stop.set()
    t_traffic.join(timeout=120)
    elapsed = time.monotonic() - t_start
    deployer.stop(args.timeout_s)

    stats = deployer.stats()
    blip = deploy.swap_window_stats(completions, swap_times,
                                    args.blip_window_s)
    swapped = [r for r in deployer.history if r["action"] == "swapped"]
    gate_ms = [1e3 * r["gate_s"] for r in deployer.history if "gate_s" in r]
    swap_ms = [1e3 * r["swap_s"] for r in swapped]
    swap_ends = [t[1] for t in swap_times]
    cadence = (None if len(swap_ends) < 2 else
               (swap_ends[-1] - swap_ends[0]) / (len(swap_ends) - 1))
    ms = lambda v: None if v is None else round(v * 1e3, 3)
    record = {
        "metric": "deploy_bench", "dry": False, "backend": backend,
        "preset": "tiny" if tiny else "flagship", "mode": mode,
        "replicas": args.replicas,
        "swaps_requested": args.swaps, "publishes": publishes,
        "swaps": stats["swaps"], "rejects": sum(stats["rejected"].values()),
        "rollbacks": stats["rollbacks"],
        # the zero-loss verdict: accepted requests that FAILED (sheds are
        # admission refusals, not losses)
        "lost_accepted": len(failed),
        "offered_rps": round(rate, 3),
        "achieved_rps": round(len(completions) / max(elapsed, 1e-9), 3),
        "completed": len(completions), "failed": len(failed),
        "shed": shed[0],
        "swap_cadence_s": None if cadence is None else round(cadence, 3),
        "gate_ms_mean": (round(float(np.mean(gate_ms)), 3)
                         if gate_ms else None),
        "swap_ms_mean": (round(float(np.mean(swap_ms)), 3)
                         if swap_ms else None),
        "per_swap": [
            {"step": r["step"], "action": r["action"],
             "gate_ms": round(1e3 * r.get("gate_s", 0.0), 3),
             "swap_ms": round(1e3 * r.get("swap_s", 0.0), 3),
             "p99_ms": ms(blip["per_swap_p99_s"][i])
             if i < len(blip["per_swap_p99_s"]) else None,
             "n_window": (blip["per_swap_n"][i]
                          if i < len(blip["per_swap_n"]) else 0)}
            for i, r in enumerate(swapped)
        ],
        "p99_steady_ms": ms(blip["p99_steady_s"]),
        "p99_swap_ms": ms(blip["p99_swap_s"]),
        "blip_ratio": (
            round(blip["p99_swap_s"] / blip["p99_steady_s"], 3)
            if blip["p99_swap_s"] and blip["p99_steady_s"] else None),
        "blip_window_s": args.blip_window_s,
    }
    _log(f"swaps {record['swaps']}/{publishes}, lost {len(failed)}, "
         f"steady p99 {record['p99_steady_ms']} ms, swap-window p99 "
         f"{record['p99_swap_ms']} ms (ratio {record['blip_ratio']})")

    if router is not None:
        router.close()
    for lr in local_replicas:
        lr.app.close()
    for e in engines:
        e.close()
    emit_json_line(record)


if __name__ == "__main__":
    main()
