"""Weight-only quantized serving A/B: bf16 vs int8w vs grouped-int4w
through the micro-batching engine, with parity vs the f32 oracle,
bytes-streamed accounting, and a fused-kernel-vs-XLA micro A/B.

The measured roofline (PERF.md, `tools/hbm_roofline.py`) shows the serving
forward bound by HBM param/elementwise streams, and every engine dispatch
re-streams the full weight set — so weight bytes are the lever. This tool
measures what `perceiver_io_tpu.quant` actually buys, per the PERF.md
discipline:

1. **Throughput A/B**: same process, interleaved rounds (bf16, int8w,
   bf16, int8w, ... — the tunnel's ±2x session swing cancels) of the same
   batch-1 gathered fill-mask request stream through two ``ServingEngine``s
   that differ ONLY in weight storage (both compute in bf16; int8w
   dequantizes inside the compiled program).
2. **Parity**: both arms' logits against the f32 oracle (the golden-parity
   forward on the identical inputs), reported as max |err| / max |oracle|
   — the bound documented in PERF.md §Quantization and pinned by
   ``tests/test_quant.py`` on the same tiny preset.
3. **Bytes-streamed**: the roofline PREDICTION (param-tree bytes per
   dispatch: int8 values + f32 scales vs the bf16 cast — every dispatch
   streams the weights once) and, on TPU, the ACHIEVED per-dispatch HBM
   bytes from the device trace's per-op ``memory_access_breakdown`` summed
   inside the engine's StepTraceAnnotation windows (the same analysis
   `tools/hbm_roofline.py` runs) — prediction vs measurement in one record.

4. **Kernel A/B** (r24): the fused dequant-matmul Pallas kernel
   (``ops/pallas_matmul``) vs the XLA dequant-then-matmul lowering on the
   SAME int8 vocab-head-shaped operands, same-process interleaved rounds.
   On CPU the kernel runs in interpret mode — expected much slower (a
   documented negative result, PERF.md §Quantization); the decision-grade
   number is the TPU run (§r10 queue).

Prints ONE JSON line on stdout (logs on stderr) — the driver-trackable
contract shared with ``tools/inference_bench.py --engine``. ``--cpu`` pins
the CPU backend before jax initializes (the tier-1 offline mode, tiny
preset); TPU runs additionally carry the ``device_*``/``achieved_*`` keys.
``--dry`` emits the record's key contract without touching any device.

Usage::

    timeout 1800 python tools/quant_bench.py [--cpu] [--dry]
        [--preset auto|tiny|flagship] [--requests N] [--rounds R]
        [--max_batch M] [--trace-dir DIR]
"""

from __future__ import annotations

import argparse
import json
import os
import statistics
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from perceiver_io_tpu.utils.jsonline import emit_json_line
from perceiver_io_tpu.utils.platform import probe_backend

# jax is imported inside main() AFTER --cpu is handled (ensure_cpu_only must
# run before any backend initializes)
import numpy as np


def _log(*a) -> None:
    print(*a, file=sys.stderr, flush=True)


def _build(tiny: bool):
    """Tiny/flagship MLM at f32 (the oracle dtype) + a synthetic batch-1
    gathered fill-mask request stream. No tokenizer: quant parity and the
    byte stream are properties of the forward, and synthetic token ids keep
    the tier-1 mode in minutes."""
    import jax

    from perceiver_io_tpu.models.presets import flagship_mlm, tiny_mlm

    build = tiny_mlm if tiny else flagship_mlm
    model = build()  # f32: scales quantize from the full-precision tree
    # read the shapes back off the preset (ONE definition — presets.py)
    max_seq_len = model.encoder.input_adapter.max_seq_len
    vocab = model.encoder.input_adapter.vocab_size

    ids = np.zeros((1, max_seq_len), np.int32)
    variables = model.init(
        {"params": jax.random.key(0), "masking": jax.random.key(1)},
        ids, ids == 1,
    )
    return model, variables["params"], max_seq_len, vocab


def _requests(n: int, max_seq_len: int, vocab: int):
    rng = np.random.default_rng(0)
    ids = rng.integers(3, vocab, (n, max_seq_len)).astype(np.int32)
    pad = np.zeros((n, max_seq_len), bool)
    positions = np.stack(
        [rng.choice(max_seq_len, 2, replace=False) for _ in range(n)]
    ).astype(np.int32)
    return [
        (ids[i: i + 1], pad[i: i + 1], positions[i: i + 1]) for i in range(n)
    ]


def _rel_to_peak_err(got: np.ndarray, ref: np.ndarray) -> float:
    scale = float(np.max(np.abs(ref))) or 1.0
    return float(np.max(np.abs(got - ref))) / scale


def _trace_hbm_per_dispatch(round_fn, trace_dir: str):
    """TPU only: per-engine-dispatch HBM bytes + lower-quartile device
    seconds, from one traced round (each engine dispatch is a
    StepTraceAnnotation step — the hbm_roofline analysis, reused)."""
    import jax

    from perceiver_io_tpu.utils.xplane import load_tpu_plane, step_windows
    from tools.hbm_roofline import HBM_SPACE, parse_memory_breakdown

    with jax.profiler.trace(trace_dir):
        round_fn()
    tpu = load_tpu_plane(trace_dir)
    names = {k: v.name for k, v in tpu.stat_metadata.items()}
    hbm_by_meta = {}
    for mid, em in tpu.event_metadata.items():
        st = {names.get(s.metadata_id): s for s in em.stats}
        if "memory_access_breakdown" not in st:
            continue
        brk = parse_memory_breakdown(st["memory_access_breakdown"].bytes_value)
        hbm_by_meta[mid] = sum(b for _, sp, b in brk if sp == HBM_SPACE)
    windows = step_windows(tpu)
    if not windows:
        return None, None, 0
    ops_line = [l for l in tpu.lines if l.name == "XLA Ops"][0]
    tot_hbm = 0
    for e in ops_line.events:
        if any(a <= e.offset_ps < b for a, b in windows):
            tot_hbm += hbm_by_meta.get(e.metadata_id, 0)
    durs = sorted(b - a for a, b in windows)
    lq_s = durs[len(durs) // 4] / 1e12
    return tot_hbm / len(windows), lq_s, len(windows)


# the record's key contract, declared for --dry (bench_compare and the
# driver read this shape; TPU runs add the achieved/device keys)
RECORD_KEYS = (
    "mode", "backend", "preset", "requests", "rounds", "max_batch",
    "seq_len",
    "bf16_requests_per_s", "int8w_requests_per_s", "int4w_requests_per_s",
    "speedup_int8w_vs_bf16", "speedup_int4w_vs_bf16",
    "parity_bf16_rel_err", "parity_int8w_rel_err", "parity_int4w_rel_err",
    "param_bytes_f32", "param_bytes_bfloat16", "param_bytes_int8w",
    "param_bytes_int4w", "quantized_leaves",
    "predicted_weight_stream_ratio", "predicted_weight_stream_ratio_int4w",
    "qmm_shape", "qmm_xla_ms", "qmm_pallas_ms", "qmm_kernel_rel_err",
    "speedup_qmm_pallas_vs_xla",
)
TPU_ONLY_KEYS = (
    "achieved_hbm_bytes_per_dispatch_bf16",
    "achieved_hbm_bytes_per_dispatch_int8w",
    "achieved_hbm_bytes_per_dispatch_int4w",
    "device_dispatch_lq_ms_bf16", "device_dispatch_lq_ms_int8w",
    "device_dispatch_lq_ms_int4w",
    "achieved_hbm_ratio_int8w_vs_bf16",
)


def _qmm_kernel_ab(tiny: bool, rounds: int):
    """Same-process interleaved fused-Pallas-vs-XLA dequant-matmul A/B at
    the vocab-head shape (the biggest serving weight stream). Both impls
    consume the SAME int8 operands, so ``qmm_kernel_rel_err`` is purely
    kernel-vs-XLA. Off-TPU the kernel runs in interpret mode — the timing
    is a correctness exercise, not a perf claim (PERF.md discipline)."""
    import jax
    import jax.numpy as jnp

    from perceiver_io_tpu.ops.pallas_matmul import quantized_matmul
    from perceiver_io_tpu.quant.int8 import QKernel, quantize_array

    m, k, n = (64, 32, 384) if tiny else (512, 64, 10112)
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.normal(0, 1, (m, k)), jnp.bfloat16)
    q, scale = quantize_array(rng.normal(0, 0.02, (k, n)).astype(np.float32))
    qk = QKernel(jnp.asarray(q, jnp.int8), jnp.asarray(scale), "bfloat16")

    impls = {
        "pallas": jax.jit(lambda x, w: quantized_matmul(x, w, impl="pallas")),
        "xla": jax.jit(lambda x, w: quantized_matmul(x, w, impl="xla")),
    }
    outs = {name: np.asarray(fn(x, qk), np.float32)
            for name, fn in impls.items()}  # warm + parity in one pass
    rel_err = _rel_to_peak_err(outs["pallas"], outs["xla"])
    times = {name: [] for name in impls}
    for _ in range(max(rounds, 2)):  # interleaved: pallas, xla, pallas, ...
        for name, fn in impls.items():
            t0 = time.perf_counter()
            fn(x, qk).block_until_ready()
            times[name].append(time.perf_counter() - t0)
    med = {k_: statistics.median(v) for k_, v in times.items()}
    return {
        "qmm_shape": f"{m}x{k}x{n}",
        "qmm_xla_ms": round(med["xla"] * 1e3, 4),
        "qmm_pallas_ms": round(med["pallas"] * 1e3, 4),
        "qmm_kernel_rel_err": round(rel_err, 6),
        "speedup_qmm_pallas_vs_xla": round(med["xla"] / med["pallas"], 4),
    }


def main() -> None:
    parser = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    parser.add_argument("--cpu", action="store_true",
                        help="pin to the CPU backend (ensure_cpu_only before "
                             "jax initializes) — the offline/tier-1 mode")
    parser.add_argument("--preset", choices=["auto", "tiny", "flagship"],
                        default="auto",
                        help="model size: auto = flagship on TPU, tiny "
                             "elsewhere (models/presets.py tiny_mlm)")
    parser.add_argument("--requests", type=int, default=64,
                        help="batch-1 requests per round")
    parser.add_argument("--rounds", type=int, default=4,
                        help="interleaved A/B rounds")
    parser.add_argument("--max_batch", type=int, default=32,
                        help="engine micro-batch cap")
    parser.add_argument("--trace-dir", default=None,
                        help="keep TPU traces here instead of a temp dir")
    parser.add_argument("--dry", action="store_true",
                        help="emit the record's key contract as one JSON "
                             "line without touching any device (stdout-"
                             "contract CI mode, like kernel_smoke --dry)")
    args = parser.parse_args()

    if args.dry:
        emit_json_line({
            "mode": "quant", "dry": True,
            "keys": list(RECORD_KEYS),
            "tpu_only_keys": list(TPU_ONLY_KEYS),
        })
        return

    if args.cpu:
        from perceiver_io_tpu.utils.platform import ensure_cpu_only

        ensure_cpu_only()
    from perceiver_io_tpu.aot import maybe_enable_cache_from_env

    maybe_enable_cache_from_env()  # PIT_COMPILE_CACHE opt-in (stderr only)
    import jax

    from perceiver_io_tpu import quant
    from perceiver_io_tpu.inference import ServingEngine

    backend = probe_backend().backend
    tiny = args.preset == "tiny" or (args.preset == "auto" and backend != "tpu")
    _log(f"backend: {backend}; preset {'tiny' if tiny else 'flagship'}; "
         f"{args.requests} requests x {args.rounds} rounds")

    model, params, max_seq_len, vocab = _build(tiny)
    requests = _requests(args.requests, max_seq_len, vocab)

    def gathered_apply(p, token_ids, pad_mask, pos):
        logits, _ = model.apply(
            {"params": p}, token_ids, pad_mask, masking=False,
            deterministic=True, positions=pos,
        )
        return logits

    # f32 oracle over the whole stream in one shot (golden-parity path)
    stacked = tuple(
        np.concatenate([r[i] for r in requests], axis=0) for i in range(3)
    )
    oracle = np.asarray(
        jax.jit(gathered_apply)(params, *stacked), np.float32
    )

    bytes_acct = quant.bytes_summary(params, compute_dtype="bfloat16")
    int4_acct = quant.bytes_summary(
        params, qparams=quant.quantize_tree(
            params, compute_dtype="bfloat16", bits=4),
        compute_dtype="bfloat16",
    )
    bytes_acct["param_bytes_int4w"] = int4_acct["param_bytes_int4w"]
    bytes_acct["predicted_weight_stream_ratio_int4w"] = (
        int4_acct["predicted_weight_stream_ratio"])
    _log(f"param bytes: f32 {bytes_acct['param_bytes_f32']:,} / bf16 "
         f"{bytes_acct['param_bytes_bfloat16']:,} / int8w "
         f"{bytes_acct['param_bytes_int8w']:,} / int4w "
         f"{bytes_acct['param_bytes_int4w']:,} "
         f"(predicted weight-stream ratios "
         f"{bytes_acct['predicted_weight_stream_ratio']} / "
         f"{bytes_acct['predicted_weight_stream_ratio_int4w']})")

    engines = {
        "bf16": ServingEngine(
            gathered_apply, params, max_batch=args.max_batch,
            compute_dtype="bfloat16", name="quant_bench_bf16",
        ),
        "int8w": ServingEngine(
            gathered_apply, params, max_batch=args.max_batch,
            compute_dtype="int8w", name="quant_bench_int8w",
        ),
        "int4w": ServingEngine(
            gathered_apply, params, max_batch=args.max_batch,
            compute_dtype="int4w", name="quant_bench_int4w",
        ),
    }
    try:
        for name, eng in engines.items():
            eng.warmup(*requests[0])
            _log(f"{name}: warmed {eng.num_programs} bucket programs")

        # parity vs the f32 oracle, identical inputs through the engine path
        parity = {}
        for name, eng in engines.items():
            futs = [eng.submit(*r) for r in requests]
            got = np.concatenate(
                [np.asarray(f.result(timeout=600), np.float32) for f in futs],
                axis=0,
            )
            parity[name] = _rel_to_peak_err(got, oracle)
            _log(f"{name}: rel-to-peak parity err vs f32 oracle "
                 f"{parity[name]:.4g}")

        def engine_round(eng) -> float:
            t0 = time.perf_counter()
            futs = [eng.submit(*r) for r in requests]
            for f in futs:
                f.result(timeout=600)
            return time.perf_counter() - t0

        for eng in engines.values():  # unmeasured steady-state round each
            engine_round(eng)
        times = {name: [] for name in engines}
        for r in range(args.rounds):  # interleaved: A, B, C, A, B, C, ...
            for name, eng in engines.items():
                times[name].append(engine_round(eng))
            _log("round %d: %s" % (r, " ".join(
                f"{name} {times[name][-1]:.3f}s" for name in engines)))
        med = {k: statistics.median(v) for k, v in times.items()}

        # the fused-kernel-vs-XLA micro A/B (interleaved, same operands)
        qmm = _qmm_kernel_ab(tiny, args.rounds)
        _log(f"qmm {qmm['qmm_shape']}: pallas {qmm['qmm_pallas_ms']} ms vs "
             f"xla {qmm['qmm_xla_ms']} ms (speedup "
             f"{qmm['speedup_qmm_pallas_vs_xla']}x, rel err "
             f"{qmm['qmm_kernel_rel_err']})")

        n = args.requests
        results = {
            "mode": "quant", "backend": backend,
            "preset": "tiny" if tiny else "flagship",
            "requests": n, "rounds": args.rounds,
            "max_batch": args.max_batch, "seq_len": max_seq_len,
            "bf16_requests_per_s": round(n / med["bf16"], 2),
            "int8w_requests_per_s": round(n / med["int8w"], 2),
            "int4w_requests_per_s": round(n / med["int4w"], 2),
            "speedup_int8w_vs_bf16": round(med["bf16"] / med["int8w"], 3),
            "speedup_int4w_vs_bf16": round(med["bf16"] / med["int4w"], 3),
            "parity_bf16_rel_err": round(parity["bf16"], 6),
            "parity_int8w_rel_err": round(parity["int8w"], 6),
            "parity_int4w_rel_err": round(parity["int4w"], 6),
            **bytes_acct,
            **qmm,
        }

        # achieved bytes-streamed (TPU): trace one round per arm, sum HBM
        # bytes inside the dispatch step windows — prediction vs measurement
        if backend == "tpu":
            trace_root = args.trace_dir or tempfile.mkdtemp(prefix="quant_bench_")
            for name, eng in engines.items():
                try:
                    hbm, lq_s, steps = _trace_hbm_per_dispatch(
                        lambda e=eng: engine_round(e),
                        os.path.join(trace_root, name),
                    )
                    if hbm is not None:
                        results[f"achieved_hbm_bytes_per_dispatch_{name}"] = (
                            int(hbm))
                        results[f"device_dispatch_lq_ms_{name}"] = round(
                            lq_s * 1e3, 4)
                        _log(f"{name}: {steps} traced dispatches, "
                             f"{hbm / 1e6:.2f} MB HBM/dispatch, "
                             f"lq {lq_s * 1e3:.3f} ms")
                except Exception as e:
                    _log(f"({name} device trace unavailable: "
                         f"{type(e).__name__}: {str(e)[:120]})")
            a, b = (results.get("achieved_hbm_bytes_per_dispatch_int8w"),
                    results.get("achieved_hbm_bytes_per_dispatch_bf16"))
            if a and b:
                results["achieved_hbm_ratio_int8w_vs_bf16"] = round(a / b, 4)
    finally:
        for eng in engines.values():
            eng.close()

    emit_json_line(results)


if __name__ == "__main__":
    main()
