"""Weight-only int8 serving A/B: bf16 vs int8w through the micro-batching
engine, with parity vs the f32 oracle and bytes-streamed accounting.

The measured roofline (PERF.md, `tools/hbm_roofline.py`) shows the serving
forward bound by HBM param/elementwise streams, and every engine dispatch
re-streams the full weight set — so weight bytes are the lever. This tool
measures what `perceiver_io_tpu.quant` actually buys, per the PERF.md
discipline:

1. **Throughput A/B**: same process, interleaved rounds (bf16, int8w,
   bf16, int8w, ... — the tunnel's ±2x session swing cancels) of the same
   batch-1 gathered fill-mask request stream through two ``ServingEngine``s
   that differ ONLY in weight storage (both compute in bf16; int8w
   dequantizes inside the compiled program).
2. **Parity**: both arms' logits against the f32 oracle (the golden-parity
   forward on the identical inputs), reported as max |err| / max |oracle|
   — the bound documented in PERF.md §Quantization and pinned by
   ``tests/test_quant.py`` on the same tiny preset.
3. **Bytes-streamed**: the roofline PREDICTION (param-tree bytes per
   dispatch: int8 values + f32 scales vs the bf16 cast — every dispatch
   streams the weights once) and, on TPU, the ACHIEVED per-dispatch HBM
   bytes from the device trace's per-op ``memory_access_breakdown`` summed
   inside the engine's StepTraceAnnotation windows (the same analysis
   `tools/hbm_roofline.py` runs) — prediction vs measurement in one record.

Prints ONE JSON line on stdout (logs on stderr) — the driver-trackable
contract shared with ``tools/inference_bench.py --engine``. ``--cpu`` pins
the CPU backend before jax initializes (the tier-1 offline mode, tiny
preset); TPU runs additionally carry the ``device_*``/``achieved_*`` keys.

Usage::

    timeout 1800 python tools/quant_bench.py [--cpu]
        [--preset auto|tiny|flagship] [--requests N] [--rounds R]
        [--max_batch M] [--trace-dir DIR]
"""

from __future__ import annotations

import argparse
import json
import os
import statistics
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from perceiver_io_tpu.utils.jsonline import emit_json_line
from perceiver_io_tpu.utils.platform import probe_backend

# jax is imported inside main() AFTER --cpu is handled (ensure_cpu_only must
# run before any backend initializes)
import numpy as np


def _log(*a) -> None:
    print(*a, file=sys.stderr, flush=True)


def _build(tiny: bool):
    """Tiny/flagship MLM at f32 (the oracle dtype) + a synthetic batch-1
    gathered fill-mask request stream. No tokenizer: quant parity and the
    byte stream are properties of the forward, and synthetic token ids keep
    the tier-1 mode in minutes."""
    import jax

    from perceiver_io_tpu.models.presets import flagship_mlm, tiny_mlm

    build = tiny_mlm if tiny else flagship_mlm
    model = build()  # f32: scales quantize from the full-precision tree
    # read the shapes back off the preset (ONE definition — presets.py)
    max_seq_len = model.encoder.input_adapter.max_seq_len
    vocab = model.encoder.input_adapter.vocab_size

    ids = np.zeros((1, max_seq_len), np.int32)
    variables = model.init(
        {"params": jax.random.key(0), "masking": jax.random.key(1)},
        ids, ids == 1,
    )
    return model, variables["params"], max_seq_len, vocab


def _requests(n: int, max_seq_len: int, vocab: int):
    rng = np.random.default_rng(0)
    ids = rng.integers(3, vocab, (n, max_seq_len)).astype(np.int32)
    pad = np.zeros((n, max_seq_len), bool)
    positions = np.stack(
        [rng.choice(max_seq_len, 2, replace=False) for _ in range(n)]
    ).astype(np.int32)
    return [
        (ids[i: i + 1], pad[i: i + 1], positions[i: i + 1]) for i in range(n)
    ]


def _rel_to_peak_err(got: np.ndarray, ref: np.ndarray) -> float:
    scale = float(np.max(np.abs(ref))) or 1.0
    return float(np.max(np.abs(got - ref))) / scale


def _trace_hbm_per_dispatch(round_fn, trace_dir: str):
    """TPU only: per-engine-dispatch HBM bytes + lower-quartile device
    seconds, from one traced round (each engine dispatch is a
    StepTraceAnnotation step — the hbm_roofline analysis, reused)."""
    import jax

    from perceiver_io_tpu.utils.xplane import load_tpu_plane, step_windows
    from tools.hbm_roofline import HBM_SPACE, parse_memory_breakdown

    with jax.profiler.trace(trace_dir):
        round_fn()
    tpu = load_tpu_plane(trace_dir)
    names = {k: v.name for k, v in tpu.stat_metadata.items()}
    hbm_by_meta = {}
    for mid, em in tpu.event_metadata.items():
        st = {names.get(s.metadata_id): s for s in em.stats}
        if "memory_access_breakdown" not in st:
            continue
        brk = parse_memory_breakdown(st["memory_access_breakdown"].bytes_value)
        hbm_by_meta[mid] = sum(b for _, sp, b in brk if sp == HBM_SPACE)
    windows = step_windows(tpu)
    if not windows:
        return None, None, 0
    ops_line = [l for l in tpu.lines if l.name == "XLA Ops"][0]
    tot_hbm = 0
    for e in ops_line.events:
        if any(a <= e.offset_ps < b for a, b in windows):
            tot_hbm += hbm_by_meta.get(e.metadata_id, 0)
    durs = sorted(b - a for a, b in windows)
    lq_s = durs[len(durs) // 4] / 1e12
    return tot_hbm / len(windows), lq_s, len(windows)


def main() -> None:
    parser = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    parser.add_argument("--cpu", action="store_true",
                        help="pin to the CPU backend (ensure_cpu_only before "
                             "jax initializes) — the offline/tier-1 mode")
    parser.add_argument("--preset", choices=["auto", "tiny", "flagship"],
                        default="auto",
                        help="model size: auto = flagship on TPU, tiny "
                             "elsewhere (models/presets.py tiny_mlm)")
    parser.add_argument("--requests", type=int, default=64,
                        help="batch-1 requests per round")
    parser.add_argument("--rounds", type=int, default=4,
                        help="interleaved A/B rounds")
    parser.add_argument("--max_batch", type=int, default=32,
                        help="engine micro-batch cap")
    parser.add_argument("--trace-dir", default=None,
                        help="keep TPU traces here instead of a temp dir")
    args = parser.parse_args()

    if args.cpu:
        from perceiver_io_tpu.utils.platform import ensure_cpu_only

        ensure_cpu_only()
    from perceiver_io_tpu.aot import maybe_enable_cache_from_env

    maybe_enable_cache_from_env()  # PIT_COMPILE_CACHE opt-in (stderr only)
    import jax

    from perceiver_io_tpu import quant
    from perceiver_io_tpu.inference import ServingEngine

    backend = probe_backend().backend
    tiny = args.preset == "tiny" or (args.preset == "auto" and backend != "tpu")
    _log(f"backend: {backend}; preset {'tiny' if tiny else 'flagship'}; "
         f"{args.requests} requests x {args.rounds} rounds")

    model, params, max_seq_len, vocab = _build(tiny)
    requests = _requests(args.requests, max_seq_len, vocab)

    def gathered_apply(p, token_ids, pad_mask, pos):
        logits, _ = model.apply(
            {"params": p}, token_ids, pad_mask, masking=False,
            deterministic=True, positions=pos,
        )
        return logits

    # f32 oracle over the whole stream in one shot (golden-parity path)
    stacked = tuple(
        np.concatenate([r[i] for r in requests], axis=0) for i in range(3)
    )
    oracle = np.asarray(
        jax.jit(gathered_apply)(params, *stacked), np.float32
    )

    bytes_acct = quant.bytes_summary(params, compute_dtype="bfloat16")
    _log(f"param bytes: f32 {bytes_acct['param_bytes_f32']:,} / bf16 "
         f"{bytes_acct['param_bytes_bfloat16']:,} / int8w "
         f"{bytes_acct['param_bytes_int8w']:,} "
         f"(predicted weight-stream ratio "
         f"{bytes_acct['predicted_weight_stream_ratio']})")

    engines = {
        "bf16": ServingEngine(
            gathered_apply, params, max_batch=args.max_batch,
            compute_dtype="bfloat16", name="quant_bench_bf16",
        ),
        "int8w": ServingEngine(
            gathered_apply, params, max_batch=args.max_batch,
            compute_dtype="int8w", name="quant_bench_int8w",
        ),
    }
    try:
        for name, eng in engines.items():
            eng.warmup(*requests[0])
            _log(f"{name}: warmed {eng.num_programs} bucket programs")

        # parity vs the f32 oracle, identical inputs through the engine path
        parity = {}
        for name, eng in engines.items():
            futs = [eng.submit(*r) for r in requests]
            got = np.concatenate(
                [np.asarray(f.result(timeout=600), np.float32) for f in futs],
                axis=0,
            )
            parity[name] = _rel_to_peak_err(got, oracle)
            _log(f"{name}: rel-to-peak parity err vs f32 oracle "
                 f"{parity[name]:.4g}")

        def engine_round(eng) -> float:
            t0 = time.perf_counter()
            futs = [eng.submit(*r) for r in requests]
            for f in futs:
                f.result(timeout=600)
            return time.perf_counter() - t0

        for eng in engines.values():  # unmeasured steady-state round each
            engine_round(eng)
        times = {"bf16": [], "int8w": []}
        for r in range(args.rounds):  # interleaved: A, B, A, B, ...
            for name, eng in engines.items():
                times[name].append(engine_round(eng))
            _log(f"round {r}: bf16 {times['bf16'][-1]:.3f}s "
                 f"int8w {times['int8w'][-1]:.3f}s")
        med = {k: statistics.median(v) for k, v in times.items()}

        n = args.requests
        results = {
            "mode": "quant", "backend": backend,
            "preset": "tiny" if tiny else "flagship",
            "requests": n, "rounds": args.rounds,
            "max_batch": args.max_batch, "seq_len": max_seq_len,
            "bf16_requests_per_s": round(n / med["bf16"], 2),
            "int8w_requests_per_s": round(n / med["int8w"], 2),
            "speedup_int8w_vs_bf16": round(med["bf16"] / med["int8w"], 3),
            "parity_bf16_rel_err": round(parity["bf16"], 6),
            "parity_int8w_rel_err": round(parity["int8w"], 6),
            **bytes_acct,
        }

        # achieved bytes-streamed (TPU): trace one round per arm, sum HBM
        # bytes inside the dispatch step windows — prediction vs measurement
        if backend == "tpu":
            trace_root = args.trace_dir or tempfile.mkdtemp(prefix="quant_bench_")
            for name, eng in engines.items():
                try:
                    hbm, lq_s, steps = _trace_hbm_per_dispatch(
                        lambda e=eng: engine_round(e),
                        os.path.join(trace_root, name),
                    )
                    if hbm is not None:
                        results[f"achieved_hbm_bytes_per_dispatch_{name}"] = (
                            int(hbm))
                        results[f"device_dispatch_lq_ms_{name}"] = round(
                            lq_s * 1e3, 4)
                        _log(f"{name}: {steps} traced dispatches, "
                             f"{hbm / 1e6:.2f} MB HBM/dispatch, "
                             f"lq {lq_s * 1e3:.3f} ms")
                except Exception as e:
                    _log(f"({name} device trace unavailable: "
                         f"{type(e).__name__}: {str(e)[:120]})")
            a, b = (results.get("achieved_hbm_bytes_per_dispatch_int8w"),
                    results.get("achieved_hbm_bytes_per_dispatch_bf16"))
            if a and b:
                results["achieved_hbm_ratio_int8w_vs_bf16"] = round(a / b, 4)
    finally:
        for eng in engines.values():
            eng.close()

    emit_json_line(results)


if __name__ == "__main__":
    main()
