"""Open-loop offered-load sweep over the serving engine: traffic curves,
per-phase tail attribution, and the measured capacity model.

The closed-loop A/B in ``tools/inference_bench.py`` answers "how much faster
is the engine than naive dispatch" — but a closed loop can never measure
*saturation*: its arrival rate self-throttles to whatever the system serves
(coordinated omission). This harness is OPEN-loop: requests arrive on a
Poisson (or bursty) schedule at a configured offered rate whether or not the
engine keeps up, which is what real traffic does. Sweeping offered rates
produces the curves every SLO claim needs:

- achieved throughput vs offered (the plateau IS the capacity);
- p50/p95/p99 end-to-end latency per point, attributed per lifecycle phase
  (``inference/engine.py`` phase tracing — past the knee, p99 grows in the
  QUEUE phase while the device phase stays flat: the signature of
  saturation, as opposed to a slowing device);
- shed rate (bounded-queue fast-fail) and breaker state;
- the fitted capacity model (``obs/slo.py fit_capacity``): service-time
  floor, the knee where p99 departs it, max sustainable requests/s at the
  SLO.

Offered rates default to fractions of a calibrated closed-loop capacity
estimate, so the same sweep spans the knee on any backend. Emits exactly ONE
JSON line on stdout (progress on stderr). ``--cpu`` pins the CPU backend
before jax initializes (tier-1 offline mode, tiny preset); ``--dry`` emits
the record schema without touching a backend. Real-TPU runs ride the PERF.md
§r10 pending queue: the capacity model composes with the device-trace
discipline because the per-phase DEVICE number can be cross-checked against
the lower-quartile trace statistic while queue/admission phases are
host-side and tunnel-insensitive.

``--replicas N`` runs the same sweep through the multi-replica fabric
(``perceiver_io_tpu.serving``): a router over N replicas —
``--replica_mode inprocess`` (default; N engines behind ``LocalReplica``
shims, fast) or ``process`` (real supervised replica processes, the
acceptance-drill configuration). ``--kill_replica_at FRAC`` is the chaos
drill: at FRAC of sweep point ``--kill_point``'s offered window one replica
dies (``kill -9`` in process mode; the supervisor restarts it and it
rejoins once warm), and the record's ``fleet`` block carries the drill's
verdict — ``lost_accepted`` MUST be 0 (accepted requests re-route, never
drop). Per-request phase attribution crosses the RPC since r15 (the replica
returns the engine future's phases; router futures surface them), so fleet
points carry BOTH router-measured end-to-end latency and the replica-side
phase breakdowns.

``--transport {http,uds,shmem}`` selects the router→replica data plane for
process fleets (``serving.transport``; the replica keeps its HTTP admin
surface either way, so scrape/drain/kill drills work identically). With
``--trace_ab``, a non-http transport also runs the paired-interleave
http-vs-transport A/B over the same live fleet: two routers, order-
alternated closed-loop waves of batch-1 small frames, and the per-attempt
RPC cost (``router_attempt`` span duration minus the replica-reported
engine phase sum) compared per arm — the record's ``transport`` block
carries ``rpc_p50_speedup`` (the r22 bar: >= 2 for uds/shmem).

``--trace_ab`` measures the r15 distributed-tracing overhead the honest way
(PERF.md discipline: same-process, interleaved): closed-loop waves alternate
traced (event log + span emission at every hop) and untraced in ONE process,
and the record's ``trace`` block reports both throughputs and the overhead
ratio — the acceptance bar is <= 2% on CPU.

Usage::

    timeout 1800 python tools/load_bench.py --cpu [--arrival poisson|bursty]
        [--duration_s 4] [--rate_factors 0.25,0.5,1.0,1.5,2.5]
        [--rates RPS,RPS,...] [--queue_limit 64] [--slo_p99_ms MS]
        [--replicas 3 [--replica_mode process] [--kill_replica_at 0.5]]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import threading
import time
from collections import defaultdict
from typing import Dict, List, Optional

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from perceiver_io_tpu.utils.jsonline import emit_json_line
from perceiver_io_tpu.utils.platform import probe_backend

# NOTE: jax is imported inside the run path AFTER --cpu is handled —
# utils.platform.ensure_cpu_only must run before any backend initializes.
import numpy as np

POINT_KEYS = (
    "offered_rps", "submitted", "completed", "shed", "failed", "shed_rate",
    "achieved_rps", "p50_ms", "p95_ms", "p99_ms", "phase_p50_ms",
    "phase_p99_ms", "breaker",
)
# mirrors inference.engine.PHASES (asserted on the run path) so --dry stays
# backend-free: importing the engine module pulls jax
PHASE_KEYS = ("admission", "queue", "assembly", "dispatch", "device",
              "complete")
# the fleet block of a --replicas run (null for single-engine sweeps);
# lost_accepted is the chaos drill's verdict and must be 0 — on EVERY
# transport (the r22 kill drill re-runs it with --transport uds/shmem)
FLEET_KEYS = ("replicas", "mode", "transport", "killed", "kill_at_frac",
              "kill_point", "reroutes", "affinity_spills", "lost_accepted",
              "restarts")
# the deploy block of a --publish_every_s run (null otherwise): the
# train→serve ride-along — checkpoints published and gate-swapped DURING the
# sweep, with p99 attributed to ±window swap windows vs steady state
# (perceiver_io_tpu.deploy.swap_window_stats; PERF.md §Deployment)
DEPLOY_KEYS = ("publish_every_s", "publishes", "swaps", "rejects",
               "rollbacks", "p99_steady_ms", "p99_swap_ms", "blip_ratio",
               "per_swap_p99_ms")
# the trace block of a --trace_ab run (null otherwise): same-process
# interleaved traced-vs-untraced closed-loop waves; overhead_pct is the
# throughput cost of full tracing (PERF.md §Tracing bar: <= 2% on CPU)
TRACE_KEYS = ("ab_waves", "untraced_rps", "traced_rps", "overhead_pct",
              "spans_recorded",
              # nested generate-class A/B (--generate_rps runs only, null
              # otherwise): the token-level streaming instrumentation's
              # own overhead bar — traced-vs-untraced tokens/s by the same
              # paired-interleave discipline, counting decode_* spans and
              # flight-recorder events in the traced arm
              "generate_ab")
# the transport block of a --trace_ab run over a process fleet spawned with
# --transport uds|shmem (null otherwise): TWO routers over the SAME live
# replicas — the portable HTTP arm and the --transport data plane — driven
# by paired order-alternated closed-loop waves of batch-1 small frames.
# rpc_* is the per-attempt TRANSPORT cost: the router_attempt span duration
# minus the replica-reported engine phase sum (server_s rides the span), i.e.
# serialize + wire + deserialize + connection wait. The r22 acceptance bar:
# rpc_p50_speedup >= 2 (uds/shmem RPC span p50 at least 2x smaller than
# HTTP's)
TRANSPORT_KEYS = ("transport", "ab_waves", "wave_size", "http_rps",
                  "transport_rps", "throughput_speedup", "http_rpc_p50_ms",
                  "http_rpc_p99_ms", "rpc_p50_ms", "rpc_p99_ms",
                  "rpc_p50_speedup", "spans_http", "spans_transport")
# the alerts block of a --series_jsonl run (null otherwise): the
# timeseries+alerting ride-along — registry sampled on a cadence during the
# sweep, context-default alert rules evaluated over the windowed series
ALERT_KEYS = ("rules", "fired", "resolved", "firing_at_end",
              "series_samples", "series_jsonl")
# the series_ab block of a --series_ab run (null otherwise): sampler
# overhead by the same paired-interleave methodology as --trace_ab
# (PERF.md §Timeseries bar: <= 2% on CPU at the default cadence); --ab_null
# runs both arms unsampled (the floor measurement)
SERIES_AB_KEYS = ("ab_waves", "unsampled_rps", "sampled_rps",
                  "overhead_pct", "interval_s", "null")
# the autoscale block of a --schedule run (null otherwise): per-segment
# offered rates ride the sweep; this block carries the control-loop verdict
# — replica-seconds actually spent vs a static fleet sized for the observed
# peak, p99 vs the SLO across segments, and lost_accepted (must be 0
# across every scale event)
AUTOSCALE_KEYS = ("enabled", "schedule", "period_s", "low", "high",
                  "rps_per_replica", "min_replicas", "max_replicas",
                  "initial_replicas", "peak_replicas", "scale_ups",
                  "scale_downs", "spawn_failures", "decisions",
                  "replica_seconds", "static_replica_seconds",
                  "replica_seconds_saved_pct", "p99_ms_max", "slo_p99_ms",
                  "p99_within_slo", "lost_accepted")
# the admission block of a --noisy_neighbor run (null otherwise): two
# classes (gold victim / bronze abuser), the abuser under a token-bucket
# quota — phase A both polite, phase B the abuser floods at flood_factor ×
# quota. The isolation verdict: the victim's p99 moves within the recorded
# ±1.5 pt paired-interleave floor while the abuser's own class absorbs the
# shedding
ADMISSION_KEYS = ("classes", "abuser_quota_rps", "flood_factor", "pairs",
                  "null", "victim_rps", "abuser_rps_baseline",
                  "abuser_rps_drill", "victim_p99_baseline_ms",
                  "victim_p99_drill_ms", "victim_p99_delta_pct",
                  "victim_completed", "victim_shed",
                  "abuser_shed_baseline", "abuser_shed_drill",
                  "abuser_admitted_drill",
                  "victim_p99_unprotected_ms", "victim_shed_unprotected",
                  "sheds_by_reason")


# the generate block of a --generate_rps run (null otherwise): the SECOND,
# stateful traffic class — streamed Perceiver-AR continuations with
# variable prefix lengths, geometric continuation lengths, and the sweep's
# arrival process — running CONCURRENTLY with the one-shot sweep so the
# r17 autoscale/admission policies (and least-loaded placement) see mixed
# traffic. Streams are sessions: ~a third issue a follow-up continuation
# against their replica-resident cache (`resumed` counts the fast path).
GENERATE_KEYS = ("offered_streams", "completed", "failed", "shed",
                 "tokens_total", "steps_per_s", "stream_p50_ms",
                 "stream_p95_ms", "stream_p99_ms", "followups", "resumed",
                 "reroutes", "spills", "mean_new", "prefix_lens",
                 "concurrency",
                 # --decode_batching: the continuous-batching arena's
                 # steady-state aggregates summed over replicas (null
                 # per-key when the per-session engine served the class) —
                 # ar_decode_slot_occupancy is the mean decode batch fill
                 # the weight stream amortized over
                 "decode_batched", "ar_decode_slot_occupancy",
                 "steps_per_dispatch", "dispatches", "arena_slots",
                 # nested token-level streaming block (STREAM_KEYS)
                 "stream")
# the stream sub-block of the generate record: caller-clock TTFT/ITL
# percentiles (stamped from the on_tokens frames the load generator
# receives — the ground truth the engine-side decode_ttft/itl histograms
# must reconcile against), engine-side goodput accounting
# (decode_tokens_total by outcome; goodput = delivered/generated), and the
# flight recorder's idle-slot-round attribution (batched engines only —
# null per-key when the per-session engine served the class)
STREAM_KEYS = ("ttft_p50_ms", "ttft_p95_ms", "itl_p50_ms", "itl_p95_ms",
               "streams_timed", "tokens_generated", "tokens_delivered",
               "tokens_wasted", "goodput", "idle_slot_rounds",
               "idle_attributed", "idle_attribution_frac", "idle_causes")
# the generate class's sampling shape — ONE definition shared by the load
# generator and the per-replica warmup (greedy vs top-k are distinct decode
# programs; a mismatch would re-introduce mid-stream compile stalls)
GENERATE_TEMPERATURE, GENERATE_TOP_K = 0.8, 16


def _pct(values: List[float], q: float) -> Optional[float]:
    """Sorted-index percentile; None when nothing was observed (a fully-shed
    sweep point) — the record carries null, never NaN (invalid JSON)."""
    v = sorted(values)
    return v[min(len(v) - 1, int(q * len(v)))] if v else None


def _log(*a) -> None:
    print(*a, file=sys.stderr, flush=True)


def _build_requests(max_seq_len: int, vocab: int, n: int, seed: int):
    """Synthetic batch-1 fill-mask-shaped requests (ids, pad, positions) —
    identical signature so the sweep isolates load behavior, not width
    bucketing (which has its own bench)."""
    rng = np.random.default_rng(seed)
    reqs = []
    for _ in range(n):
        ids = rng.integers(
            3, vocab, size=(1, max_seq_len), dtype=np.int64).astype(np.int32)
        pad = np.zeros((1, max_seq_len), bool)
        positions = np.array([[1, 2]], np.int32)
        reqs.append((ids, pad, positions))
    return reqs


def _fut_latencies(fut, t_submit: float):
    """(end-to-end latencies, phase records) for one completed future.
    Router futures carry a completion stamp (the honest e2e, including
    RPC + routing) AND, since r15, the replica engine's phase records
    returned through the RPC; engine futures carry phases only, whose sum
    IS the e2e (the r11 reconciliation)."""
    recs = getattr(fut, "phases", None) or []
    t_done = getattr(fut, "t_done", None)
    if t_done is not None:
        return [t_done - t_submit], recs
    if recs:
        return [sum(r.values()) for r in recs], recs
    return [], []


def _calibrate(submit, reqs, waves: int, wave_size: int):
    """Closed-loop capacity estimate: submit ``wave_size`` requests, wait for
    all, repeat — the engine batches each wave, so the measured rate is the
    batched service capacity the open-loop sweep should straddle. Also
    returns the median end-to-end latency (the service-time scale for the
    default SLO target)."""
    rates, lats = [], []
    for w in range(waves):
        t0 = time.monotonic()
        futs = [(submit(reqs[i % len(reqs)]), time.monotonic())
                for i in range(wave_size)]
        for f, _ in futs:
            f.result(timeout=300)
        dt = time.monotonic() - t0
        rates.append(wave_size / dt)
        for f, ts in futs:
            lats.extend(_fut_latencies(f, ts)[0])
    rates.sort()
    lat = _pct(lats, 0.5)
    return rates[len(rates) // 2], lat if lat is not None else 0.01


def _ab_rates(submit, reqs, waves: int, wave_size: int,
              drain_timeout_s: float, set_arm) -> Dict[bool, List[float]]:
    """The shared paired-interleave wave engine (PERF.md discipline):
    closed-loop waves alternate the armed/disarmed condition AND the order
    per pair (U,T then T,U — a null control measured a ~0.5% second-of-
    pair bias on this host), so the per-pair ratios cancel slow drift."""
    rates: Dict[bool, List[float]] = {False: [], True: []}
    for w in range(2 * waves):
        armed = bool(w % 2) ^ bool((w // 2) % 2)
        set_arm(armed)
        t0 = time.monotonic()
        futs = [submit(reqs[i % len(reqs)]) for i in range(wave_size)]
        for f in futs:
            f.result(timeout=drain_timeout_s)
        rates[armed].append(wave_size / (time.monotonic() - t0))
    set_arm(False)
    return rates


def _paired_overhead(rates: Dict[bool, List[float]]):
    """(disarmed median rps, armed median rps, paired overhead fraction):
    the overhead is the median of per-adjacent-pair ratios, so host drift
    cancels instead of inflating the arm medians."""
    med = lambda v: sorted(v)[len(v) // 2]
    paired = med([1.0 - t / u
                  for u, t in zip(rates[False], rates[True])])
    return med(rates[False]), med(rates[True]), paired


def _series_ab(submit, reqs, waves: int, wave_size: int,
               drain_timeout_s: float, interval_s: float,
               null: bool) -> Dict:
    """Sampler-overhead A/B: armed waves run a live Sampler at
    ``interval_s`` over the process registry (the full instrument sweep +
    store append path), disarmed waves run none. ``null`` arms NOTHING in
    either arm — the floor measurement the overhead claim is judged
    against."""
    import perceiver_io_tpu.obs as obs

    state = {"sampler": None}

    def set_arm(armed: bool) -> None:
        if state["sampler"] is not None:
            state["sampler"].close()
            state["sampler"] = None
        if armed and not null:
            state["sampler"] = obs.Sampler(
                store=obs.SeriesStore(), interval_s=interval_s,
                name="series_ab").start()

    rates = _ab_rates(submit, reqs, waves, wave_size, drain_timeout_s,
                      set_arm)
    unsampled, sampled, paired = _paired_overhead(rates)
    return {
        "ab_waves": waves,
        "unsampled_rps": round(unsampled, 3),
        "sampled_rps": round(sampled, 3),
        "overhead_pct": round(100.0 * paired, 3),
        "interval_s": interval_s,
        "null": null,
    }


def _trace_ab(submit, reqs, waves: int, wave_size: int,
              drain_timeout_s: float) -> Dict:
    """Same-process INTERLEAVED traced-vs-untraced A/B (the PERF.md
    measurement discipline — a cross-run comparison would measure host
    drift, not tracing): closed-loop waves alternate with the event log
    (and therefore trace minting + span emission at every hop) on and off;
    the reported overhead is the median of per-adjacent-PAIR ratios, so
    slow host drift cancels instead of inflating the arm medians."""
    import tempfile

    import perceiver_io_tpu.obs as obs

    tmp = tempfile.NamedTemporaryFile(prefix="load_bench_trace_",
                                      suffix=".jsonl", delete=False)
    tmp.close()
    spans = 0
    try:
        rates = _ab_rates(
            submit, reqs, waves, wave_size, drain_timeout_s,
            lambda traced: obs.configure_event_log(
                tmp.name if traced else None))
        with open(tmp.name) as f:
            for line in f:
                rec = json.loads(line)
                if rec.get("event") == "span":
                    spans += 1
                elif rec.get("event") == "request_phases_batch":
                    # parts is the ";"-joined packed row string
                    parts = rec.get("parts") or ""
                    spans += parts.count(";") + 1 if parts else 0
    finally:
        # unhook FIRST: a wave that raised mid-A/B must not leave the
        # process-wide log writing into the inode unlinked below
        obs.configure_event_log(None)
        os.unlink(tmp.name)
    untraced, traced_rps, paired = _paired_overhead(rates)
    return {
        "ab_waves": waves,
        "untraced_rps": round(untraced, 3),
        "traced_rps": round(traced_rps, 3),
        "overhead_pct": round(100.0 * paired, 3),
        "spans_recorded": spans,
    }


def _transport_ab(transport: str, ports: Dict[str, int], waves: int,
                  wave_size: int, drain_timeout_s: float, reqs,
                  registry, request_timeout_s: float) -> Dict:
    """Same-process INTERLEAVED transport A/B (the PERF.md discipline): TWO
    routers over the SAME live replica processes — one on the portable HTTP
    client, one on the ``--transport`` data plane (the replica serves both;
    its endpoints are keyed by the HTTP port) — with the paired order-
    alternated closed-loop waves choosing which router submits. The event
    log runs for the WHOLE A/B so both arms pay identical span-emission
    cost, and the RPC verdict reads the ``router_attempt`` spans: each ok
    span carries ``server_s`` (the replica-reported engine phase sum), so
    ``dur_s - server_s`` isolates serialize + wire + deserialize +
    connection wait — the transport, not the shared engine compute."""
    import tempfile

    import perceiver_io_tpu.obs as obs
    from perceiver_io_tpu.serving import Router
    from perceiver_io_tpu.serving.transport import make_client

    routers: Dict[str, object] = {}
    arm_clients: Dict[str, list] = {}
    for arm in ("http", transport):
        cs = [make_client(arm, f"ab-{arm}-{name}", port)
              for name, port in sorted(ports.items())]
        arm_clients[arm] = cs
        routers[arm] = Router(cs, name=f"lb_ab_{arm}", registry=registry,
                              scrape_interval_s=0.1,
                              request_timeout_s=request_timeout_s)
        routers[arm].refresh()
    state = {"arm": "http"}
    submit = lambda req: routers[state["arm"]].submit(*req)
    tmp = tempfile.NamedTemporaryFile(prefix="load_bench_transport_",
                                      suffix=".jsonl", delete=False)
    tmp.close()
    rpc: Dict[str, List[float]] = {"http": [], transport: []}
    by_router = {"lb_ab_http": "http", f"lb_ab_{transport}": transport}
    try:
        obs.configure_event_log(tmp.name)
        rates = _ab_rates(
            submit, reqs, waves, wave_size, drain_timeout_s,
            lambda armed: state.__setitem__(
                "arm", transport if armed else "http"))
        obs.configure_event_log(None)
        with open(tmp.name) as f:
            for line in f:
                try:
                    rec = json.loads(line)
                except json.JSONDecodeError:
                    continue
                arm = by_router.get(rec.get("router", ""))
                if (arm is not None and rec.get("event") == "span"
                        and rec.get("name") == "router_attempt"
                        and rec.get("ok") is True):
                    rpc[arm].append(max(
                        0.0, rec["dur_s"] - rec.get("server_s", 0.0)))
    finally:
        # unhook FIRST (the _trace_ab discipline), then tear down the A/B
        # routers + both client sets — the fleet itself stays up for the
        # sweep that follows
        obs.configure_event_log(None)
        os.unlink(tmp.name)
        for arm, r in routers.items():
            r.close()
            for c in arm_clients[arm]:
                c.close()
    http_rps, t_rps, paired = _paired_overhead(rates)
    p50 = {a: _pct(v, 0.5) for a, v in rpc.items()}
    p99 = {a: _pct(v, 0.99) for a, v in rpc.items()}
    return {
        "transport": transport,
        "ab_waves": waves,
        "wave_size": wave_size,
        "http_rps": round(http_rps, 3),
        "transport_rps": round(t_rps, 3),
        # _paired_overhead's fraction is 1 - armed/disarmed per pair; the
        # armed arm is the fast transport, so the paired speedup is 1 - it
        "throughput_speedup": round(1.0 - paired, 3),
        "http_rpc_p50_ms": _ms(p50["http"]),
        "http_rpc_p99_ms": _ms(p99["http"]),
        "rpc_p50_ms": _ms(p50[transport]),
        "rpc_p99_ms": _ms(p99[transport]),
        # the acceptance headline: HTTP RPC span p50 over the transport's
        "rpc_p50_speedup": (round(p50["http"] / p50[transport], 3)
                            if p50["http"] and p50[transport] else None),
        "spans_http": len(rpc["http"]),
        "spans_transport": len(rpc[transport]),
    }


def _generate_trace_ab(router, waves: int, wave_size: int, seed: int,
                       vocab: int = 503, max_new: int = 8) -> Dict:
    """Traced-vs-untraced A/B on the GENERATE class — the overhead bar for
    the token-level streaming instrumentation (per-stream spans, TTFT/ITL
    stamps, goodput counters, flight-recorder spooling). Same paired-
    interleave wave engine as ``_trace_ab``, but each wave is
    ``wave_size`` SEQUENTIAL streams (generation runs on the caller's
    thread) and the rate is tokens/s, the unit the per-token stamps tax.
    The traced arm's event file is scanned for decode_* spans and flight
    events — zero recorded means the arm never actually armed.

    A NULL pass runs first: the same paired wave structure with the event
    log hooked in NEITHER arm, so ``null_overhead_pct`` measures the
    pairing noise floor of this run in this process. An ``overhead_pct``
    inside the null envelope is indistinguishable from zero — on the
    single-core CPU box the null floor is several points wide (thread
    scheduling, not instrument cost; PERF.md §Streaming observability),
    which is why the record carries its own control."""
    import tempfile

    import perceiver_io_tpu.obs as obs

    tmp = tempfile.NamedTemporaryFile(prefix="load_bench_genab_",
                                      suffix=".jsonl", delete=False)
    tmp.close()
    rng = np.random.default_rng(seed + 13)
    decode_events = 0

    def run_pass(tag: str,
                 arm_log_path: Optional[str]) -> Dict[bool, List[float]]:
        rates: Dict[bool, List[float]] = {False: [], True: []}
        for w in range(2 * waves):
            traced = bool(w % 2) ^ bool((w // 2) % 2)
            obs.configure_event_log(arm_log_path if traced else None)
            t0 = time.monotonic()
            toks = 0
            for i in range(wave_size):
                prefix = [int(t) for t in rng.integers(3, vocab, 8)]
                res = router.generate(
                    prefix, session=f"genab-{tag}-{w}-{i}",
                    max_new=max_new, temperature=GENERATE_TEMPERATURE,
                    top_k=GENERATE_TOP_K, seed=seed)
                toks += len(res["tokens"])
            rates[traced].append(toks / (time.monotonic() - t0))
        return rates

    try:
        null_rates = run_pass("null", None)
        rates = run_pass("real", tmp.name)
        with open(tmp.name) as f:
            for line in f:
                try:
                    rec = json.loads(line)
                except json.JSONDecodeError:
                    continue
                ev = rec.get("event")
                if ev == "span" and str(rec.get("name", "")
                                        ).startswith("decode_"):
                    decode_events += 1
                elif ev in ("decode_flight_batch", "decode_flight_dump"):
                    decode_events += 1
    finally:
        # unhook FIRST (same discipline as _trace_ab): a raised wave must
        # not leave the global log writing into the unlinked inode
        obs.configure_event_log(None)
        os.unlink(tmp.name)
    _, _, null_paired = _paired_overhead(null_rates)
    untraced, traced_tps, paired = _paired_overhead(rates)
    return {
        "ab_waves": waves,
        "untraced_tokens_per_s": round(untraced, 3),
        "traced_tokens_per_s": round(traced_tps, 3),
        "overhead_pct": round(100.0 * paired, 3),
        "null_overhead_pct": round(100.0 * null_paired, 3),
        "decode_events_recorded": decode_events,
    }


def _arrival_gaps(arrival: str, rate: float, duration: float, burst: int,
                  rng) -> List[float]:
    """Arrival offsets (seconds from point start) over the offered window."""
    times, t = [], 0.0
    i = 0
    while t < duration:
        times.append(t)
        i += 1
        if arrival == "poisson":
            t += float(rng.exponential(1.0 / rate))
        else:  # bursty: `burst` back-to-back arrivals, then one long gap
            t += 0.0 if i % burst else burst / rate
    return times


def _schedule_factors(schedule: str, low: float, high: float) -> List[float]:
    """Per-segment offered-rate factors (of the calibrated initial-fleet
    capacity) for the --schedule arrival profiles: ``step`` holds low, steps
    to the peak, steps back; ``burst`` alternates; ``diurnal`` traces one
    raised-cosine day. Each factor runs for --schedule_period_s."""
    if schedule == "step":
        return [low, low, high, high, low, low]
    if schedule == "burst":
        return [low, high, low, high, low, high]
    # diurnal: one smooth low → high → low cycle over 8 segments
    import math

    k = 8
    return [low + (high - low) * 0.5 * (1.0 - math.cos(2.0 * math.pi
                                                       * i / k))
            for i in range(k)]


def _run_point(submit, breaker_state, reqs, rate: float, duration: float,
               arrival: str, burst: int, rng, drain_timeout_s: float,
               on_frac=None, sink=None) -> Dict:
    from perceiver_io_tpu.resilience import (
        BreakerOpen,
        DeadlineExceeded,
        RejectedError,
    )

    arrivals = _arrival_gaps(arrival, rate, duration, burst, rng)
    t0 = time.monotonic()
    futures = []
    shed = 0
    fired = on_frac is None
    for i, at in enumerate(arrivals):
        if not fired and at / duration >= on_frac[0]:
            fired = True
            on_frac[1]()  # the chaos hook (kill a replica mid-window)
        delay = t0 + at - time.monotonic()
        if delay > 0:
            time.sleep(delay)
        try:
            futures.append((submit(reqs[i % len(reqs)]), time.monotonic()))
        except (RejectedError, DeadlineExceeded, BreakerOpen):
            shed += 1  # open loop: an arrival the engine refuses is SHED
    if not fired:
        on_frac[1]()  # a sparse schedule may end before FRAC: fire late
    submitted = len(arrivals)

    completed = failed = 0
    lats: List[float] = []
    phases: Dict[str, List[float]] = defaultdict(list)
    for fut, ts in futures:
        try:
            fut.result(timeout=drain_timeout_s)
        except (RejectedError, DeadlineExceeded):
            shed += 1
            continue
        except Exception:
            failed += 1
            continue
        completed += 1
        fut_lats, recs = _fut_latencies(fut, ts)
        lats.extend(fut_lats)
        if sink is not None:
            # (completion stamp, latency) pairs for the deploy ride-along's
            # swap-window attribution (engine futures: submit stamp + latency
            # approximates t_done; router futures carry t_done directly)
            t_done = getattr(fut, "t_done", None)
            for la in fut_lats:
                sink.append((t_done if t_done is not None else ts + la, la))
        for rec in recs:
            for k, v in rec.items():
                phases[k].append(v)
    elapsed = time.monotonic() - t0  # offered window + drain: under
    # overload the drain serves at capacity, so achieved ≈ the plateau
    point = {
        "offered_rps": round(rate, 3),
        "submitted": submitted,
        "completed": completed,
        "shed": shed,
        "failed": failed,
        "shed_rate": round((shed + failed) / max(submitted, 1), 4),
        "achieved_rps": round(completed / elapsed, 3),
        "p50_s": _pct(lats, 0.50),
        "p95_s": _pct(lats, 0.95),
        "p99_s": _pct(lats, 0.99),
        "phase_p50_s": {k: _pct(v, 0.50) for k, v in sorted(phases.items())},
        "phase_p99_s": {k: _pct(v, 0.99) for k, v in sorted(phases.items())},
        "breaker": breaker_state(),
    }
    return point


def _noisy_neighbor(router, reqs, rng, duration: float, victim_rps: float,
                    quota_rps: float, flood_factor: float,
                    drain_timeout_s: float, pairs: int = 3,
                    null: bool = False) -> Dict:
    """The noisy-neighbor drill: a gold-class victim at a steady polite
    rate, a bronze-class abuser that alternates polite (under its token-
    bucket quota) and flooding (``flood_factor`` × quota) sub-phases. The
    PERF.md paired-interleave discipline applies — ``pairs`` (baseline,
    drill) sub-phase pairs run order-ALTERNATED in one process, and the
    victim's verdict is the paired median p99 delta, so slow host drift
    cancels instead of masquerading as interference. ``null`` runs the
    abuser polite in BOTH arms: the drill's own noise floor. The verdict
    the record carries: the victim's p99 stays flat (within that floor)
    while the abuser's own class absorbs the shedding."""
    from perceiver_io_tpu.resilience import RejectedError

    def phase(abuser_rps: float, abuser_tag: str = "abuser",
              abuser_cls: Optional[str] = "bronze") -> Dict:
        arrivals = sorted(
            [(t, "victim", "gold")
             for t in _arrival_gaps("poisson", victim_rps, duration, 8, rng)]
            + [(t, abuser_tag, abuser_cls)
               for t in _arrival_gaps("poisson", abuser_rps, duration, 8,
                                      rng)])
        t0 = time.monotonic()
        futs = {"victim": [], abuser_tag: []}
        shed = {"victim": 0, abuser_tag: 0}
        for at, client, cls in arrivals:
            delay = t0 + at - time.monotonic()
            if delay > 0:
                time.sleep(delay)
            try:
                futs[client].append(
                    (router.submit(*reqs[len(futs[client]) % len(reqs)],
                                   client=(None if client == "anon"
                                           else client),
                                   priority=cls),
                     time.monotonic()))
            except RejectedError:
                shed[client] += 1
        lats = {"victim": [], abuser_tag: []}
        for client, fs in futs.items():
            for fut, ts in fs:
                try:
                    fut.result(timeout=drain_timeout_s)
                except RejectedError:
                    shed[client] += 1
                    continue
                except Exception:
                    shed[client] += 1
                    continue
                lats[client].extend(_fut_latencies(fut, ts)[0])
        return {
            "victim_p99_s": _pct(lats["victim"], 0.99),
            "victim_completed": len(lats["victim"]),
            "victim_shed": shed["victim"],
            "abuser_completed": len(lats[abuser_tag]),
            "abuser_shed": shed[abuser_tag],
        }

    base_rps = quota_rps * 0.8
    flood_rps = base_rps if null else quota_rps * flood_factor
    _log(f"noisy-neighbor: victim {victim_rps:.1f} req/s (gold), abuser "
         f"polite {base_rps:.1f} / "
         + ("NULL (polite both arms)" if null
            else f"flood {flood_rps:.1f}")
         + f" req/s (bronze, quota {quota_rps:.1f}), {pairs} "
         f"order-alternated pairs x {duration:g}s")
    base_phases, drill_phases, deltas = [], [], []
    for pair in range(pairs):
        drill_first = bool(pair % 2)  # order-alternate within each pair
        order = ([flood_rps, base_rps] if drill_first
                 else [base_rps, flood_rps])
        a = phase(order[0])
        b = phase(order[1])
        drill, base = (a, b) if drill_first else (b, a)
        base_phases.append(base)
        drill_phases.append(drill)
        if base["victim_p99_s"] and drill["victim_p99_s"]:
            deltas.append(drill["victim_p99_s"] / base["victim_p99_s"]
                          - 1.0)
    med = lambda v: sorted(v)[len(v) // 2] if v else None
    ms = lambda v: None if v is None else round(v * 1e3, 3)
    p99_b = med([p["victim_p99_s"] for p in base_phases
                 if p["victim_p99_s"] is not None])
    p99_d = med([p["victim_p99_s"] for p in drill_phases
                 if p["victim_p99_s"] is not None])
    paired = med(deltas)
    unprotected = None
    if not null:
        # the contrast arm: the SAME flood with no client id — it bypasses
        # the quota and lands in the DEFAULT (victim's) class, which is
        # exactly what a fleet without admission control experiences
        _log("noisy-neighbor contrast: the same flood UNPROTECTED "
             "(no quota, victim's class)")
        unprotected = phase(flood_rps, abuser_tag="anon", abuser_cls=None)
    adm_stats = router.admission.stats()
    return {
        "classes": {n: c["weight"]
                    for n, c in adm_stats["classes"].items()},
        "abuser_quota_rps": round(quota_rps, 3),
        "flood_factor": flood_factor,
        "pairs": pairs,
        "null": null,
        "victim_rps": round(victim_rps, 3),
        "abuser_rps_baseline": round(base_rps, 3),
        "abuser_rps_drill": round(flood_rps, 3),
        "victim_p99_baseline_ms": ms(p99_b),
        "victim_p99_drill_ms": ms(p99_d),
        # the headline: paired MEDIAN victim p99 delta across the
        # order-alternated pairs (drift cancels; judge vs the --nn_null
        # floor)
        "victim_p99_delta_pct": (None if paired is None
                                 else round(100.0 * paired, 2)),
        "victim_completed": sum(p["victim_completed"]
                                for p in base_phases + drill_phases),
        "victim_shed": sum(p["victim_shed"]
                           for p in base_phases + drill_phases),
        "abuser_shed_baseline": sum(p["abuser_shed"]
                                    for p in base_phases),
        "abuser_shed_drill": sum(p["abuser_shed"] for p in drill_phases),
        "abuser_admitted_drill": sum(p["abuser_completed"]
                                     for p in drill_phases),
        "victim_p99_unprotected_ms": (
            None if unprotected is None
            else ms(unprotected["victim_p99_s"])),
        "victim_shed_unprotected": (
            None if unprotected is None
            else unprotected["victim_shed"]),
        "sheds_by_reason": adm_stats["shed"],
    }


class _GenerateLoad:
    """Open-loop generative traffic: streams launched at the offered rate
    on daemon threads (bounded concurrency; an arrival finding the pool
    full is SHED and counted — open-loop honesty, never self-throttling),
    each a `router.generate(session=...)` with a random prefix and a
    geometric continuation budget. Runs until `stop()`; aggregates the
    stream-level record."""

    def __init__(self, router, rps: float, prefix_lens: List[int],
                 mean_new: int, vocab: int, max_seq_len: int, seed: int,
                 arrival: str, burst: int, concurrency: int = 12,
                 client: Optional[str] = None):
        self.router = router
        self.rps = rps
        self.prefix_lens = prefix_lens
        self.mean_new = mean_new
        self.vocab = vocab
        self.max_seq_len = max_seq_len
        self.rng = np.random.default_rng(seed + 7)
        self.seed = seed
        self.arrival = arrival
        self.burst = burst
        self.client = client
        self._sem = threading.Semaphore(concurrency)
        self.concurrency = concurrency
        self._stop = threading.Event()
        self._lock = threading.Lock()
        self._walls: List[float] = []
        self._ttfts: List[float] = []
        self._itls: List[float] = []
        self._threads: List[threading.Thread] = []
        self.offered = self.completed = self.failed = self.shed = 0
        self.tokens = self.steps_window_tokens = 0
        self.followups = self.resumed = 0
        self.reroutes = self.spills = 0
        self._t0 = None
        self._launcher = threading.Thread(target=self._run,
                                          name="genload", daemon=True)

    def start(self) -> "_GenerateLoad":
        self._t0 = time.monotonic()
        self._launcher.start()
        return self

    def _stream(self, i: int, plen: int, max_new: int,
                followup: bool) -> None:
        try:
            prefix = [int(t) for t in
                      self.rng.integers(3, self.vocab, plen)]
            t0 = time.monotonic()
            # caller-clock frame stamps: TTFT is first-frame arrival, ITL
            # is the per-token inter-frame gap — the ground truth the
            # engine-side decode_ttft/itl histograms reconcile against
            frames = {"t_first": None, "t_prev": t0,
                      "itl_sum": 0.0, "itl_n": 0}

            def on_tokens(tokens, info, _f=frames):
                now = time.monotonic()
                if not tokens:
                    return
                if _f["t_first"] is None:
                    _f["t_first"] = now
                else:
                    _f["itl_sum"] += now - _f["t_prev"]
                    _f["itl_n"] += len(tokens)
                _f["t_prev"] = now

            res = self.router.generate(
                prefix, session=f"genload-{i}", max_new=max_new,
                temperature=GENERATE_TEMPERATURE, top_k=GENERATE_TOP_K,
                seed=self.seed, on_tokens=on_tokens, client=self.client)
            toks = res["tokens"]
            res2 = None
            if followup and toks and len(prefix) + len(toks) + 4 < self.max_seq_len:
                res2 = self.router.generate(
                    prefix + toks, session=f"genload-{i}", max_new=3,
                    temperature=GENERATE_TEMPERATURE, top_k=GENERATE_TOP_K,
                    seed=self.seed, client=self.client)
                toks = toks + res2["tokens"]
            wall = time.monotonic() - t0
            with self._lock:
                if frames["t_first"] is not None:
                    self._ttfts.append(frames["t_first"] - t0)
                if frames["itl_n"]:
                    self._itls.append(frames["itl_sum"] / frames["itl_n"])
                self.completed += 1
                self.tokens += len(toks)
                self.reroutes += res["reroutes"]
                self.spills += res["spills"]
                if res2 is not None:
                    self.followups += 1
                    self.resumed += 1 if res2["resumed"] else 0
                    self.reroutes += res2["reroutes"]
                    self.spills += res2["spills"]
                self._walls.append(wall)
        except Exception:
            with self._lock:
                self.failed += 1
        finally:
            self._sem.release()

    def _run(self) -> None:
        i = 0
        mean_gap = 1.0 / max(self.rps, 1e-6)
        while not self._stop.is_set():
            if self.arrival == "bursty":
                n, gap = self.burst, self.burst * mean_gap
            else:
                n, gap = 1, float(self.rng.exponential(mean_gap))
            for _ in range(n):
                if self._stop.is_set():
                    return
                self.offered += 1
                if not self._sem.acquire(blocking=False):
                    self.shed += 1
                    continue
                plen = int(self.rng.choice(self.prefix_lens))
                max_new = int(min(
                    self.rng.geometric(1.0 / max(self.mean_new, 1)),
                    self.max_seq_len - plen - 1))
                followup = self.rng.random() < 0.33
                t = threading.Thread(
                    target=self._stream, args=(i, plen, max(1, max_new),
                                               followup),
                    name=f"genload-{i}", daemon=True)
                self._threads.append(t)
                t.start()
                i += 1
            self._stop.wait(gap)

    def stop_and_record(self, timeout_s: float) -> Dict:
        self._stop.set()
        self._launcher.join(timeout=5)
        deadline = time.monotonic() + timeout_s
        for t in self._threads:
            t.join(timeout=max(0.0, deadline - time.monotonic()))
        total_s = time.monotonic() - self._t0
        with self._lock:
            walls = list(self._walls)
            return {
                "offered_streams": self.offered,
                "completed": self.completed,
                "failed": self.failed,
                "shed": self.shed,
                "tokens_total": self.tokens,
                "steps_per_s": (round(self.tokens / total_s, 3)
                                if total_s > 0 else None),
                "stream_p50_ms": _ms(_pct(walls, 0.5)),
                "stream_p95_ms": _ms(_pct(walls, 0.95)),
                "stream_p99_ms": _ms(_pct(walls, 0.99)),
                "followups": self.followups,
                "resumed": self.resumed,
                "reroutes": self.reroutes,
                "spills": self.spills,
                "mean_new": self.mean_new,
                "prefix_lens": self.prefix_lens,
                "concurrency": self.concurrency,
                # caller-clock token-level latency; the engine-side
                # goodput/flight fields are filled by the record assembly
                # (key set fixed by STREAM_KEYS either way)
                "stream": {
                    "ttft_p50_ms": _ms(_pct(self._ttfts, 0.5)),
                    "ttft_p95_ms": _ms(_pct(self._ttfts, 0.95)),
                    "itl_p50_ms": _ms(_pct(self._itls, 0.5)),
                    "itl_p95_ms": _ms(_pct(self._itls, 0.95)),
                    "streams_timed": len(self._ttfts),
                    "tokens_generated": None,
                    "tokens_delivered": None,
                    "tokens_wasted": None,
                    "goodput": None,
                    "idle_slot_rounds": None,
                    "idle_attributed": None,
                    "idle_attribution_frac": None,
                    "idle_causes": None,
                },
            }


def _ms(v: Optional[float]) -> Optional[float]:
    return None if v is None else round(v * 1e3, 3)


def _point_for_record(p: Dict) -> Dict:
    """Seconds → ms for the emitted record (fit_capacity reads the _s keys)."""
    out = {k: p[k] for k in ("offered_rps", "submitted", "completed", "shed",
                             "failed", "shed_rate", "achieved_rps", "breaker")}
    for q in ("p50", "p95", "p99"):
        v = p[f"{q}_s"]
        out[f"{q}_ms"] = None if v is None else round(v * 1e3, 3)
    for q in ("p50", "p99"):
        out[f"phase_{q}_ms"] = {
            k: round(v * 1e3, 4) for k, v in p[f"phase_{q}_s"].items()
        }
    return out


def main() -> None:
    parser = argparse.ArgumentParser(
        description="open-loop offered-load sweep + capacity model")
    parser.add_argument("--cpu", action="store_true",
                        help="pin to the CPU backend (ensure_cpu_only before "
                             "jax initializes) — the offline/tier-1 mode")
    parser.add_argument("--dry", action="store_true",
                        help="emit the record schema (one JSON line) without "
                             "touching any backend")
    parser.add_argument("--preset", choices=["auto", "tiny", "flagship"],
                        default="auto",
                        help="model size: auto = flagship on TPU, tiny "
                             "elsewhere (models/presets.py)")
    parser.add_argument("--arrival", choices=["poisson", "bursty"],
                        default="poisson",
                        help="arrival process: poisson = exponential gaps at "
                             "the offered rate; bursty = back-to-back bursts "
                             "of --burst at the same mean rate")
    parser.add_argument("--burst", type=int, default=8,
                        help="bursty mode: arrivals per burst")
    parser.add_argument("--duration_s", type=float, default=4.0,
                        help="offered-traffic window per sweep point")
    parser.add_argument("--rate_factors", default="0.25,0.5,0.75,1.0,1.5,2.5",
                        help="offered rates as fractions of the calibrated "
                             "closed-loop capacity (spans the knee on any "
                             "backend)")
    parser.add_argument("--rates", default=None,
                        help="explicit offered rates (requests/s, comma-"
                             "separated) — overrides --rate_factors")
    parser.add_argument("--quantize", choices=("none", "int8", "int4"),
                        default="none",
                        help="weight-only quantized serving for every "
                             "engine/generator this run builds (the fused "
                             "dequant-matmul weight stream under load; "
                             "process replicas get it via --quantize "
                             "passthrough)")
    parser.add_argument("--max_batch", type=int, default=8,
                        help="engine micro-batch cap")
    parser.add_argument("--queue_limit", type=int, default=64,
                        help="bounded queue (parts) — the load-shedding "
                             "mechanism the sweep provokes past the knee; "
                             "0 = unbounded (latency grows without shedding)")
    parser.add_argument("--deadline_s", type=float, default=None,
                        help="per-request deadline (optional second shedding "
                             "mechanism)")
    parser.add_argument("--slo_p99_ms", type=float, default=None,
                        help="SLO latency target for the capacity fit; "
                             "default: 5x the calibrated median latency")
    parser.add_argument("--slo_availability", type=float, default=0.999,
                        help="SLO availability target")
    parser.add_argument("--calibration_waves", type=int, default=3)
    parser.add_argument("--calibration_wave_size", type=int, default=24)
    parser.add_argument("--drain_timeout_s", type=float, default=120.0)
    parser.add_argument("--seed", type=int, default=0)
    fleet = parser.add_argument_group(
        "multi-replica fabric (perceiver_io_tpu.serving)")
    fleet.add_argument("--replicas", type=int, default=0,
                       help="run the sweep through a router over N replicas "
                            "(0 = the single engine, the historical mode)")
    fleet.add_argument("--replica_mode", choices=["inprocess", "process"],
                       default="inprocess",
                       help="inprocess = N engines behind LocalReplica shims "
                            "(fast, tier-1); process = real supervised "
                            "replica processes (the acceptance-drill mode)")
    fleet.add_argument("--transport", choices=["http", "uds", "shmem"],
                       default="http",
                       help="router→replica data plane for process fleets "
                            "(serving.transport): http = the portable "
                            "pooled-connection twin; uds = pipelined unix-"
                            "socket frames; shmem = shared-memory slot ring "
                            "with a uds control channel. With --trace_ab "
                            "the record gains a 'transport' block: a "
                            "paired-interleave http-vs-transport A/B over "
                            "the same live fleet (rpc_p50_speedup must be "
                            ">= 2 for uds/shmem at batch-1 small frames)")
    fleet.add_argument("--kill_replica_at", type=float, default=None,
                       metavar="FRAC",
                       help="chaos drill: at FRAC of --kill_point's offered "
                            "window, kill one replica (SIGKILL in process "
                            "mode — the supervisor restarts it; simulated "
                            "death + later revive inprocess). The fleet "
                            "block's lost_accepted must stay 0")
    fleet.add_argument("--kill_point", type=int, default=0,
                       help="sweep point index the kill fires in")
    fleet.add_argument("--revive_after_s", type=float, default=1.0,
                       help="inprocess mode: seconds the killed replica "
                            "stays dead before reviving (the supervisor-"
                            "restart stand-in)")
    dep = parser.add_argument_group(
        "continuous deployment ride-along (perceiver_io_tpu.deploy)")
    dep.add_argument("--publish_every_s", type=float, default=None,
                     metavar="S",
                     help="publish a (gate-passing) checkpoint every S "
                          "seconds DURING the sweep and hot-swap it through "
                          "the deployment loop; the record gains a 'deploy' "
                          "block (swaps/rejects/rollbacks + per-swap p99 "
                          "blip vs steady). Default: off")
    dep.add_argument("--blip_window_s", type=float, default=0.5,
                     help="half-width of the per-swap p99 attribution window")
    trc = parser.add_argument_group(
        "distributed tracing (perceiver_io_tpu.obs.reqtrace)")
    trc.add_argument("--events_jsonl", default=None,
                     help="configure the event log here for the whole run: "
                          "every request mints a TraceContext and records "
                          "spans at each hop — assemble with "
                          "tools/trace_assemble.py. Default: off")
    trc.add_argument("--trace_ab", action="store_true",
                     help="measure tracing overhead: same-process "
                          "INTERLEAVED traced/untraced closed-loop waves; "
                          "the record gains a 'trace' block "
                          "(overhead_pct must stay <= 2 on CPU)")
    trc.add_argument("--trace_ab_waves", type=int, default=6,
                     help="waves per arm of the A/B")
    ser = parser.add_argument_group(
        "metrics time-series + alerting (perceiver_io_tpu.obs.timeseries)")
    ser.add_argument("--series_jsonl", default=None, metavar="PATH",
                     help="ride-along: sample every registry instrument "
                          "into a bounded series store each "
                          "--series_interval_s during the sweep, persist "
                          "the samples here (rotating JSONL), and evaluate "
                          "context-default alert rules (queue-depth "
                          "threshold + shed-rate) over the windowed "
                          "series; the record gains an 'alerts' block "
                          "(fired/resolved counts)")
    ser.add_argument("--series_interval_s", type=float, default=0.5,
                     help="sampling + alert-evaluation cadence for the "
                          "ride-along (sweeps are short; serving defaults "
                          "to 1 s)")
    ser.add_argument("--series_ab", action="store_true",
                     help="measure sampler overhead: same-process "
                          "INTERLEAVED sampled/unsampled closed-loop waves "
                          "(the --trace_ab methodology); the record gains "
                          "a 'series_ab' block (overhead_pct must stay "
                          "<= 2 on CPU at the default cadence)")
    ser.add_argument("--ab_null", action="store_true",
                     help="null control for --series_ab: BOTH arms run "
                          "unsampled — measures the host noise floor the "
                          "overhead verdict is judged against")
    aut = parser.add_argument_group(
        "elastic autoscaling + admission "
        "(perceiver_io_tpu.serving.autoscale / .admission)")
    aut.add_argument("--schedule", choices=["step", "burst", "diurnal"],
                     default=None,
                     help="replace the rate sweep with a time-varying "
                          "offered-rate profile (per-segment rates as "
                          "fractions of the calibrated initial-fleet "
                          "capacity): step = low→peak→low, burst = "
                          "alternating, diurnal = one raised-cosine cycle. "
                          "The per-segment points ride the sweep array; "
                          "pair with --autoscale for the control-loop "
                          "verdict")
    aut.add_argument("--schedule_period_s", type=float, default=3.0,
                     help="seconds per schedule segment")
    aut.add_argument("--schedule_low", type=float, default=0.2,
                     help="low-rate factor of the calibrated capacity")
    aut.add_argument("--schedule_high", type=float, default=0.5,
                     help="peak-rate factor. The default sits ABOVE the "
                          "autoscaler's target utilization but BELOW the "
                          "initial fleet's knee: the control loop grows "
                          "the fleet on utilization pressure BEFORE "
                          "saturation, so p99 never leaves the service "
                          "floor (raise toward/past 1 for the "
                          "saturation-transient variant instead — p99 "
                          "then rides the reaction window)")
    aut.add_argument("--autoscale_target_util", type=float, default=0.4,
                     help="the policy's target utilization (scale up once "
                          "windowed demand / fleet capacity exceeds it; "
                          "scale down below 0.6x this). Deliberately low "
                          "default: pre-knee headroom sized so p99 stays "
                          "on the service floor THROUGH a scale-up "
                          "reaction window on this class of host — real "
                          "fleets with faster joins push it up")
    aut.add_argument("--autoscale", action="store_true",
                     help="run the Autoscaler over the fleet during the "
                          "sweep/schedule (requires --replicas >= 1): "
                          "spawn/drain-then-retire replicas from the "
                          "windowed fleet series, seeded by the calibrated "
                          "per-replica capacity; the record gains an "
                          "'autoscale' block (replica-seconds vs a static "
                          "peak fleet, lost_accepted must stay 0)")
    aut.add_argument("--min_replicas", type=int, default=1,
                     help="autoscale floor")
    aut.add_argument("--max_replicas", type=int, default=None,
                     help="autoscale ceiling (default: 2x --replicas)")
    aut.add_argument("--autoscale_interval_s", type=float, default=0.25,
                     help="control-loop tick cadence")
    aut.add_argument("--noisy_neighbor", action="store_true",
                     help="admission-control drill (requires --replicas "
                          ">= 1): gold victim + bronze abuser behind "
                          "per-client token-bucket quotas and WFQ; phase A "
                          "both polite, phase B the abuser floods. The "
                          "record gains an 'admission' block — the "
                          "victim's p99 must stay flat while the abuser's "
                          "class absorbs the shedding")
    aut.add_argument("--nn_quota_rps", type=float, default=None,
                     help="abuser token-bucket rate, also the victim's "
                          "offered rate (default: 10%% of the calibrated "
                          "capacity — low enough that the flood's "
                          "SUBMISSION overhead cannot itself saturate a "
                          "small host and masquerade as interference)")
    aut.add_argument("--nn_flood_factor", type=float, default=4.0,
                     help="drill-arm abuser rate as a multiple of its "
                          "quota")
    aut.add_argument("--nn_pairs", type=int, default=3,
                     help="order-alternated (polite, flood) sub-phase "
                          "pairs — the victim verdict is the paired "
                          "median p99 delta")
    aut.add_argument("--nn_null", action="store_true",
                     help="null control: the abuser stays polite in BOTH "
                          "arms — measures the drill's own noise floor "
                          "the isolation verdict is judged against")
    gen = parser.add_argument_group(
        "generative traffic class (task=generate)")
    gen.add_argument("--generate_rps", type=float, default=0.0,
                     help="offered generate-STREAM starts/s, running "
                          "CONCURRENTLY with the one-shot sweep (0 = off). "
                          "Each stream is a pinned session with a random "
                          "prefix and a geometric continuation budget — "
                          "the second, stateful, bursty class the r17 "
                          "autoscale/admission policies balance. Needs "
                          "--replicas >= 1 in inprocess mode")
    gen.add_argument("--generate_mean_new", type=int, default=16,
                     help="mean of the geometric continuation length")
    gen.add_argument("--generate_prefix_lens", default="6,12,24",
                     help="prefix lengths sampled uniformly per stream")
    gen.add_argument("--generate_chunk", type=int, default=4,
                     help="decode steps per chunked dispatch")
    gen.add_argument("--decode_batching", action="store_true",
                     help="serve the generate class through the continuous-"
                          "batching arena (ONE batched step dispatch per "
                          "chunk across all active streams) instead of "
                          "per-session chains; the generate record gains "
                          "slot-occupancy/steps-per-dispatch aggregates")
    gen.add_argument("--decode_slots", type=int, default=8,
                     help="decode batching: initial arena slots per "
                          "prefill width")
    args = parser.parse_args()

    if (args.autoscale or args.noisy_neighbor) and args.replicas < 1:
        parser.error("--autoscale/--noisy_neighbor need --replicas >= 1 "
                     "(the control loop lives at the router tier)")
    if args.transport != "http" and not args.dry and (
            args.replicas < 1 or args.replica_mode != "process"):
        parser.error("--transport uds/shmem needs --replicas >= 1 with "
                     "--replica_mode process (in-process LocalReplica shims "
                     "have no wire to put a transport on)")
    if args.generate_rps > 0 and (args.replicas < 1
                                  or args.replica_mode != "inprocess"):
        parser.error("--generate_rps needs --replicas >= 1 with "
                     "--replica_mode inprocess (process replicas serve "
                     "generation via `serving.replica --task generate`)")

    if args.dry:
        record = {
            "metric": "load_bench", "dry": True, "backend": None,
            "preset": args.preset, "arrival": args.arrival,
            "duration_s": args.duration_s, "schedule": args.schedule,
            "quantize": args.quantize,
            "point_keys": list(POINT_KEYS), "phase_keys": list(PHASE_KEYS),
            "fleet_keys": list(FLEET_KEYS), "deploy_keys": list(DEPLOY_KEYS),
            "trace_keys": list(TRACE_KEYS),
            "transport_keys": list(TRANSPORT_KEYS),
            "alert_keys": list(ALERT_KEYS),
            "series_ab_keys": list(SERIES_AB_KEYS),
            "autoscale_keys": list(AUTOSCALE_KEYS),
            "admission_keys": list(ADMISSION_KEYS),
            "generate_keys": list(GENERATE_KEYS),
            "stream_keys": list(STREAM_KEYS),
            "sweep": [], "capacity": None, "fleet": None, "deploy": None,
            "trace": None, "transport": None, "alerts": None,
            "series_ab": None, "autoscale": None, "admission": None,
            "generate": None,
        }
        emit_json_line(record)
        return

    if args.cpu:
        from perceiver_io_tpu.utils.platform import ensure_cpu_only

        ensure_cpu_only()
    from perceiver_io_tpu.aot import maybe_enable_cache_from_env

    maybe_enable_cache_from_env()  # PIT_COMPILE_CACHE opt-in (stderr only)
    import jax

    import perceiver_io_tpu.obs as obs
    from perceiver_io_tpu.inference import ServingEngine
    from perceiver_io_tpu.inference.engine import PHASES
    from perceiver_io_tpu.models.presets import flagship_mlm, tiny_mlm

    assert tuple(PHASES) == PHASE_KEYS, "load_bench PHASE_KEYS drifted"

    backend = probe_backend().backend
    tiny = args.preset == "tiny" or (args.preset == "auto" and backend != "tpu")
    _log(f"backend: {backend}; preset {'tiny' if tiny else 'flagship'}; "
         f"arrival {args.arrival}; duration {args.duration_s}s/point"
         + (f"; fleet {args.replicas}x{args.replica_mode}"
            if args.replicas else ""))

    vocab = 503 if tiny else 10003
    max_seq_len = 64 if tiny else 512
    reqs = _build_requests(max_seq_len, vocab, n=64, seed=args.seed)
    registry = obs.get_registry()

    def build_model_apply():
        build = tiny_mlm if tiny else flagship_mlm
        model = build(vocab_size=vocab, max_seq_len=max_seq_len)
        ids0 = np.zeros((1, max_seq_len), np.int32)
        variables = model.init(
            {"params": jax.random.key(0), "masking": jax.random.key(1)},
            ids0, ids0 == 0,
        )

        def gathered_apply(p, token_ids, pad_mask, pos):
            logits, _ = model.apply(
                {"params": p}, token_ids, pad_mask, masking=False,
                deterministic=True, positions=pos,
            )
            return logits

        return gathered_apply, variables["params"]

    queue_limit = args.queue_limit if args.queue_limit > 0 else None
    engine = router = sup = params = None
    admission = None
    spawn_replica = None  # in-process autoscale spawn hook
    local_replicas = []
    killed = {"name": None}
    if args.replicas > 0:
        from perceiver_io_tpu.serving import Router

        if args.noisy_neighbor:
            # gold carries the victim, bronze the abuser; the abuser's
            # token bucket is sized AFTER calibration (client_quotas is
            # consulted lazily on the client's first admit)
            from perceiver_io_tpu.serving import (
                AdmissionController,
                PriorityClass,
            )

            admission = AdmissionController(
                classes=[PriorityClass("gold", weight=4.0),
                         PriorityClass("bronze", weight=1.0)],
                default_class="gold", queue_limit=512,
                name="load_bench", registry=registry)
        if args.replica_mode == "process":
            from perceiver_io_tpu.serving import ReplicaSupervisor

            extra = ["--preset", "tiny" if tiny else "flagship",
                     "--max_batch", str(args.max_batch)]
            if args.quantize != "none":
                extra += ["--quantize", args.quantize]
            if args.cpu:
                extra.append("--cpu")
            if queue_limit is not None:
                extra += ["--queue_limit", str(queue_limit)]
            if args.deadline_s is not None:
                extra += ["--request_deadline_s", str(args.deadline_s)]
            sup = ReplicaSupervisor(count=args.replicas, extra_args=extra,
                                    cpu=args.cpu, registry=registry,
                                    transport=args.transport)
            clients = sup.start()
            _log(f"spawned {args.replicas} replica processes; waiting for "
                 "warm pools (engine_ready)")
            sup.wait_ready(timeout_s=600.0)
        else:
            from perceiver_io_tpu.serving import LocalReplica, ReplicaApp

            gathered_apply, params = build_model_apply()
            ar_model = ar_params = None
            if args.generate_rps > 0:
                # the stateful class shares one tiny AR tree; each replica
                # gets its own generator (its own session caches/programs)
                from perceiver_io_tpu.models.presets import tiny_ar

                ar_model = tiny_ar()
                ids0 = np.zeros((1, 64), np.int32)
                ar_params = ar_model.init(
                    {"params": jax.random.key(0)}, ids0, ids0 == 0,
                )["params"]
            made = [0]
            compile_cache = None
            if args.autoscale:
                # autoscale spawns share one AOT executable cache: the
                # first replica's compile persists, every later spawn
                # DESERIALIZES — the reaction window is process bring-up,
                # not a compile wall (the r10 cold-start property, and
                # what serve.py --replicas --compile_cache does for real
                # process fleets)
                import tempfile

                compile_cache = tempfile.mkdtemp(prefix="lb_autoscale_aot_")

            def spawn_replica(background: bool = False):
                i = made[0]
                made[0] += 1
                eng = ServingEngine(
                    gathered_apply, params, max_batch=args.max_batch,
                    quantize=(None if args.quantize == "none"
                              else args.quantize),
                    name=f"lb_r{i}", registry=registry,
                    queue_limit=queue_limit,
                    request_deadline_s=args.deadline_s,
                    compile_cache=compile_cache,
                )
                # autoscale spawns warm in the BACKGROUND: the newcomer
                # scrapes as JOINING until its program is live, exactly
                # like a supervised process replica
                eng.warmup(*reqs[0], background=background)
                generator = None
                if ar_model is not None:
                    from perceiver_io_tpu.inference.generate import (
                        ARGenerator,
                        SamplingConfig,
                    )

                    if args.decode_batching:
                        from perceiver_io_tpu.inference.batching import (
                            ContinuousBatcher,
                        )

                        generator = ContinuousBatcher(
                            ar_model, ar_params, max_seq_len=64,
                            chunk=args.generate_chunk,
                            slots=args.decode_slots,
                            quantize=(None if args.quantize == "none"
                                      else args.quantize),
                            name=f"lb_r{i}-gen", registry=registry)
                    else:
                        generator = ARGenerator(
                            ar_model, ar_params, max_seq_len=64,
                            chunk=args.generate_chunk,
                            quantize=(None if args.quantize == "none"
                                      else args.quantize),
                            name=f"lb_r{i}-gen", registry=registry)
                    warm_sampling = SamplingConfig(
                        temperature=GENERATE_TEMPERATURE,
                        top_k=GENERATE_TOP_K)
                    if background:
                        threading.Thread(
                            target=generator.warmup,
                            kwargs={"sampling": warm_sampling},
                            daemon=True).start()
                    else:
                        generator.warmup(sampling=warm_sampling)
                app = ReplicaApp({"infer": eng}, params, name=f"r{i}",
                                 registry=registry, generator=generator)
                rep = LocalReplica(app)
                local_replicas.append(rep)
                return rep

            for i in range(args.replicas):
                spawn_replica()
            clients = list(local_replicas)
            _log(f"warmed {args.replicas} in-process replicas")
        router = Router(clients, name="load_bench", registry=registry,
                        scrape_interval_s=0.1,
                        request_timeout_s=args.drain_timeout_s,
                        admission=admission)
        router.refresh()
        submit = lambda req: router.submit(*req)

        def breaker_state():
            states = [s["state"] for s in router.statuses().values()]
            return f"{sum(s == 'serving' for s in states)}/{len(states)} serving"

        def kill_hook():
            if args.replica_mode == "process":
                name = sup.clients()[0].name
                sup.kill(name)
            else:
                victim = local_replicas[0]
                victim.kill()
                name = victim.name
                # the supervisor-restart stand-in: revive after a bounded
                # outage (sessions stay lost, as a real restart loses them)
                threading.Timer(args.revive_after_s, victim.revive).start()
            killed["name"] = name
            _log(f"chaos: killed replica {name!r} "
                 f"({args.replica_mode} mode)")
    else:
        gathered_apply, params = build_model_apply()
        engine = ServingEngine(
            gathered_apply, params, max_batch=args.max_batch,
            quantize=None if args.quantize == "none" else args.quantize,
            name="load_bench", registry=registry,
            queue_limit=queue_limit,
            request_deadline_s=args.deadline_s,
        )
        engine.warmup(*reqs[0])
        _log(f"warmed {engine.num_programs} bucket programs")
        submit = lambda req: engine.submit(*req)
        breaker_state = lambda: (engine.breaker.state
                                 if engine.breaker is not None else "absent")

    cal_rps, cal_lat_s = _calibrate(
        submit, reqs, args.calibration_waves, args.calibration_wave_size)
    _log(f"calibrated closed-loop capacity ~{cal_rps:.1f} req/s, "
         f"median latency {cal_lat_s * 1e3:.2f} ms")

    trace_record = None
    if args.trace_ab:
        trace_record = _trace_ab(submit, reqs, args.trace_ab_waves,
                                 args.calibration_wave_size,
                                 args.drain_timeout_s)
        # the generate-class arm runs BEFORE gen_load starts (and before
        # the sweep): the paired waves own the router, so the tokens/s
        # ratio measures instrumentation, not contention
        trace_record["generate_ab"] = None
        if args.generate_rps > 0:
            trace_record["generate_ab"] = _generate_trace_ab(
                router, args.trace_ab_waves,
                max(4, args.calibration_wave_size // 4), args.seed)
        _log(f"trace A/B: {json.dumps(trace_record)}")
    transport_record = None
    if args.trace_ab and args.transport != "http":
        # the fleet serves BOTH data planes (the replica always keeps its
        # HTTP surface); the A/B owns the event log and its own two routers,
        # so it runs before the sweep touches the main router
        transport_record = _transport_ab(
            args.transport, sup.ports(), args.trace_ab_waves,
            args.calibration_wave_size, args.drain_timeout_s, reqs,
            registry, args.drain_timeout_s)
        _log(f"transport A/B: {json.dumps(transport_record)}")
    series_ab_record = None
    if args.series_ab:
        series_ab_record = _series_ab(
            submit, reqs, args.trace_ab_waves, args.calibration_wave_size,
            args.drain_timeout_s, args.series_interval_s, args.ab_null)
        _log(f"series A/B: {json.dumps(series_ab_record)}")
    if args.events_jsonl:
        # configured AFTER the A/B (which owns the global log while it
        # runs): the sweep itself records spans at every hop
        obs.configure_event_log(args.events_jsonl)

    # -- timeseries + alerting ride-along (--series_jsonl) -------------------
    sampler = alert_engine = None
    if args.series_jsonl:
        store = obs.SeriesStore()
        sampler = obs.Sampler(
            store=store, interval_s=args.series_interval_s,
            jsonl_path=args.series_jsonl, name="load_bench").start()
        qthresh = float(max(4, (queue_limit or 64) // 2))
        window = max(4 * args.series_interval_s, 2.0)
        common = dict(window_s=window, severity="warn",
                      resolve_threshold=qthresh / 2)
        if args.replicas > 0:
            # fleet gauges are per-replica labeled: a bare-name rule fires
            # per replica; sheds count at the router's admission edge
            rules = [
                obs.AlertRule(name="replica_queue_depth",
                              metric="fleet_replica_queue_depth",
                              threshold=qthresh, agg="max", **common),
                obs.AlertRule(name="router_shed_rate",
                              metric="router_shed_total", kind="rate",
                              threshold=0.0, window_s=window,
                              severity="warn"),
            ]
        else:
            rules = [
                obs.AlertRule(name="queue_depth",
                              metric=obs.series_key(
                                  "serving_queue_depth",
                                  {"engine": "load_bench"}),
                              threshold=qthresh, agg="max", **common),
                obs.AlertRule(name="shed_rate",
                              metric="serving_shed_total", kind="rate",
                              threshold=0.0, window_s=window,
                              severity="warn"),
            ]
        alert_engine = obs.AlertEngine(
            store, rules, interval_s=args.series_interval_s,
            name="load_bench").start()
        _log(f"series ride-along: sampling every "
             f"{args.series_interval_s:g}s -> {args.series_jsonl}; "
             f"{len(rules)} alert rule(s): "
             f"{', '.join(r.name for r in rules)}")

    # -- continuous-deployment ride-along (--publish_every_s) ----------------
    deploy_stack = None
    completion_sink = None
    if args.publish_every_s:
        import tempfile

        import perceiver_io_tpu.deploy as deploy_mod

        if params is None:
            # process-replica fleets never built the model locally; the
            # replicas init the SAME tree (preset + seed 0), so this copy is
            # a faithful incumbent for the gate
            gathered_apply, params = build_model_apply()
        publish_dir = tempfile.mkdtemp(prefix="load_bench_pub_")
        gate = deploy_mod.AdmissionGate(
            gathered_apply, reqs[0], params, quality_tol=0.5,
            registry=registry, name="load_bench")
        if router is not None:
            target = deploy_mod.RouterSwapTarget(router, bake_s=0.2,
                                                 poll_s=0.02)
        else:
            target = deploy_mod.EngineSwapTarget(engine, params, bake_s=0.2,
                                                 poll_s=0.02)
        swap_times: List[float] = []

        def _on_deployed(rec):
            if rec["action"] == "swapped":
                # install-start → bake-end interval (see swap_window_stats)
                swap_times.append((rec["t_swap"], rec["t_done"]))
            _log(f"deploy: step {rec['step']} {rec['action']}"
                 + (f" ({rec['reason']})" if rec.get("reason") else ""))

        deployer = deploy_mod.ModelDeployer(
            publish_dir, gate, target,
            poll_s=max(args.publish_every_s / 4, 0.05),
            registry=registry, name="load_bench",
            on_deployed=_on_deployed).start()
        stop_pub = threading.Event()
        pub_count = [0]

        def _publisher():
            import jax as _jax

            while not stop_pub.wait(args.publish_every_s):
                k = pub_count[0] + 1
                scale = 1.0 + 1e-3 * k  # same-regime tree: the gate passes
                tree = _jax.tree.map(
                    lambda x: x * scale
                    if np.issubdtype(np.asarray(x).dtype, np.floating)
                    else x, params)
                try:
                    deploy_mod.publish_params(publish_dir, 10 * k, tree,
                                              {"val_loss": 1.0})
                    pub_count[0] = k
                except Exception as e:
                    _log(f"deploy: publish failed {type(e).__name__}: {e}")

        pub_thread = threading.Thread(target=_publisher, daemon=True)
        pub_thread.start()
        completion_sink = []
        deploy_stack = (deploy_mod, deployer, stop_pub, pub_thread,
                        swap_times, pub_count)
        _log(f"deploy ride-along: publishing every {args.publish_every_s}s "
             f"into {publish_dir}")

    slo = obs.SLO(
        latency_target_s=(args.slo_p99_ms / 1e3 if args.slo_p99_ms
                          else max(5.0 * cal_lat_s, 1e-3)),
        availability_target=args.slo_availability,
        name="load_bench",
    )

    if args.schedule:
        factors = _schedule_factors(args.schedule, args.schedule_low,
                                    args.schedule_high)
        rates = [f * cal_rps for f in factors]
        durations = [args.schedule_period_s] * len(rates)
        _log(f"schedule {args.schedule}: "
             + ", ".join(f"{r:.0f}" for r in rates)
             + f" req/s x {args.schedule_period_s:g}s segments")
    elif args.rates:
        rates = [float(r) for r in args.rates.split(",")]
        durations = [args.duration_s] * len(rates)
    else:
        rates = [float(f) * cal_rps
                 for f in args.rate_factors.split(",")]
        durations = [args.duration_s] * len(rates)

    # -- the elastic control loop (--autoscale) ------------------------------
    auto = None
    if args.autoscale:
        from perceiver_io_tpu.serving import (
            Autoscaler,
            AutoscalePolicy,
            CallbackPool,
            SupervisorPool,
        )

        rps_per_replica = cal_rps / args.replicas
        max_reps = args.max_replicas or 2 * args.replicas
        tick = args.autoscale_interval_s
        policy = AutoscalePolicy(
            rps_per_replica=rps_per_replica,
            min_replicas=args.min_replicas, max_replicas=max_reps,
            target_utilization=args.autoscale_target_util,
            scale_down_utilization=0.6 * args.autoscale_target_util,
            window_s=max(4 * tick, 1.5),
            hold_up_s=2 * tick, hold_down_s=6 * tick,
            cooldown_up_s=2 * tick, cooldown_down_s=8 * tick,
            max_step=1, drain_timeout_s=args.drain_timeout_s)
        if sup is not None:
            pool = SupervisorPool(sup,
                                  drain_timeout_s=args.drain_timeout_s)
        else:
            def _retire_local(name):
                for rep in local_replicas:
                    if rep.name == name:
                        rep.app.close()

            pool = CallbackPool(lambda: spawn_replica(background=True),
                                _retire_local)
        auto = Autoscaler(router, pool, policy, interval_s=tick,
                          registry=registry).start()
        peak = [len(router.replicas())]
        stop_peak = threading.Event()

        def _watch_peak():
            while not stop_peak.wait(0.05):
                peak[0] = max(peak[0], len(router.replicas()))

        peak_thread = threading.Thread(target=_watch_peak, daemon=True)
        peak_thread.start()
        t_auto0 = time.monotonic()
        _log(f"autoscale: {rps_per_replica:.1f} req/s/replica fit, fleet "
             f"[{args.min_replicas}, {max_reps}], tick {tick:g}s")

    gen_load = None
    if args.generate_rps > 0:
        gen_load = _GenerateLoad(
            router, rps=args.generate_rps,
            prefix_lens=[int(p) for p in
                         args.generate_prefix_lens.split(",")],
            mean_new=args.generate_mean_new, vocab=503, max_seq_len=64,
            seed=args.seed, arrival=args.arrival, burst=args.burst,
            client="genload" if admission is not None else None).start()
        _log(f"generate class: {args.generate_rps:g} streams/s "
             f"({args.arrival}), mean_new {args.generate_mean_new}, "
             f"prefixes {args.generate_prefix_lens} — concurrent with the "
             "one-shot sweep")

    rng = np.random.default_rng(args.seed)
    points = []
    for idx, rate in enumerate(rates):
        on_frac = None
        if (args.kill_replica_at is not None and args.replicas > 0
                and idx == args.kill_point):
            on_frac = (args.kill_replica_at, kill_hook)
        point = _run_point(submit, breaker_state, reqs, rate,
                           durations[idx], args.arrival, args.burst, rng,
                           args.drain_timeout_s, on_frac=on_frac,
                           sink=completion_sink)
        points.append(point)
        ms = lambda v: f"{v * 1e3:8.2f}" if v is not None else "       —"
        _log(f"offered {point['offered_rps']:8.1f} req/s -> achieved "
             f"{point['achieved_rps']:8.1f}, p50 {ms(point['p50_s'])} "
             f"ms, p99 {ms(point['p99_s'])} ms, shed "
             f"{point['shed_rate']:.3f}, breaker {point['breaker']}")

    # a fully-shed point has no latency observations: it enters the fit as
    # an infinitely-slow (never-sustaining, never-SLO-meeting) point; a
    # sweep with NO completions anywhere has nothing to fit
    if any(p["p50_s"] is not None for p in points):
        inf = float("inf")
        capacity = obs.fit_capacity(
            [{"offered_rps": p["offered_rps"],
              "achieved_rps": p["achieved_rps"],
              "p50_s": inf if p["p50_s"] is None else p["p50_s"],
              "p99_s": inf if p["p99_s"] is None else p["p99_s"],
              "shed_rate": p["shed_rate"]} for p in points],
            slo=slo,
        )
        for k in ("service_floor_s", "p99_floor_s"):
            capacity[f"{k[:-2]}_ms"] = round(capacity.pop(k) * 1e3, 3)
        capacity["knee_rps"] = round(capacity["knee_rps"], 3)
        capacity["capacity_rps"] = round(capacity["capacity_rps"], 3)
        capacity["slo_sustainable_rps"] = round(
            capacity["slo_sustainable_rps"], 3)
        _log(f"capacity model: {json.dumps(capacity)}")
    else:
        capacity = None
        _log("capacity model: no point completed any request — nothing to fit")

    autoscale_record = None
    if auto is not None:
        total_s = time.monotonic() - t_auto0
        auto.close()
        stop_peak.set()
        peak_thread.join(timeout=2)
        st = auto.stats()
        # the verdict: replica-seconds actually spent vs a static fleet
        # sized for the observed peak over the same wall window
        static_rs = peak[0] * total_s
        saved = (100.0 * (1.0 - st["replica_seconds"] / static_rs)
                 if static_rs > 0 else None)
        p99s = [p["p99_s"] for p in points if p["p99_s"] is not None]
        p99_max = max(p99s) if p99s else None
        # lost = accepted work that FAILED (non-shed exceptions at the
        # point level: RejectedError/DeadlineExceeded deliveries are
        # taxonomy-honest SHEDS, not losses — the router's coarse failed
        # counter includes placement-exhaustion rejections under overload)
        lost = sum(int(p["failed"]) for p in points)
        autoscale_record = {
            "enabled": True,
            "schedule": args.schedule,
            "period_s": args.schedule_period_s if args.schedule else None,
            "low": args.schedule_low if args.schedule else None,
            "high": args.schedule_high if args.schedule else None,
            "rps_per_replica": round(cal_rps / args.replicas, 3),
            "min_replicas": args.min_replicas,
            "max_replicas": args.max_replicas or 2 * args.replicas,
            "initial_replicas": args.replicas,
            "peak_replicas": peak[0],
            "scale_ups": st["scale_ups"],
            "scale_downs": st["scale_downs"],
            "spawn_failures": st["spawn_failures"],
            "decisions": st["decisions"],
            "replica_seconds": st["replica_seconds"],
            "static_replica_seconds": round(static_rs, 3),
            "replica_seconds_saved_pct": (None if saved is None
                                          else round(saved, 2)),
            "p99_ms_max": (None if p99_max is None
                           else round(p99_max * 1e3, 3)),
            "slo_p99_ms": round(slo.latency_target_s * 1e3, 3),
            "p99_within_slo": (None if p99_max is None
                               else p99_max <= slo.latency_target_s),
            # accepted-but-never-delivered across every scale event —
            # drain-then-retire keeps this 0
            "lost_accepted": lost,
        }
        _log(f"autoscale: {json.dumps(autoscale_record)}")

    generate_record = None
    if gen_load is not None:
        # stopped AFTER the sweep (and the autoscale drill riding it): the
        # stateful class overlapped every segment
        generate_record = gen_load.stop_and_record(args.drain_timeout_s)
        # the arena's dispatch aggregates, summed over the fleet (occupancy
        # and steps/dispatch weighted by each replica's dispatch count) —
        # null-valued when the per-session engine served the class, so the
        # key set is identical either way (one-JSON-line contract)
        batched = [r.app.generator.stats() for r in local_replicas
                   if hasattr(getattr(r.app, "generator", None), "stats")]
        dispatches = sum(s["dispatches"] for s in batched)
        def _wmean(key):
            num = sum(s[key] * s["dispatches"] for s in batched
                      if s[key] is not None)
            return round(num / dispatches, 4) if dispatches else None
        generate_record.update({
            "decode_batched": bool(batched),
            "ar_decode_slot_occupancy": _wmean("slot_occupancy_mean"),
            "steps_per_dispatch": _wmean("steps_per_dispatch_mean"),
            "dispatches": dispatches if batched else None,
            "arena_slots": (sum(s["slots"] for s in batched)
                            if batched else None),
        })
        # engine-side goodput accounting (token_stats is shared by both
        # engine types) + the flight recorder's idle-slot-round attribution
        # (batched engines only)
        token_stats = [r.app.generator.token_stats() for r in local_replicas
                       if hasattr(getattr(r.app, "generator", None),
                                  "token_stats")]
        stream = generate_record["stream"]
        if token_stats:
            tok = {o: sum(t["tokens"][o] for t in token_stats)
                   for o in token_stats[0]["tokens"]}
            gen_n = tok["generated"]
            stream.update(
                tokens_generated=gen_n,
                tokens_delivered=tok["delivered"],
                tokens_wasted=sum(v for o, v in tok.items()
                                  if o.startswith("wasted_")),
                goodput=(round(tok["delivered"] / gen_n, 4)
                         if gen_n else None))
        flights = [s["flight"] for s in batched if "flight" in s]
        if flights:
            idle = sum(f["idle_slot_rounds"] for f in flights)
            attributed = sum(f["attributed"] for f in flights)
            causes: Dict[str, int] = {}
            for f in flights:
                for c, n in f["causes"].items():
                    causes[c] = causes.get(c, 0) + n
            stream.update(
                idle_slot_rounds=idle,
                idle_attributed=attributed,
                idle_attribution_frac=(round(attributed / idle, 4)
                                       if idle else 1.0),
                idle_causes=causes)
        _log(f"generate: {json.dumps(generate_record)}")

    admission_record = None
    if args.noisy_neighbor:
        quota = args.nn_quota_rps or 0.1 * cal_rps
        # the abuser's bucket is sized from the CALIBRATED capacity (the
        # controller consults client_quotas lazily, on the client's first
        # admit — no abuser traffic has flowed yet)
        admission.client_quotas["abuser"] = (quota, max(8.0, quota / 4.0))
        admission_record = _noisy_neighbor(
            router, reqs, rng, args.duration_s,
            victim_rps=quota, quota_rps=quota,
            flood_factor=args.nn_flood_factor,
            drain_timeout_s=args.drain_timeout_s,
            pairs=args.nn_pairs, null=args.nn_null)
        _log(f"admission: {json.dumps(admission_record)}")

    deploy_record = None
    if deploy_stack is not None:
        deploy_mod, deployer, stop_pub, pub_thread, swap_times, pub_count = \
            deploy_stack
        stop_pub.set()
        pub_thread.join(timeout=30)
        deadline = time.monotonic() + 60
        while (len(deployer.history) < pub_count[0]
               and time.monotonic() < deadline):
            time.sleep(0.05)
        deployer.stop(120)
        st = deployer.stats()
        blip = deploy_mod.swap_window_stats(
            completion_sink, swap_times, args.blip_window_s)
        ms = lambda v: None if v is None else round(v * 1e3, 3)
        deploy_record = {
            "publish_every_s": args.publish_every_s,
            "publishes": pub_count[0],
            "swaps": st["swaps"],
            "rejects": sum(st["rejected"].values()),
            "rollbacks": st["rollbacks"],
            "p99_steady_ms": ms(blip["p99_steady_s"]),
            "p99_swap_ms": ms(blip["p99_swap_s"]),
            "blip_ratio": (
                round(blip["p99_swap_s"] / blip["p99_steady_s"], 3)
                if blip["p99_swap_s"] and blip["p99_steady_s"] else None),
            "per_swap_p99_ms": [ms(v) for v in blip["per_swap_p99_s"]],
        }
        _log(f"deploy: {json.dumps(deploy_record)}")

    fleet_record = None
    if args.replicas > 0:
        stats = router.stats()
        if sup is not None:
            restarts = sum(sup.restarts(c.name) for c in sup.clients())
        else:
            restarts = 1 if killed["name"] is not None else 0
        fleet_record = {
            "replicas": args.replicas, "mode": args.replica_mode,
            "transport": args.transport,
            "killed": killed["name"],
            "kill_at_frac": args.kill_replica_at,
            "kill_point": (args.kill_point
                           if args.kill_replica_at is not None else None),
            "reroutes": int(stats["reroutes"]),
            "affinity_spills": int(stats["affinity_spills"]),
            # accepted-but-never-delivered — the chaos drill's verdict:
            # a healthy fabric keeps this 0 through a kill -9
            "lost_accepted": int(stats["failed"]),
            "restarts": int(restarts),
        }
        _log(f"fleet: {json.dumps(fleet_record)}")

    alerts_record = None
    if sampler is not None:
        # one final sample + evaluation tick so an episode that ended with
        # the sweep still resolves into the counters before teardown
        sampler.sample_once()
        alert_engine.evaluate()
        st = alert_engine.stats()
        alerts_record = {
            "rules": st["rules"],
            "fired": st["fired"],
            "resolved": st["resolved"],
            "firing_at_end": sum(len(v) for v in st["firing"].values()),
            "series_samples": sampler.sweeps,
            "series_jsonl": args.series_jsonl,
        }
        alert_engine.close()
        sampler.close()  # drains the series JSONL to disk
        _log(f"alerts: {json.dumps(alerts_record)}")

    if engine is not None:
        ratio = registry.gauge(
            "serving_phase_sum_ratio", labels={"engine": "load_bench"}).value
        ratio = round(ratio, 5)
    else:
        ratio = None  # phases stay replica-side in fleet mode
    record = {
        "metric": "load_bench", "dry": False, "backend": backend,
        "preset": "tiny" if tiny else "flagship",
        "arrival": args.arrival, "burst": args.burst,
        "duration_s": args.duration_s, "schedule": args.schedule,
        "max_batch": args.max_batch, "quantize": args.quantize,
        "queue_limit": args.queue_limit, "seed": args.seed,
        "seq_len": max_seq_len,
        "calibrated_rps": round(cal_rps, 3),
        "calibrated_latency_ms": round(cal_lat_s * 1e3, 3),
        "phase_sum_ratio": ratio,
        "sweep": [_point_for_record(p) for p in points],
        "capacity": capacity,
        "fleet": fleet_record,
        "deploy": deploy_record,
        "trace": trace_record,
        "transport": transport_record,
        "alerts": alerts_record,
        "series_ab": series_ab_record,
        "autoscale": autoscale_record,
        "admission": admission_record,
        "generate": generate_record,
    }
    if args.events_jsonl:
        obs.configure_event_log(None)  # flush + release the sweep's log
    if router is not None:
        router.drain(args.drain_timeout_s)
        router.close()
    for lr in local_replicas:
        lr.app.close()
    if sup is not None:
        sup.stop()
    if engine is not None:
        engine.close()
    emit_json_line(record)


if __name__ == "__main__":
    main()
