"""Import/export the reference's checkpoint artifacts (both directions).

Three entry points:

- ``ckpt``: a PyTorch-Lightning checkpoint (``.ckpt``, reference
  ``README.md:46-48``) → an Orbax checkpoint directory in this framework's
  run layout, directly usable as ``--mlm_checkpoint DIR`` (transfer: encoder
  grafted into a fresh classifier, reference ``train_seq_clf.py:18-24``),
  ``--clf_checkpoint DIR``, or ``restore_params(DIR, …)`` for inference;
- ``export``: the REVERSE — a run directory's checkpoint (this framework's
  Orbax layout) → a Lightning-style ``.ckpt`` the reference loads
  (``LitMLM.load_from_checkpoint`` / its ``--mlm_checkpoint``), so users can
  move trained weights back; round-trip exactness + strict
  ``load_state_dict`` into reference-shaped modules are pinned by
  ``tests/test_interop.py``;
- ``tokenizer``: an HF ``tokenizers`` JSON (e.g. the cached
  ``imdb-tokenizer-10003.json``) → verified loadable, optionally re-saved in
  either schema. Token ids index embedding rows, so a checkpoint moving in
  either direction needs this exact vocab.

Usage::

    python tools/import_reference.py ckpt  epoch=198-val_loss=4.619.ckpt -o runs/imported-mlm
    python tools/import_reference.py ckpt  model.ckpt -o out/ --encoder-only
    python tools/import_reference.py export logs/mlm/version_0/checkpoints -o exported.ckpt
    python tools/import_reference.py tokenizer imdb-tokenizer-10003.json -o .cache/imdb-tokenizer-10003.json
"""

from __future__ import annotations

import argparse
import json
import sys


def _import_ckpt(args: argparse.Namespace) -> None:
    from perceiver_io_tpu.interop import (
        export_orbax_checkpoint,
        import_lightning_checkpoint,
    )

    params, hparams = import_lightning_checkpoint(
        args.checkpoint, encoder_only=args.encoder_only,
        allow_unsafe_pickle=args.unsafe_load,
    )
    import jax

    n_leaves = len(jax.tree.leaves(params))
    n_params = sum(leaf.size for leaf in jax.tree.leaves(params))
    export_orbax_checkpoint(params, args.out, hparams=hparams or None)
    print(
        f"imported {args.checkpoint} -> {args.out}: "
        f"{n_leaves} arrays, {n_params:,} parameters"
        + (" (encoder subtree only)" if args.encoder_only else ""), file=sys.stderr)
    if hparams:
        shape_keys = sorted(
            k for k in hparams
            if k.startswith(("num_", "vocab_", "max_seq", "dropout"))
        )
        print("hparams:", {k: hparams[k] for k in shape_keys}, file=sys.stderr)


def _export_ckpt(args: argparse.Namespace) -> None:
    from perceiver_io_tpu.interop import export_lightning_checkpoint
    from perceiver_io_tpu.training.checkpoint import (
        load_hparams,
        restore_raw_params,
    )

    params, step = restore_raw_params(args.checkpoint_dir)
    hparams = {}
    try:
        hparams = load_hparams(args.checkpoint_dir)
    except FileNotFoundError:
        pass
    export_lightning_checkpoint(
        params, args.out, hparams=hparams or None, layout=args.layout,
        global_step=step,
    )
    import jax

    n_params = sum(leaf.size for leaf in jax.tree.leaves(params))
    print(
        f"exported {args.checkpoint_dir} (step {step}) -> {args.out}: "
        f"{n_params:,} parameters as a reference-loadable Lightning .ckpt "
        f"({args.layout} layout)", file=sys.stderr)


def _import_tokenizer(args: argparse.Namespace) -> None:
    from perceiver_io_tpu.data.tokenizer import WordPieceTokenizer

    tok = WordPieceTokenizer.from_file(args.tokenizer)
    print(
        f"loaded {args.tokenizer}: vocab {tok.get_vocab_size()}, "
        f"replacements {tok.replacements}", file=sys.stderr)
    if args.out:
        tok.save(args.out, format=args.format)
        print(f"saved -> {args.out} ({args.format} schema)", file=sys.stderr)


def main(argv=None) -> None:
    parser = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    sub = parser.add_subparsers(dest="cmd", required=True)

    p_ckpt = sub.add_parser("ckpt", help="import a Lightning .ckpt")
    p_ckpt.add_argument("checkpoint")
    p_ckpt.add_argument("-o", "--out", required=True,
                        help="Orbax checkpoint directory to write")
    p_ckpt.add_argument("--encoder-only", action="store_true",
                        help="import only the encoder subtree (transfer)")
    p_ckpt.add_argument("--unsafe_load", action="store_true",
                        help="fall back to torch's unrestricted pickle loader "
                             "when the safe weights-only loader rejects the "
                             "file (executes code embedded in the artifact — "
                             "only for checkpoints you trust)")
    p_ckpt.set_defaults(fn=_import_ckpt)

    p_exp = sub.add_parser(
        "export", help="export a checkpoint dir as a reference .ckpt")
    p_exp.add_argument("checkpoint_dir",
                       help="this framework's checkpoints/ dir (run layout)")
    p_exp.add_argument("-o", "--out", required=True,
                       help="Lightning .ckpt file to write")
    p_exp.add_argument("--layout", choices=("mlm", "classifier"),
                       default="mlm",
                       help="reference model whose key space to emit: "
                            "PerceiverMLM named children ('mlm') or the "
                            "PerceiverIO Sequential ('classifier')")
    p_exp.set_defaults(fn=_export_ckpt)

    p_tok = sub.add_parser("tokenizer", help="import/convert an HF tokenizers JSON")
    p_tok.add_argument("tokenizer")
    p_tok.add_argument("-o", "--out", default=None,
                       help="optionally re-save the tokenizer here")
    p_tok.add_argument("--format", choices=("native", "hf"), default="native")
    p_tok.set_defaults(fn=_import_tokenizer)

    args = parser.parse_args(argv)
    args.fn(args)


if __name__ == "__main__":
    main()
