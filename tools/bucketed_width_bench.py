"""Measure the bucketed-width text-batch win (VERDICT r2 item 8).

The reference pads each batch to its longest sequence (reference
``data/imdb.py:56-57`` ``enable_padding``), so short batches cost less than
512 tokens; this framework's static shapes pad everything to ``max_seq_len``.
The SPMD-safe middle ground is width buckets + length-sorted windows
(``Collator(bucket_widths=...)`` + ``DataLoader(sort_key=..., sort_window=``).

Method (tunnel-robust): the win = Σ_w share(w) · step_time(w), with
- share(w): the fraction of an epoch's batches landing in each width bucket,
  counted by running the REAL data module (collator + window-sorted loader)
  over an IMDB-length-realistic corpus (log-normal word counts fit to the
  published IMDB profile: mean ≈ 230 words, median ≈ 175, ~20% truncated at
  512 wordpieces) — the real aclImdb tree is used instead when present;
- step_time(w): device-trace-measured train-step time compiled at each width
  (flagship MLM config, fused head), immune to tunnel noise.

Prints per-bucket shares + device times and the bucketed-vs-static epoch
time ratio. Usage: ``timeout 900 python tools/bucketed_width_bench.py``.
"""

from __future__ import annotations

import os
import sys
from collections import Counter

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from perceiver_io_tpu.utils.platform import probe_backend

import numpy as np

BUCKETS = [256, 384]  # + the 512 cap appended by the Collator
SEQ_CAP = 512
BATCH = 64
VOCAB = 10003


def realistic_corpus(n: int, seed: int = 0):
    """Log-normal review lengths matching the published IMDB profile."""
    from perceiver_io_tpu.data.imdb import (
        _NEGATIVE_WORDS,
        _NEUTRAL_WORDS,
        _POSITIVE_WORDS,
    )

    rng = np.random.default_rng(seed)
    words = np.asarray(_POSITIVE_WORDS + _NEGATIVE_WORDS + _NEUTRAL_WORDS)
    lengths = np.clip(
        rng.lognormal(mean=np.log(175), sigma=0.72, size=n), 15, 2500
    ).astype(int)
    texts = [" ".join(rng.choice(words, size=k)) for k in lengths]
    labels = [int(rng.integers(0, 2)) for _ in range(n)]
    return texts, labels


def batch_width_shares(root: str) -> dict:
    """share(width) over one epoch of the bucketed module."""
    from perceiver_io_tpu.data import imdb as imdb_mod
    from perceiver_io_tpu.data.imdb import IMDBDataModule

    have_real = os.path.isdir(
        os.path.join(root, "IMDB", "aclImdb", "train")
    )
    dm = IMDBDataModule(
        root=root, max_seq_len=SEQ_CAP, vocab_size=VOCAB, batch_size=BATCH,
        synthetic=not have_real, synthetic_size=4096,
        bucket_widths=BUCKETS, length_sort_window=8,
    )
    if not have_real:
        # swap in the length-realistic generator (the stock synthetic corpus
        # is uniform 20-120 words — far shorter than IMDB)
        dm._train_texts = lambda: realistic_corpus(4096)  # type: ignore
    dm.prepare_data()
    dm.setup()
    counts: Counter = Counter()
    for b in dm.train_dataloader():
        counts[b["token_ids"].shape[1]] += 1
    total = sum(counts.values())
    return {w: c / total for w, c in sorted(counts.items())}


def device_step_ms(width: int) -> float:
    import jax
    import jax.numpy as jnp

    from perceiver_io_tpu.models.presets import flagship_mlm
    from perceiver_io_tpu.training import (
        OptimizerConfig,
        TrainState,
        make_mlm_steps,
        make_optimizer,
        mlm_gather_capacity,
    )
    from perceiver_io_tpu.utils.benchmarking import (
        time_train_step,
        time_train_step_device,
    )

    model = flagship_mlm(
        vocab_size=VOCAB, max_seq_len=SEQ_CAP, num_latents=256,
        num_channels=64, dtype=jnp.bfloat16, attn_impl="xla",
    )
    rng = np.random.default_rng(0)
    batch = {
        "token_ids": jnp.asarray(
            rng.integers(3, VOCAB, (BATCH, width)).astype(np.int32)),
        "pad_mask": jnp.zeros((BATCH, width), bool),
    }
    full = {
        "token_ids": jnp.asarray(
            rng.integers(3, VOCAB, (BATCH, SEQ_CAP)).astype(np.int32)),
        "pad_mask": jnp.zeros((BATCH, SEQ_CAP), bool),
    }
    variables = model.init(
        {"params": jax.random.key(0), "masking": jax.random.key(1)},
        full["token_ids"], full["pad_mask"],
    )
    tx, sched = make_optimizer(OptimizerConfig(learning_rate=1e-3))
    state = TrainState.create(variables["params"], tx, jax.random.key(2))
    head = "pallas" if probe_backend().backend == "tpu" else False
    train_step, _, _ = make_mlm_steps(
        model, sched, loss_gather_capacity=mlm_gather_capacity(SEQ_CAP),
        fused_head=head,
    )
    try:
        seconds, _, _ = time_train_step_device(train_step, state, batch, 15)
    except Exception:
        seconds, _ = time_train_step(train_step, state, batch, 10, windows=3)
    return seconds * 1e3


def eval_width_shares(root: str) -> dict:
    """share(width) over the EVAL split under the r5 eval width oracle
    (``val_dataloader``: sort_window=0, widths from the val token-length
    table — the reference's pad-to-longest eval behavior, SPMD-safe)."""
    from perceiver_io_tpu.data.imdb import IMDBDataModule

    have_real = os.path.isdir(os.path.join(root, "IMDB", "aclImdb", "train"))
    dm = IMDBDataModule(
        root=root, max_seq_len=SEQ_CAP, vocab_size=VOCAB, batch_size=BATCH,
        synthetic=not have_real, synthetic_size=4096,
        bucket_widths=BUCKETS, length_sort_window=8,
    )
    if not have_real:
        dm._train_texts = lambda: realistic_corpus(4096)  # type: ignore
        dm._valid_texts = lambda: realistic_corpus(4096, seed=3)  # type: ignore
    dm.prepare_data()
    dm.setup()
    counts: Counter = Counter()
    for b in dm.val_dataloader():
        counts[b["token_ids"].shape[1]] += 1
    total = sum(counts.values())
    return {w: c / total for w, c in sorted(counts.items())}


def device_eval_step_ms(width: int) -> float:
    """Device-trace eval (forward-only) step time at a width."""
    import tempfile

    import jax
    import jax.numpy as jnp

    from perceiver_io_tpu.models.presets import flagship_mlm
    from perceiver_io_tpu.training import (
        OptimizerConfig,
        TrainState,
        make_mlm_steps,
        make_optimizer,
        mlm_gather_capacity,
    )
    from perceiver_io_tpu.utils import xplane

    model = flagship_mlm(
        vocab_size=VOCAB, max_seq_len=SEQ_CAP, num_latents=256,
        num_channels=64, dtype=jnp.bfloat16, attn_impl="xla",
    )
    rng = np.random.default_rng(0)
    batch = {
        "token_ids": jnp.asarray(
            rng.integers(3, VOCAB, (BATCH, width)).astype(np.int32)),
        "pad_mask": jnp.zeros((BATCH, width), bool),
    }
    full = {
        "token_ids": jnp.asarray(
            rng.integers(3, VOCAB, (BATCH, SEQ_CAP)).astype(np.int32)),
        "pad_mask": jnp.zeros((BATCH, SEQ_CAP), bool),
    }
    variables = model.init(
        {"params": jax.random.key(0), "masking": jax.random.key(1)},
        full["token_ids"], full["pad_mask"],
    )
    tx, _ = make_optimizer(OptimizerConfig(learning_rate=1e-3))
    state = TrainState.create(variables["params"], tx, jax.random.key(2))
    head = "pallas" if probe_backend().backend == "tpu" else False
    _, eval_step, _ = make_mlm_steps(
        model, loss_gather_capacity=mlm_gather_capacity(SEQ_CAP),
        fused_head=head,
    )
    jitted = jax.jit(eval_step)
    key = jax.random.key(9)
    float(jitted(state, batch, key)["loss"])  # compile
    td = tempfile.mkdtemp(prefix=f"evalw{width}_")
    with jax.profiler.trace(td):
        for i in range(12):
            with jax.profiler.StepTraceAnnotation("e", step_num=i):
                m = jitted(state, batch, key)
        float(m["loss"])
    sec, _ = xplane.device_step_seconds(td, skip_first=2)
    return sec * 1e3


def eval_main() -> None:
    shares = eval_width_shares(os.environ.get("PIT_ROOT", ".cache"))
    print("eval bucket shares (r5 width oracle, order preserved):",
          {w: f"{s:.1%}" for w, s in shares.items()}, file=sys.stderr)
    times = {w: device_eval_step_ms(w) for w in sorted(set(shares) | {SEQ_CAP})}
    for w, ms in times.items():
        print(f"  width {w}: {ms:.3f} ms/eval-step (device)", file=sys.stderr)
    bucketed = sum(shares[w] * times[w] for w in shares)
    static = times[SEQ_CAP]
    print(
        f"eval cost: bucketed {bucketed:.3f} ms/step avg vs static "
        f"{static:.3f} -> {static / bucketed:.3f}x "
        f"({(static / bucketed - 1) * 100:+.1f}% eval throughput)", file=sys.stderr)


def main() -> None:
    if "--eval" in sys.argv:
        eval_main()
        return
    shares = batch_width_shares(os.environ.get("PIT_ROOT", ".cache"))
    print("bucket shares over one epoch:",
          {w: f"{s:.1%}" for w, s in shares.items()}, file=sys.stderr)

    times = {w: device_step_ms(w) for w in sorted(set(shares) | {SEQ_CAP})}
    for w, ms in times.items():
        print(f"  width {w}: {ms:.3f} ms/step (device)", file=sys.stderr)

    bucketed = sum(shares[w] * times[w] for w in shares)
    static = times[SEQ_CAP]
    print(
        f"epoch cost: bucketed {bucketed:.3f} ms/step avg vs static "
        f"{static:.3f} -> {static / bucketed:.3f}x "
        f"({(static / bucketed - 1) * 100:+.1f}% throughput)", file=sys.stderr)


if __name__ == "__main__":
    main()
