"""Per-round hardware kernel smoke: compile + parity-check EVERY Pallas path
at guard-boundary block geometries on the real chip (VERDICT r3 item 5).

The block-size tiers in ``ops/pallas_attention.py`` (``_auto_kv_block``, the
q-block bump) and the flash-CE row-block rule encode hardware sweeps with
measured scoped-VMEM OOM boundaries. CI exercises the kernels in interpret
mode on CPU, which can NOT catch a Mosaic/compiler upgrade moving the ~16 MB
scoped-VMEM boundary — that failure mode is a remote-compile error only the
real chip produces. This tool compiles and parity-checks each path at the
geometries sitting on those guard boundaries, so the measured tiers are
re-validated every round instead of only when the sweep tools are re-run by
hand.

Run directly (``timeout 900 python tools/kernel_smoke.py [--out FILE]``) —
prints ONE JSON line and exits non-zero on any failure — or let ``bench.py``
invoke it as a subprocess (it writes ``KERNELSMOKE.json`` at the repo root
each bench run; ``PIT_SKIP_KERNEL_SMOKE=1`` skips).

Covered paths and what each geometry pins:

- attention fwd + BOTH backward kernels (dq and dkv) at: the d<=64
  wide-stream tier (kv 2048) at long S; the d<=128 tier (kv 1024); the
  full-2048-KV flow-self shape; a deep-head d=512 shape sitting exactly ON
  the q-bump s_blk*d guard (must resolve to the safe 512 default); a
  lane-unaligned awkward-S shape (the pad-to-block path).
- flash-CE fwd + both backward kernels (dx and dw/db) at the flagship
  exact-divisor row count and at the 131k-context gathered row count
  39328 = 32*1229 (no aligned divisor above 32 — the row-PADDING rule that
  fixed the r3 regression).
- the sequence-parallel shard_map path compiled on the real chip (1-device
  seq axis — the collective merge compiles and matches; multi-device
  equivalence is CI's job on the 8-device CPU mesh).
- the weight-only int8 serving path (`perceiver_io_tpu.quant`): in-program
  dequant (int8 values × f32 per-channel scales → bf16) feeding a matmul,
  parity-checked against the f32 oracle.
- the fused dequant-matmul kernel (``ops/pallas_matmul``) at the flagship
  vocab-head shape (int8), a grouped-int4 MLP shape (bk pinned to the
  group), and an all-axes-unaligned f32 shape (the pad/slice path) — each
  vs the XLA-dequant oracle over identical quantized values.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from perceiver_io_tpu.utils.jsonline import emit_json_line
from perceiver_io_tpu.utils.platform import probe_backend

import numpy as np


def _attention_case(b, t, s, h, d, seed=0, causal_offset=None):
    import jax
    import jax.numpy as jnp

    from perceiver_io_tpu.ops.pallas_attention import fused_attention

    rng = np.random.default_rng(seed)
    q = jnp.asarray(rng.normal(0, 1, (b, t, h, d)), jnp.bfloat16)
    k = jnp.asarray(rng.normal(0, 1, (b, s, h, d)), jnp.bfloat16)
    v = jnp.asarray(rng.normal(0, 1, (b, s, h, d)), jnp.bfloat16)

    def ref_loss(q, k, v):
        logits = jnp.einsum(
            "bthd,bshd->bhts", q * (d ** -0.5), k,
            preferred_element_type=jnp.float32,
        )
        if causal_offset is not None:
            from perceiver_io_tpu.ops.masking import causal_mask

            logits = jnp.where(
                causal_mask(t, s, causal_offset)[None, None],
                jnp.finfo(jnp.float32).min, logits)
        probs = jax.nn.softmax(logits, axis=-1).astype(v.dtype)
        out = jnp.einsum("bhts,bshd->bthd", probs, v)
        return jnp.sum(out.astype(jnp.float32) ** 2)

    def ker_loss(q, k, v):
        out = fused_attention(q, k, v, causal_offset=causal_offset)
        return jnp.sum(out.astype(jnp.float32) ** 2)

    ref = jax.jit(jax.value_and_grad(ref_loss, argnums=(0, 1, 2)))(q, k, v)
    got = jax.jit(jax.value_and_grad(ker_loss, argnums=(0, 1, 2)))(q, k, v)
    _assert_close("loss", got[0], ref[0])
    for name, g, r in zip(("dq", "dk", "dv"), got[1], ref[1]):
        _assert_close(name, g, r)


def _assert_close(name, got, ref, rtol=0.05):
    got = np.asarray(got, np.float32)
    ref = np.asarray(ref, np.float32)
    scale = float(np.max(np.abs(ref))) or 1.0
    err = float(np.max(np.abs(got - ref))) / scale
    if not np.isfinite(got).all():
        raise AssertionError(f"{name}: non-finite values")
    if err > rtol:
        raise AssertionError(f"{name}: max rel-to-peak error {err:.3g} > {rtol}")


def _ce_case(rows, c, vocab, seed=0):
    import jax
    import jax.numpy as jnp

    from perceiver_io_tpu.ops.pallas_ce import pallas_linear_ce_integer

    rng = np.random.default_rng(seed)
    x = jnp.asarray(rng.normal(0, 1, (rows, c)), jnp.bfloat16)
    w = jnp.asarray(rng.normal(0, 0.02, (c, vocab)), jnp.bfloat16)
    bias = jnp.asarray(rng.normal(0, 0.02, (vocab,)), jnp.float32)
    labels = jnp.asarray(rng.integers(0, vocab, (rows,)).astype(np.int32))

    def ref_loss(x, w, bias):
        logits = (x.astype(jnp.float32) @ w.astype(jnp.float32)) + bias
        lse = jax.scipy.special.logsumexp(logits, axis=-1)
        picked = jnp.take_along_axis(logits, labels[:, None], axis=-1)[:, 0]
        return jnp.sum(lse - picked)

    def ker_loss(x, w, bias):
        return jnp.sum(pallas_linear_ce_integer(x, w, bias, labels))

    ref = jax.jit(jax.value_and_grad(ref_loss, argnums=(0, 1, 2)))(x, w, bias)
    got = jax.jit(jax.value_and_grad(ker_loss, argnums=(0, 1, 2)))(x, w, bias)
    _assert_close("loss", got[0], ref[0])
    for name, g, r in zip(("dx", "dw", "db"), got[1], ref[1]):
        _assert_close(name, g, r)


def _quant_case():
    """int8w dequant-inside-jit parity on the real compiler: quantize a
    small kernel tree, run the bf16 matmul over the in-program dequant, and
    check against the f32 oracle — pins that the convert*scale lowering
    stays numerically sane as the compiler moves (the serving engines'
    weight-only path, `perceiver_io_tpu.quant`)."""
    import jax
    import jax.numpy as jnp

    from perceiver_io_tpu.quant import dequantize_tree, quantize_tree

    rng = np.random.default_rng(0)
    params = {
        "dense": {
            "kernel": rng.normal(0, 1, (256, 512)).astype(np.float32),
            "bias": rng.normal(0, 0.02, (512,)).astype(np.float32),
        }
    }
    x = jnp.asarray(rng.normal(0, 1, (64, 256)), jnp.bfloat16)

    def apply_fn(p, x):
        d = p["dense"]
        return x @ d["kernel"].astype(x.dtype) + d["bias"].astype(x.dtype)

    ref = apply_fn(params, x)
    qp = quantize_tree(params, compute_dtype="bfloat16")

    got = jax.jit(lambda q, x: apply_fn(dequantize_tree(q), x))(qp, x)
    _assert_close("int8w-matmul", got, ref)


def _qmm_case(m, k, n, bits=8, group_size=None, compute_dtype="bfloat16",
              rtol=0.02, seed=0):
    """Fused dequant-matmul kernel (ops/pallas_matmul) vs the XLA-dequant
    oracle over the SAME quantized values — any difference is purely
    kernel-vs-XLA, so the bound is tight. Pins that the int8/int4
    convert×scale-in-VMEM lowering and the block/padding resolution stay
    sane as Mosaic moves (the r3 lesson: scoped-VMEM boundaries only
    surface on the real compiler)."""
    import jax.numpy as jnp

    from perceiver_io_tpu.ops.pallas_matmul import quantized_matmul
    from perceiver_io_tpu.quant.int8 import QKernel, quantize_array

    rng = np.random.default_rng(seed)
    w = rng.normal(0, 0.02, (k, n)).astype(np.float32)
    x = jnp.asarray(rng.normal(0, 1, (m, k)), jnp.dtype(compute_dtype))
    q, scale = quantize_array(w, bits=bits, group_size=group_size)
    store = jnp.int8 if bits == 8 else jnp.int4
    qk = QKernel(jnp.asarray(q, store), jnp.asarray(scale), compute_dtype)

    got = quantized_matmul(x, qk, impl="pallas")
    ref = (x.astype(qk.compute_dtype) @ qk.dequantize()).astype(x.dtype)
    _assert_close(f"qmm-int{bits}", got, ref, rtol=rtol)


def _sp_case():
    import jax
    import jax.numpy as jnp

    from perceiver_io_tpu.ops.pallas_attention import (
        fused_attention,
        seq_parallel_fused_attention,
    )
    from perceiver_io_tpu.parallel import make_mesh

    rng = np.random.default_rng(0)
    q = jnp.asarray(rng.normal(0, 1, (2, 256, 4, 16)), jnp.bfloat16)
    k = jnp.asarray(rng.normal(0, 1, (2, 4096, 4, 16)), jnp.bfloat16)
    v = jnp.asarray(rng.normal(0, 1, (2, 4096, 4, 16)), jnp.bfloat16)
    mesh = make_mesh(dp=1, tp=1, sp=probe_backend().device_count)

    def sp_loss(q, k, v):
        out = seq_parallel_fused_attention(q, k, v, mesh=mesh, axis="seq")
        return jnp.sum(out.astype(jnp.float32) ** 2)

    def ref_loss(q, k, v):
        out = fused_attention(q, k, v)
        return jnp.sum(out.astype(jnp.float32) ** 2)

    ref = jax.jit(jax.value_and_grad(ref_loss, argnums=(0, 1, 2)))(q, k, v)
    got = jax.jit(jax.value_and_grad(sp_loss, argnums=(0, 1, 2)))(q, k, v)
    _assert_close("loss", got[0], ref[0])
    for name, g, r in zip(("dq", "dk", "dv"), got[1], ref[1]):
        _assert_close(name, g, r)


CASES = {
    # _auto_kv_block d<=64 tier at long S: kv resolves to 2048
    "attn-32k-d16": lambda: _attention_case(1, 256, 32768, 4, 16),
    # d<=128 tier: kv resolves to 1024 (the in-8h family, shrunk for runtime)
    "attn-8k-d128": lambda: _attention_case(2, 512, 8192, 8, 128),
    # full-2048 KV stream at d=64 (the flow-self win) + q-bump interplay
    "attn-flowself-d64": lambda: _attention_case(2, 2048, 2048, 8, 64),
    # deep head exactly ON the q-bump s_blk*d guard: must resolve to the
    # safe 512 default, NOT the measured-OOM (1024, 512, 512) combo
    "attn-deep-d512": lambda: _attention_case(1, 2048, 2048, 1, 512),
    # lane-unaligned S: the pad-to-block streaming path
    "attn-awkward-s": lambda: _attention_case(1, 256, 2944, 4, 16),
    # flash-CE at the flagship gathered shape (10240 = 512*20, exact blocks)
    "ce-flagship": lambda: _ce_case(10240, 64, 10003),
    # flash-CE at the 131k-context gathered rows: 39328 = 32*1229 forces the
    # row-padding rule (the r3 +48% fix) — dead rows must stay exact
    "ce-padded-rows": lambda: _ce_case(39328, 64, 10003),
    # the shard_map'd sequence-parallel kernel compiled on real hardware
    "sp-shard": _sp_case,
    # weight-only int8: in-program dequant feeding a bf16 matmul stays
    # within parity vs the f32 oracle (the serving engines' int8w path)
    "quant-int8w-dequant": _quant_case,
    # -- fused dequant-matmul (ops/pallas_matmul) guard geometries --
    # the flagship vocab head (C=64 → 10003 padded to 10112): the single
    # biggest weight stream in the serving forward, lane-unaligned only
    # after class padding — the shape the int8w serving path lives on
    "qmm-int8-vocab-head": lambda: _qmm_case(512, 64, 10112, bits=8),
    # grouped int4 at the flagship MLP width: bk pinned to group_size=128
    # (the grouped-scale broadcast path), K a multiple of the group
    "qmm-int4-grouped-mlp": lambda: _qmm_case(2048, 512, 2048, bits=4,
                                              group_size=128),
    # sublane/lane-unaligned M/K/N: the zero-pad + slice path, f32 compute
    # (parity dtype) where kernel-vs-XLA must be near-exact
    "qmm-int8-awkward-f32": lambda: _qmm_case(
        96, 320, 161, bits=8, compute_dtype="float32", rtol=2e-5),
    # -- generative decode geometries (the in-kernel causal flag) --
    # causal prefill at the d<=128 wide-KV tier (kv resolves to 2048 with
    # the q-bump interplay): fwd + BOTH backward kernels recompute the same
    # in-kernel causal bias — parity vs the masked-einsum oracle
    "attn-causal-prefill-d128": lambda: _attention_case(
        2, 512, 8192, 8, 128, causal_offset=7680),
    # square-causal self-attention exactly ON the q-bump s_blk*d guard
    # (must resolve to the safe default like its non-causal twin)
    "attn-causal-deep-d512": lambda: _attention_case(
        1, 2048, 2048, 1, 512, causal_offset=0),
    # the q_len=1 incremental decode cross over a long token ring at the
    # VMEM-guard KV tier — the serving step shape (ring validity rides the
    # causal offset here; the engine uses a pad mask, same masking math)
    "attn-q1-decode-32k": lambda: _attention_case(
        1, 1, 32768, 4, 128, causal_offset=32767),
    # -- continuous-batching arena geometries (batch = arena slots) --
    # the arena's batched q_len=1 step at the d<=128 VMEM-guard KV tier:
    # 8 slots × one decode row each over the long ring — the vmapped-step
    # dispatch shape, which must ride the SAME block resolution as b=1
    # (batch is grid-parallel; the per-block VMEM guard maths must not move)
    "attn-arena8-q1-32k": lambda: _attention_case(
        8, 1, 32768, 4, 128, causal_offset=32767),
    # batched causal prefill across a 16-slot arena at the d<=64 wide-KV
    # tier: admission re-encodes burst-compile this exact family
    "attn-arena16-prefill-d64": lambda: _attention_case(
        16, 256, 2048, 8, 64, causal_offset=1792),
}


def run(out_path: str | None, dry: bool = False) -> int:
    if dry:
        # --dry: the stdout-contract mode — emit the one JSON line without
        # touching ANY device (no jax import: safe on a wedged tunnel, and
        # what CI uses to pin the one-JSON-line-on-stdout invariant)
        report = {
            "metric": "kernel_smoke",
            "dry": True,
            "backend": None,
            "device": None,
            "passed": 0,
            "total": len(CASES),
            "cases": [],
            "skipped": sorted(CASES),
            "failures": {},
        }
        line = emit_json_line(report)
        if out_path:
            with open(out_path, "w") as f:
                f.write(line + "\n")
        return 0

    from perceiver_io_tpu.aot import maybe_enable_cache_from_env

    maybe_enable_cache_from_env()  # PIT_COMPILE_CACHE opt-in (stderr only)
    import jax

    results, failures = [], {}
    for name, fn in CASES.items():
        try:
            fn()
            results.append(name)
        except Exception as e:  # noqa: BLE001 — every failure belongs in the artifact
            failures[name] = f"{type(e).__name__}: {str(e)[:300]}"
    report = {
        "metric": "kernel_smoke",
        "backend": probe_backend().backend,
        "device": probe_backend().device_kind,
        "passed": len(results),
        "total": len(CASES),
        "cases": results,
        "failures": failures,
    }
    line = emit_json_line(report)
    if out_path:
        with open(out_path, "w") as f:
            f.write(line + "\n")
    return 1 if failures else 0


def main():
    p = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    p.add_argument("--out", default=None, help="also write the JSON here")
    p.add_argument("--dry", action="store_true",
                   help="emit the JSON report shape without running any case "
                        "or touching a device (stdout-contract CI mode)")
    args = p.parse_args()
    raise SystemExit(run(args.out, dry=args.dry))


if __name__ == "__main__":
    main()
