"""Profiling helper: MFU + per-impl timing for the flagship MLM step.

Not part of the library API — a developer tool. Computes compiled-graph FLOPs
via XLA cost analysis and reports model FLOPs utilisation against the chip's
peak, for each attention impl.
"""

from __future__ import annotations

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np

import perceiver_io_tpu as pit
from perceiver_io_tpu.ops.masking import TextMasking
from perceiver_io_tpu.training import (
    OptimizerConfig,
    TrainState,
    make_mlm_steps,
    make_optimizer,
    mlm_gather_capacity,
)

# bf16 peak FLOP/s per chip
PEAK = {
    "TPU v5 lite": 197e12,
    "TPU v5e": 197e12,
    "TPU v4": 275e12,
    "TPU v6 lite": 918e12,
}


def peak_flops() -> float:
    kind = jax.devices()[0].device_kind
    for name, val in PEAK.items():
        if kind.startswith(name):
            return val
    return 197e12


def build(attn_impl: str, vocab=10003, seq_len=512, num_latents=256, channels=64):
    latent_shape = (num_latents, channels)
    return pit.PerceiverMLM(
        encoder=pit.PerceiverEncoder(
            input_adapter=pit.TextInputAdapter(
                vocab_size=vocab, max_seq_len=seq_len, num_channels=channels,
                dtype=jnp.bfloat16,
            ),
            latent_shape=latent_shape,
            num_layers=3,
            num_self_attention_layers_per_block=6,
            dtype=jnp.bfloat16,
            attn_impl=attn_impl,
        ),
        decoder=pit.PerceiverDecoder(
            output_adapter=pit.TextOutputAdapter(
                vocab_size=vocab, max_seq_len=seq_len, num_output_channels=channels,
                dtype=jnp.bfloat16,
            ),
            latent_shape=latent_shape,
            dtype=jnp.bfloat16,
            attn_impl=attn_impl,
        ),
        masking=TextMasking(vocab_size=vocab, unk_token_id=1, mask_token_id=2,
                            num_special_tokens=3),
    )


def run(attn_impl: str, batch_size=64, steps=20, gather=None):
    model = build(attn_impl)
    rng = np.random.default_rng(0)
    batch = {
        "token_ids": jnp.asarray(rng.integers(3, 10003, (batch_size, 512)).astype(np.int32)),
        "pad_mask": jnp.zeros((batch_size, 512), dtype=bool),
    }
    variables = model.init(
        {"params": jax.random.key(0), "masking": jax.random.key(1)},
        batch["token_ids"], batch["pad_mask"],
    )
    tx, schedule = make_optimizer(OptimizerConfig(learning_rate=1e-3))
    state = TrainState.create(variables["params"], tx, jax.random.key(2))
    train_step, _, _ = make_mlm_steps(model, schedule, loss_gather_capacity=gather)
    step = jax.jit(train_step, donate_argnums=(0,))

    lowered = step.lower(state, batch)
    compiled = lowered.compile()
    cost = compiled.cost_analysis()
    flops = cost.get("flops", 0.0) if cost else 0.0

    # float() fetch is the only reliable sync on tunneled backends (PERF.md);
    # the 1-step run subtracts the fetch round-trip.
    for _ in range(3):
        state, metrics = step(state, batch)
    float(metrics["loss"])

    def timed(n):
        nonlocal state, metrics
        t0 = time.perf_counter()
        for _ in range(n):
            state, metrics = step(state, batch)
        float(metrics["loss"])
        return time.perf_counter() - t0

    t_one = timed(1)
    dt = (timed(steps + 1) - t_one) / steps

    toks = batch_size * 512 / dt
    mfu = flops / dt / peak_flops()
    tag = f"{attn_impl}+g{gather}" if gather else attn_impl
    print(f"{tag:12s} step {dt*1e3:7.2f} ms  {toks/1e6:6.2f} Mtok/s  "
          f"flops/step {flops/1e9:.1f} G  MFU {mfu*100:.1f}%")


if __name__ == "__main__":
    print(f"device: {jax.devices()[0].device_kind}, peak {peak_flops()/1e12:.0f} TF/s")
    cap = mlm_gather_capacity(512)
    for impl in ("xla", "pallas"):
        run(impl)
        run(impl, gather=cap)
