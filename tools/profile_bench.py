"""Profiling helper: MFU + per-impl timing for the flagship MLM step.

Not part of the library API — a developer tool. Computes compiled-graph FLOPs
via XLA cost analysis and reports model FLOPs utilisation against the chip's
peak, for each attention impl.
"""

from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from perceiver_io_tpu.utils.platform import probe_backend

import jax
import jax.numpy as jnp
import numpy as np

from perceiver_io_tpu.training import (
    OptimizerConfig,
    TrainState,
    make_mlm_steps,
    make_optimizer,
    mlm_gather_capacity,
)

def build(attn_impl: str):
    from perceiver_io_tpu.models.presets import flagship_mlm

    return flagship_mlm(dtype=jnp.bfloat16, attn_impl=attn_impl)


def run(attn_impl: str, batch_size=64, steps=20, gather=None):
    model = build(attn_impl)
    rng = np.random.default_rng(0)
    batch = {
        "token_ids": jnp.asarray(rng.integers(3, 10003, (batch_size, 512)).astype(np.int32)),
        "pad_mask": jnp.zeros((batch_size, 512), dtype=bool),
    }
    variables = model.init(
        {"params": jax.random.key(0), "masking": jax.random.key(1)},
        batch["token_ids"], batch["pad_mask"],
    )
    tx, schedule = make_optimizer(OptimizerConfig(learning_rate=1e-3))
    state = TrainState.create(variables["params"], tx, jax.random.key(2))
    train_step, _, _ = make_mlm_steps(model, schedule, loss_gather_capacity=gather)
    step = jax.jit(train_step, donate_argnums=(0,))

    from perceiver_io_tpu.utils import profiling

    flops = profiling.compiled_flops(step, state, batch) or 0.0

    from perceiver_io_tpu.utils.benchmarking import time_train_step

    dt, _ = time_train_step(train_step, state, batch, steps, windows=3, jitted=step)

    toks = batch_size * 512 / dt
    u = profiling.mfu(flops, dt)
    mfu_str = f"  MFU {100 * u:.1f}%" if u is not None else ""
    tag = f"{attn_impl}+g{gather}" if gather else attn_impl
    print(f"{tag:12s} step {dt*1e3:7.2f} ms  {toks/1e6:6.2f} Mtok/s  "
          f"flops/step {flops/1e9:.1f} G{mfu_str}", file=sys.stderr)


if __name__ == "__main__":
    from perceiver_io_tpu.utils import profiling

    peak = profiling.device_peak_flops()
    peak_str = f", peak {peak/1e12:.0f} TF/s" if peak else " (no known peak: MFU off)"
    print(f"device: {probe_backend().device_kind}{peak_str}", file=sys.stderr)
    cap = mlm_gather_capacity(512)
    for impl in ("xla", "pallas"):
        run(impl)
        run(impl, gather=cap)
