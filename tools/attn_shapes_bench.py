"""Compare XLA vs fused-Pallas attention across the framework's hot shapes.

Shapes: (name, B, T, S, H, D) — T queries against S keys/values.
- mlm-cross:   encoder cross-attn at the flagship MLM config
- mlm-self:    latent self-attn at the flagship MLM config
- in-cross:    ImageNet encoder cross-attn (M = 224² = 50176, 1 head × 1024)
- in-small:    ImageNet with 8 cross heads (paper variant)
- flow-cross:  Sintel flow encoder cross-attn (M = 368×496 = 182528)
"""

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np

from perceiver_io_tpu.ops.pallas_attention import fused_attention

SHAPES = [
    ("mlm-cross", 8, 256, 512, 4, 16),
    ("mlm-self", 8, 256, 256, 4, 16),
    ("in-cross", 2, 512, 50176, 1, 1024),
    ("in-8h", 2, 512, 50176, 8, 128),
    ("flow-cross", 1, 2048, 182528, 1, 512),
]


def xla_attn(q, k, v):
    d = q.shape[-1]
    logits = jnp.einsum("bthd,bshd->bhts", q * (d**-0.5), k,
                        preferred_element_type=jnp.float32)
    probs = jax.nn.softmax(logits, axis=-1).astype(v.dtype)
    return jnp.einsum("bhts,bshd->bthd", probs, v)


def timeit(fn, args, steps=20):
    out = fn(*args)
    jax.block_until_ready(out)
    t0 = time.perf_counter()
    for _ in range(steps):
        out = fn(*args)
    jax.block_until_ready(out)
    return (time.perf_counter() - t0) / steps


def main():
    rng = np.random.default_rng(0)
    for name, b, t, s, h, d in SHAPES:
        q = jnp.asarray(rng.standard_normal((b, t, h, d)), jnp.bfloat16)
        k = jnp.asarray(rng.standard_normal((b, s, h, d)), jnp.bfloat16)
        v = jnp.asarray(rng.standard_normal((b, s, h, d)), jnp.bfloat16)
        try:
            t_xla = timeit(jax.jit(xla_attn), (q, k, v))
        except Exception as e:
            t_xla = float("nan")
            print(f"{name}: xla failed: {type(e).__name__}")
        try:
            t_pal = timeit(jax.jit(fused_attention), (q, k, v))
        except Exception as e:
            t_pal = float("nan")
            print(f"{name}: pallas failed: {type(e).__name__}: {e}")
        flops = 4 * b * h * t * s * d
        print(f"{name:10s} xla {t_xla*1e3:8.3f} ms ({flops/t_xla/1e12:6.1f} TF/s)   "
              f"pallas {t_pal*1e3:8.3f} ms ({flops/t_pal/1e12:6.1f} TF/s)")


if __name__ == "__main__":
    main()
