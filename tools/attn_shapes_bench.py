"""Compare XLA vs fused-Pallas attention across the framework's hot shapes.

Shapes: (name, B, T, S, H, D) — T queries against S keys/values.
- mlm-cross:   encoder cross-attn at the flagship MLM config
- mlm-self:    latent self-attn at the flagship MLM config
- in-cross:    ImageNet encoder cross-attn (M = 224² = 50176, 1 head × 1024)
- in-small:    ImageNet with 8 cross heads (paper variant)
- flow-cross:  Sintel flow encoder cross-attn (M = 368×496 = 182528)

``--decode`` appends the GENERATIVE (Perceiver-AR) decode family — causal
prefill cross/self at the flagship_ar widths and the q_len=1 incremental
step shapes — with both impls running the causal mask (XLA: masked einsum;
Pallas: the in-kernel ``causal_offset`` flag). These rows are what the
``attn_impl='auto'`` causal dispatch thresholds must be set from; until the
sweep runs on hardware, auto resolves every causal call to XLA (PERF.md
§Generation pending).
"""

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np

from perceiver_io_tpu.ops.masking import causal_mask
from perceiver_io_tpu.ops.pallas_attention import fused_attention

SHAPES = [
    ("mlm-cross", 8, 256, 512, 4, 16),
    ("mlm-self", 8, 256, 256, 4, 16),
    ("in-cross", 2, 512, 50176, 1, 1024),
    ("in-8h", 2, 512, 50176, 8, 128),
    ("flow-cross", 1, 2048, 182528, 1, 512),
    ("flow-self", 2, 2048, 2048, 8, 64),
    # shapes the area-based auto trigger also flips: the flow DECODER cross
    # (many queries, few keys) and ImageNet self-attn at batch >= 16
    ("flow-dec-cross", 2, 182528, 2048, 1, 512),
    ("in-self-b16", 16, 512, 512, 8, 128),
    # long-context MLM encoder cross (auto-kv streams 2048-wide blocks)
    ("mlm-32k", 2, 256, 32768, 4, 16),
    ("mlm-131k", 1, 256, 131072, 4, 16),
]

# Generative decode family: (name, B, T, S, H, D, causal_offset).
# - ar-prefill-cross: the causal latent-window cross at flagship_ar widths
#   (256 window queries over a long prefix; offset = S - T)
# - ar-prefill-self:  the square-causal latent self-attention
# - ar-step-cross:    ONE decode step's q_len=1 cross over the token ring
# - ar-step-latent:   q_len=1 over the latent ring (validity-masked; the
#   causal constraint degenerates to the offset)
DECODE_SHAPES = [
    ("ar-prefill-cross", 8, 256, 512, 4, 128, 256),
    ("ar-prefill-self", 8, 256, 256, 4, 128, 0),
    ("ar-prefill-32k", 1, 256, 32768, 4, 128, 32512),
    ("ar-step-cross", 8, 1, 512, 4, 128, 511),
    ("ar-step-cross-32k", 1, 1, 32768, 4, 128, 32767),
    ("ar-step-latent", 8, 1, 256, 4, 128, 255),
]


def xla_attn(q, k, v, causal_offset=None):
    d = q.shape[-1]
    logits = jnp.einsum("bthd,bshd->bhts", q * (d**-0.5), k,
                        preferred_element_type=jnp.float32)
    if causal_offset is not None:
        mask = causal_mask(q.shape[1], k.shape[1], causal_offset)
        logits = jnp.where(mask[None, None], jnp.finfo(jnp.float32).min,
                           logits)
    probs = jax.nn.softmax(logits, axis=-1).astype(v.dtype)
    return jnp.einsum("bhts,bshd->bthd", probs, v)


def timeit(fn, args, steps=20):
    """Honest step time on tunneled backends, where per-dispatch latency is
    ~ms, block_until_ready can return early, and dispatches whose outputs go
    unreferenced are elided: run the whole loop device-side in ONE dispatch
    (fori_loop), chaining each iteration's input on a reduction of EVERY
    output leaf (so no part of the computation is dead code — carrying just
    one element lets XLA DCE the rest of the body), then sync with a host
    scalar fetch. A 1-iteration run is subtracted to remove the fetch
    round-trip and loop overheads."""

    @jax.jit
    def loop(n, q0, *rest):
        def body(_, q):
            out = fn(q, *rest)
            dep = sum(jnp.sum(lf.astype(jnp.float32)) for lf in jax.tree.leaves(out))
            return q0 + (dep * 1e-30).astype(q0.dtype)

        return jnp.sum(jax.lax.fori_loop(0, n, body, q0).astype(jnp.float32))

    float(loop(1, *args))  # compile + warm

    def run(n):
        t0 = time.perf_counter()
        float(loop(n, *args))
        return time.perf_counter() - t0

    # grow the iteration count until the run dwarfs the ~100ms fetch noise
    t1 = run(1)
    n = steps
    while True:
        tn = run(n + 1)
        if tn > 1.0 or n >= 4096:
            return (tn - t1) / n
        n *= 4


def grad_of(attn):
    """fwd+bwd step: value_and_grad keeps the primal live so nothing DCEs."""
    def loss(q, k, v):
        return jnp.sum(attn(q, k, v).astype(jnp.float32) ** 2)
    return jax.jit(jax.value_and_grad(loss, argnums=(0, 1, 2)))


def main():
    import functools

    with_grad = "--grad" in sys.argv
    with_decode = "--decode" in sys.argv
    rng = np.random.default_rng(0)
    shapes = [(*row, None) for row in SHAPES]
    if with_decode:
        shapes += DECODE_SHAPES
    for name, b, t, s, h, d, causal in shapes:
        q = jnp.asarray(rng.standard_normal((b, t, h, d)), jnp.bfloat16)
        k = jnp.asarray(rng.standard_normal((b, s, h, d)), jnp.bfloat16)
        v = jnp.asarray(rng.standard_normal((b, s, h, d)), jnp.bfloat16)
        xla_fn = functools.partial(xla_attn, causal_offset=causal)
        pal_fn = functools.partial(fused_attention, causal_offset=causal)
        fns = ((grad_of(xla_fn), grad_of(pal_fn)) if with_grad
               else (jax.jit(xla_fn), jax.jit(pal_fn)))
        times = []
        for impl, fn in zip(("xla", "pallas"), fns):
            try:
                times.append(timeit(fn, (q, k, v)))
            except Exception as e:
                times.append(float("nan"))
                print(f"{name}: {impl} failed: {type(e).__name__}: {e}", file=sys.stderr)
        t_xla, t_pal = times
        # fwd: QKᵀ + PV; bwd adds dq/dk/ds/dp/dv tile matmuls (~2.5x more);
        # a causal mask halves the LIVE area, but the dense-equivalent count
        # is reported so impls stay comparable across the flag
        flops = 4 * b * h * t * s * d * (3.5 if with_grad else 1.0)
        print(f"{name:16s} xla {t_xla*1e3:8.3f} ms ({flops/t_xla/1e12:6.1f} TF/s)   "
              f"pallas {t_pal*1e3:8.3f} ms ({flops/t_pal/1e12:6.1f} TF/s)", file=sys.stderr)


if __name__ == "__main__":
    main()
