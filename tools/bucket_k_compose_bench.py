"""A/B: bucketed widths x steps_per_dispatch compose (VERDICT r3 item 2).

Round 3 measured +11.3% from width buckets and separately showed
``steps_per_dispatch=K`` sustaining 86-95% of the device rate through the
tunnel — but the two excluded each other. Round 4 composes them (loader-
decided global widths + K-grouped same-width runs + the trainer's
flush-on-width-change stacker); this tool shows the wins STACK on hardware:

1. full-window fraction: over one epoch of the real bucketed module at
   ``group_size=K``, how many K-batch dispatch windows are full (the
   grouping's job — without it, width changes would flush nearly every
   window early and forfeit the dispatch amortization);
2. interleaved trainer A/B on the chip: ``Trainer.fit`` tokens/s with
   buckets x K=16 vs static-512 x K=16, run A/B/A/B in ONE process
   (CLAUDE.md tunnel discipline), steady-state windows only (every shape
   compiled in a warmup epoch first).

Corpus: the same IMDB-length-realistic generator as
``bucketed_width_bench.py`` (log-normal fit to the published profile; the
real aclImdb tree is used instead when present).

Usage: ``timeout 1800 python tools/bucket_k_compose_bench.py``.
"""

from __future__ import annotations

import os
import statistics
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from perceiver_io_tpu.utils.platform import probe_backend

import numpy as np

from bucketed_width_bench import BATCH, BUCKETS, SEQ_CAP, VOCAB, realistic_corpus

K = int(os.environ.get("PIT_COMPOSE_K", "16"))
STEPS = int(os.environ.get("PIT_COMPOSE_STEPS", "640"))


CORPUS = int(os.environ.get("PIT_COMPOSE_CORPUS", "16384"))


def make_module(root: str, buckets):
    from perceiver_io_tpu.data.imdb import IMDBDataModule

    have_real = os.path.isdir(os.path.join(root, "IMDB", "aclImdb", "train"))
    dm = IMDBDataModule(
        root=root, max_seq_len=SEQ_CAP, vocab_size=VOCAB, batch_size=BATCH,
        synthetic=not have_real, synthetic_size=CORPUS,
        bucket_widths=buckets, length_sort_window=8, dispatch_group=K,
    )
    if not have_real:
        dm._train_texts = lambda: realistic_corpus(CORPUS)  # type: ignore
        dm._valid_texts = lambda: realistic_corpus(256, seed=1)  # type: ignore
    dm.prepare_data()
    dm.setup()
    return dm


def window_stats(dm):
    """(full-window fraction, fraction of STEPS inside full windows) under
    the trainer's greedy flush-on-width-change stacker
    (Trainer._dispatch_batches)."""
    windows, run, prev = [], 0, None
    for b in dm.train_dataloader():
        w = b["token_ids"].shape[1]
        if run and (w != prev or run == K):
            windows.append(run)
            run = 0
        run += 1
        prev = w
    if run:
        windows.append(run)
    total = sum(windows) or 1
    return (
        sum(1 for w in windows if w == K) / max(len(windows), 1),
        sum(w for w in windows if w == K) / total,
    )


def trainer_rate(dm, label: str) -> float:
    """Median steady-state tokens/s over a fixed-step Trainer.fit run."""
    import jax
    import jax.numpy as jnp

    from perceiver_io_tpu.models.presets import flagship_mlm
    from perceiver_io_tpu.training import (
        OptimizerConfig,
        TrainState,
        make_mlm_steps,
        make_optimizer,
        mlm_gather_capacity,
        read_metrics,
    )
    from perceiver_io_tpu.training.trainer import Trainer, TrainerConfig

    model = flagship_mlm(
        vocab_size=dm.tokenizer.get_vocab_size(), max_seq_len=SEQ_CAP,
        dtype=jnp.bfloat16, attn_impl="xla",
    )
    example = next(iter(dm.val_dataloader()))
    variables = model.init(
        {"params": jax.random.key(0), "masking": jax.random.key(1)},
        example["token_ids"][:1], example["pad_mask"][:1],
    )
    tx, sched = make_optimizer(OptimizerConfig(learning_rate=1e-3))
    state = TrainState.create(variables["params"], tx, jax.random.key(2))
    head = "pallas" if probe_backend().backend == "tpu" else False
    train_step, eval_step, _ = make_mlm_steps(
        model, sched, loss_gather_capacity=mlm_gather_capacity(SEQ_CAP),
        fused_head=head,
    )
    import tempfile

    logdir = tempfile.mkdtemp(prefix=f"compose_{label}_")
    cfg = TrainerConfig(
        max_steps=STEPS, log_every_n_steps=32, steps_per_dispatch=K,
        logdir=logdir, experiment=label, use_tensorboard=False,
        compute_mfu=False, async_checkpoint=False, max_to_keep=1,
    )
    trainer = Trainer(
        train_step, lambda s, b, k: eval_step(s, b, k), state, cfg,
        example_batch={k: example[k] for k in ("token_ids", "pad_mask")},
        tokens_per_example=SEQ_CAP,
    )
    with trainer:
        trainer.fit(dm.train_dataloader(), dm.val_dataloader())
    rows = read_metrics(trainer.run_dir)
    rates = [r["tokens_per_sec"] for r in rows if "tokens_per_sec" in r]
    # steady state: drop the first half (covers every per-shape compile)
    steady = rates[len(rates) // 2:] or rates
    return statistics.median(steady)


def _stacked_windows(dm):
    """The trainer's greedy flush-on-width-change stacking
    (Trainer._dispatch_batches), materialized: [(width, stacked_batch, k)].
    Collation and widths are exactly the composed loop's — only the dispatch
    site moves out here so each window can carry a StepTraceAnnotation."""
    windows, run, prev = [], [], None
    for b in dm.train_dataloader():
        w = b["token_ids"].shape[1]
        if run and (w != prev or len(run) == K):
            windows.append((prev, run))
            run = []
        run.append(b)
        prev = w
    if run:
        windows.append((prev, run))
    out = []
    for w, batches in windows:
        stacked = {
            key: np.stack([b[key] for b in batches])
            for key in ("token_ids", "pad_mask")
        }
        out.append((w, stacked, len(batches)))
    return out


def trace_ab(root: str) -> None:
    """Device-trace A/B of the composed bucketed K-loop vs static-512
    (VERDICT r4 item 4): per-dispatch device windows from the xplane Steps
    line, per-width LOWER-QUARTILE per-step durations over full windows,
    share-weighted by each width's true step share (partials included in the
    shares). Interleaved bucketed/static/bucketed/static in ONE process."""
    import tempfile

    import jax
    import jax.numpy as jnp

    from perceiver_io_tpu.models.presets import flagship_mlm
    from perceiver_io_tpu.training import (
        OptimizerConfig,
        TrainState,
        make_mlm_steps,
        make_optimizer,
        mlm_gather_capacity,
    )
    from perceiver_io_tpu.training.steps import make_scanned_step
    from perceiver_io_tpu.utils import xplane

    dm_b = make_module(root, BUCKETS)
    dm_s = make_module(root, None)

    model = flagship_mlm(
        vocab_size=dm_b.tokenizer.get_vocab_size(), max_seq_len=SEQ_CAP,
        dtype=jnp.bfloat16, attn_impl="xla",
    )
    example = next(iter(dm_b.val_dataloader()))
    variables = model.init(
        {"params": jax.random.key(0), "masking": jax.random.key(1)},
        example["token_ids"][:1], example["pad_mask"][:1],
    )
    tx, sched = make_optimizer(OptimizerConfig(learning_rate=1e-3))
    head = "pallas" if probe_backend().backend == "tpu" else False
    train_step, _, _ = make_mlm_steps(
        model, sched, loss_gather_capacity=mlm_gather_capacity(SEQ_CAP),
        fused_head=head,
    )
    scanned = jax.jit(make_scanned_step(train_step), donate_argnums=(0,))

    def run_arm(windows, state, trace_dir):
        # warmup pass compiles every (width, k) program OUTSIDE the trace
        seen = set()
        for w, stacked, k in windows:
            if (w, k) not in seen:
                seen.add((w, k))
                state, _ = scanned(state, stacked)
        meta = []
        with jax.profiler.trace(trace_dir):
            for i, (w, stacked, k) in enumerate(windows):
                with jax.profiler.StepTraceAnnotation("win", step_num=i):
                    state, m = scanned(state, stacked)
                meta.append((w, k))
            float(m["loss"])  # sync inside the trace window
        spans = xplane.step_windows(xplane.load_tpu_plane(trace_dir))
        assert len(spans) == len(meta), (len(spans), len(meta))
        per_width: dict = {}
        shares: dict = {}
        for (w, k), (a, b) in zip(meta, spans):
            shares[w] = shares.get(w, 0) + k
            if k == K:  # LQ statistic over FULL windows only
                per_width.setdefault(w, []).append((b - a) / 1e12 / k)
        total = sum(shares.values())
        weighted = 0.0
        for w, share in shares.items():
            durs = sorted(per_width.get(w, []))
            if not durs:  # width with only partial windows — use all of them
                durs = sorted(
                    (b - a) / 1e12 / k
                    for (ww, k), (a, b) in zip(meta, spans) if ww == w
                )
            lq = durs[len(durs) // 4]
            weighted += lq * (share / total)
        return state, weighted, total, dict(
            (w, (s, sorted(per_width.get(w, [0]))[len(per_width.get(w, [0])) // 4]))
            for w, s in shares.items()
        )

    state = TrainState.create(variables["params"], tx, jax.random.key(2))
    win_b = _stacked_windows(dm_b)
    win_s = _stacked_windows(dm_s)
    results = {"buckets": [], "static": []}
    for rep in range(2):
        for which, windows in (("buckets", win_b), ("static", win_s)):
            td = tempfile.mkdtemp(prefix=f"compose_trace_{which}{rep}_")
            state, weighted, steps, detail = run_arm(windows, state, td)
            results[which].append(weighted)
            wd = ", ".join(
                f"{w}: {s} steps @ {lq * 1e3:.2f} ms"
                for w, (s, lq) in sorted(detail.items())
            )
            print(f"  rep{rep} {which:8s}: share-weighted LQ "
                  f"{weighted * 1e3:.3f} ms/step over {steps} steps ({wd})",
                  flush=True, file=sys.stderr)
    b = statistics.median(results["buckets"])
    s = statistics.median(results["static"])
    print(
        f"device-trace composed A/B: bucketed {b * 1e3:.3f} vs static "
        f"{s * 1e3:.3f} ms/step -> {s / b:.3f}x ({(s / b - 1) * 100:+.1f}% "
        f"examples/s)", file=sys.stderr)


def main() -> None:
    root = os.environ.get("PIT_ROOT", ".cache")
    dm_b = make_module(root, BUCKETS)
    frac, steps_frac = window_stats(dm_b)
    print(f"full {K}-batch windows with buckets {BUCKETS}+cap: {frac:.1%} "
          f"of windows, {steps_frac:.1%} of steps", file=sys.stderr)

    if "--trace-ab" in sys.argv:
        trace_ab(root)
        return

    dm_s = make_module(root, None)
    order = ["buckets", "static", "buckets", "static"]
    rates = {"buckets": [], "static": []}
    for which in order:
        dm = dm_b if which == "buckets" else dm_s
        r = trainer_rate(dm, which)
        rates[which].append(r)
        print(f"  {which:8s} K={K}: {r / 1e6:.3f}M tokens/s (trainer loop)", file=sys.stderr)
    b = statistics.median(rates["buckets"])
    s = statistics.median(rates["static"])
    print(
        f"composed win: bucketed {b / 1e6:.3f}M vs static {s / 1e6:.3f}M "
        f"tokens/s at K={K} -> {b / s:.3f}x ({(b / s - 1) * 100:+.1f}%)", file=sys.stderr)


if __name__ == "__main__":
    main()
