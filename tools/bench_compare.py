"""Noise-floor-aware bench record comparison: the regression sentinel.

The bench trajectory (BENCH_*.json, load_bench/deploy_bench records,
PERF.md's measured curves) has been compared by EYE against the measurement
discipline's noise floors — this tool machine-checks it. Given a baseline
record and one or more candidates, every comparable numeric metric gets a
verdict: ``improved`` / ``regressed`` / ``within_noise``.

The floors are TAKEN FROM PERF.md's recorded null-control measurements,
never re-derived at compare time (re-deriving would launder today's noise
into tomorrow's threshold):

- **device-trace** statistics (``bench.py``'s headline
  ``mlm_tokens_per_sec_per_chip`` with ``method=device_trace``, and
  ``device_ms_per_step``): ±0.04% — the lower-quartile device-trace step
  time reproduces to that across sessions (PERF.md §Measurement, r3).
- **same-process paired-interleave** percentages (``overhead_pct`` from
  ``--trace_ab``-family A/Bs): ±1.5 absolute points — the r15 null control
  (both arms identical) measured a ±1.5% floor on this host.
- **host-clock / cross-session** numbers (``host_ms_per_step``, CPU
  requests/s, latency percentiles, calibrated capacities): the tunnel and
  the shared CPU swing ±2x BETWEEN sessions (CLAUDE.md / PERF.md), so a
  cross-record comparison gets a 100% floor — only a >2x change clears it.
  This is deliberately brutal: cross-session host numbers cannot resolve
  finer, and the honest verdict for a 30% "win" measured across sessions
  is ``within_noise``. Same-process interleaves are the tool for finer
  claims; this sentinel's job is the trajectory, not the A/B.

Record formats accepted: a bare one-line JSON record (what every tool
emits), or the driver's ``BENCH_rNN.json`` wrapper (the ``parsed`` field is
used). Nested records flatten to dot paths (``capacity.knee_rps``,
``trace.overhead_pct``); list elements index (``sweep.0.p99_ms``). By
default only keys a floor class recognizes are compared (counts and config
echoes are not measurements); ``--keys`` selects explicitly, ``--all``
compares every shared numeric key (unrecognized keys get the host floor).

Usage::

    python tools/bench_compare.py BASELINE.json CAND.json [MORE.json ...]
        [--keys value,device_ms_per_step] [--all] [--fail_on_regress]

Emits exactly ONE JSON line on stdout; per-metric detail rides stderr.
Exit 0 always, unless ``--fail_on_regress`` and any candidate regressed.
"""

from __future__ import annotations

import argparse
import json
import os
import re
import sys
from typing import Any, Dict, List, Optional, Tuple

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from perceiver_io_tpu.utils.jsonline import emit_json_line, log

# -- noise floors: PERF.md's recorded null-control numbers --------------------
# (pattern over the flattened dot-path key; first match wins; floor is a
# FRACTION of the baseline unless mode == "abs" — absolute difference in the
# metric's own unit, for metrics that are already percentages)

DEVICE_FLOOR = 0.0004   # PERF.md §Measurement (r3): device-trace lower
# quartile reproduces ±0.04% across sessions
PAIRED_FLOOR_PTS = 1.5  # PERF.md §Tracing (r15): null-control paired
# interleave measured a ±1.5% floor on this host
HOST_FLOOR = 1.0        # CLAUDE.md / PERF.md: host clocks + tunnel swing
# ±2x between sessions — cross-record host numbers resolve nothing finer

FLOOR_CLASSES: List[Tuple[str, str, float, str, str]] = [
    # (key regex, mode frac|abs, floor, direction higher|lower, source)
    (r"(^|\.)device_ms_per_step$", "frac", DEVICE_FLOOR, "lower",
     "PERF.md §Measurement r3: device-trace lower-quartile ±0.04%"),
    (r"(^|\.)overhead_pct$", "abs", PAIRED_FLOOR_PTS, "lower",
     "PERF.md §Tracing r15: paired-interleave null control ±1.5%"),
    (r"(^|\.)blip_ratio$", "frac", HOST_FLOOR, "lower",
     "PERF.md §Deployment: host-clock blip attribution, cross-session"),
    (r"(^|\.)host_ms_per_step$", "frac", HOST_FLOOR, "lower",
     "CLAUDE.md: host clock rides the tunnel (±2x session swing)"),
    (r"(^|\.)(mfu|mxu)([_%]|$)", "frac", DEVICE_FLOOR, "higher",
     "PERF.md §Roofline: derived from the device trace"),
    (r"(_|\.|^)(knee_rps|capacity_rps|slo_sustainable_rps|calibrated_rps"
     r"|achieved_rps|offered_rps)$", "frac", HOST_FLOOR, "higher",
     "PERF.md §SLO: CPU open-loop rates are host-clock, cross-session"),
    (r"(_|\.|^)p\d+_ms$|(^|\.)calibrated_latency_ms$|service_floor_ms$"
     r"|p99_floor_ms$|_p99_ms$|_steady_ms$|_swap_ms$", "frac", HOST_FLOOR,
     "lower", "PERF.md: latency percentiles are host-clock, cross-session"),
    (r"(^|\.)shed_rate$", "abs", 0.01, "lower",
     "PERF.md §SLO: shed fractions jitter ~1e-2 point-to-point on CPU"),
    # decode_batching_bench (r20): the speedup is a SAME-PROCESS paired
    # ratio, so host drift cancels — the floor is the observed per-pair
    # spread (pairs 1.973/2.114/2.266 around the 2.114 median, ±7%),
    # doubled. The arm throughputs themselves are host-clock.
    (r"(^|\.)speedup(_median)?$", "frac", 0.15, "higher",
     "PERF.md §Continuous batching r20: per-pair speedup spread ±7% "
     "around the 2.114x median; 2x that as the floor"),
    (r"(^|\.)(batched|sequential)_tokens_per_s$|(^|\.)tokens_per_s$",
     "frac", HOST_FLOOR, "higher",
     "CLAUDE.md: CPU tokens/s is host-clock, cross-session (±2x swing)"),
    # load_bench transport A/B (r22): the speedups are SAME-PROCESS paired
    # (throughput: order-alternated wave pairs) or same-log derived (rpc
    # span p50 ratio) — host drift cancels, so the floor is per-pair
    # spread, the r20 paired-speedup treatment. The arm rates themselves
    # are host-clock.
    (r"(^|\.)(rpc_p50_speedup|throughput_speedup)$", "frac", 0.15, "higher",
     "PERF.md §Transport r22: same-process http-vs-transport paired "
     "ratio; per-pair spread floor (the r20 paired-speedup class)"),
    (r"(^|\.)(http_rps|transport_rps)$", "frac", HOST_FLOOR, "higher",
     "CLAUDE.md: CPU requests/s is host-clock, cross-session (±2x swing)"),
    (r"(^|\.)(slot_occupancy|steps_per_dispatch)(_mean)?$"
     r"|(^|\.)ar_decode_slot_occupancy$", "frac", 0.10, "higher",
     "PERF.md §Continuous batching r20: occupancy/steps-per-dispatch are "
     "schedule-determined aggregates; ~10% run-to-run on CPU"),
    # multihost_drill (r19 restart / r23 elastic): recovery walls are
    # host-clock CPU-sim walls; the elastic-vs-restart `speedup` is a
    # same-process paired ratio and matches the r20 speedup class above.
    (r"(^|\.)(kill_to_\w+_s|total_wall_s|resize_wall_s|grow_wall_s"
     r"|join_wall_s|restart_baseline_s)$", "frac", HOST_FLOOR, "lower",
     "PERF.md §Elastic training r23: recovery walls are host-clock, "
     "cross-session (±2x swing)"),
    (r"(^|\.)steps_lost$", "abs", 0.0, "lower",
     "PERF.md §Elastic training r23: zero-loss accounting is "
     "deterministic — ANY lost step is a regression"),
    # quant_bench (r24): parity errors are seed/model-deterministic
    # (identical quantized values every run) — only the compiler's lowering
    # can wiggle the last ulps, so a 10% floor is already generous; any
    # bigger jump means the kernel or the quantizer changed behavior.
    (r"(^|\.)(parity_\w+_rel_err|qmm_kernel_rel_err)$", "frac", 0.10,
     "lower", "PERF.md §Quantization r24: parity vs the f32 oracle is "
     "deterministic per seed/preset; 10% floor covers compiler ulps"),
    # byte accounting is pure arithmetic over the param tree: ANY drift is
    # a storage-format change, not noise
    (r"(^|\.)(param_bytes_\w+|predicted_weight_stream_ratio(_int4w)?)$",
     "abs", 0.0, "lower",
     "PERF.md §Quantization r24: predicted weight-stream bytes are "
     "deterministic accounting — any change is a format change"),
    # the engine-arm and kernel A/B speedups are SAME-PROCESS interleaved
    # ratios (drift cancels): the r20 paired-speedup treatment
    (r"(^|\.)(speedup_int[48]w_vs_bf16|speedup_qmm_pallas_vs_xla)$",
     "frac", 0.15, "higher",
     "PERF.md §Quantization r24: same-process interleaved A/B ratio; "
     "per-round spread floor (the r20 paired-speedup class)"),
    (r"(^|\.)(bf16|int8w|int4w)_requests_per_s$", "frac", HOST_FLOOR,
     "higher",
     "CLAUDE.md: CPU requests/s is host-clock, cross-session (±2x swing)"),
    (r"(^|\.)qmm_(pallas|xla)_ms$", "frac", HOST_FLOOR, "lower",
     "CLAUDE.md: kernel micro-A/B arm times are host-clock; only the "
     "paired speedup_qmm ratio resolves finer"),
    (r"(^|\.)device_dispatch_lq_ms_\w+$", "frac", DEVICE_FLOOR, "lower",
     "PERF.md §Measurement r3: device-trace lower-quartile ±0.04%"),
    (r"(^|\.)achieved_hbm_(bytes_per_dispatch_\w+|ratio_\w+)$", "frac",
     0.05, "lower",
     "PERF.md §Quantization r24: traced HBM bytes/dispatch vary with "
     "batching composition ~5% run-to-run"),
]

# bench.py's headline: 'value' is device-trace only when the record says so
_HEADLINE = "mlm_tokens_per_sec_per_chip"


def classify(key: str, record: Dict[str, Any]
             ) -> Optional[Tuple[str, float, str, str]]:
    """``(mode, floor, direction, source)`` for a flattened key, or None
    when the key is not a recognized measurement."""
    leaf = key.rsplit(".", 1)[-1]
    if leaf == "value" and record.get("metric") == _HEADLINE:
        if record.get("method") == "device_trace":
            return ("frac", DEVICE_FLOOR, "higher",
                    "PERF.md §Measurement r3: device-trace headline ±0.04%")
        return ("frac", HOST_FLOOR, "higher",
                "CLAUDE.md: host-clock headline rides the tunnel (±2x)")
    for pattern, mode, floor, direction, source in FLOOR_CLASSES:
        if re.search(pattern, key):
            return (mode, floor, direction, source)
    return None


def flatten(obj: Any, prefix: str = "") -> Dict[str, float]:
    """Numeric scalars by dot path (bools excluded — they are states, not
    measurements; list elements index numerically)."""
    out: Dict[str, float] = {}
    if isinstance(obj, dict):
        for k, v in obj.items():
            out.update(flatten(v, f"{prefix}.{k}" if prefix else str(k)))
    elif isinstance(obj, list):
        for i, v in enumerate(obj):
            out.update(flatten(v, f"{prefix}.{i}" if prefix else str(i)))
    elif isinstance(obj, (int, float)) and not isinstance(obj, bool):
        out[prefix] = float(obj)
    return out


def load_record(path: str) -> Dict[str, Any]:
    """One bench record: a bare JSON object/line, or the driver's
    BENCH_rNN.json wrapper (its ``parsed`` field is the record)."""
    with open(path) as f:
        text = f.read().strip()
    try:
        body = json.loads(text)
    except json.JSONDecodeError:
        # a JSONL file: take the last parseable line (tools emit one, but
        # a concatenated log should still compare by its newest record)
        body = None
        for line in reversed(text.splitlines()):
            try:
                body = json.loads(line)
                break
            except json.JSONDecodeError:
                continue
        if body is None:
            raise ValueError(f"{path}: no JSON record found")
    if isinstance(body, dict) and isinstance(body.get("parsed"), dict):
        body = body["parsed"]
    if not isinstance(body, dict):
        raise ValueError(f"{path}: record is not a JSON object")
    return body


def compare(base: Dict[str, Any], cand: Dict[str, Any],
            keys: Optional[List[str]] = None,
            include_all: bool = False) -> List[Dict[str, Any]]:
    """Per-metric verdicts for one candidate against the baseline."""
    fb, fc = flatten(base), flatten(cand)
    shared = sorted(set(fb) & set(fc))
    out: List[Dict[str, Any]] = []
    for key in shared:
        if keys is not None and key not in keys:
            continue
        cls = classify(key, base)
        if cls is None:
            if not (include_all or keys is not None):
                continue
            cls = ("frac", HOST_FLOOR, None,
                   "unclassified metric — host-conservative 100% floor")
        mode, floor, direction, source = cls
        b, c = fb[key], fc[key]
        delta = c - b
        if mode == "abs":
            over = abs(delta) > floor
            floor_desc = f"±{floor:g} abs"
            delta_frac = None if b == 0 else delta / abs(b)
        else:
            delta_frac = None if b == 0 else delta / abs(b)
            over = (abs(delta) > 0 if b == 0
                    else abs(delta_frac) > floor)
            floor_desc = f"±{100 * floor:g}%"
        if not over:
            verdict = "within_noise"
        elif direction is None:
            verdict = "changed"
        else:
            better = delta > 0 if direction == "higher" else delta < 0
            verdict = "improved" if better else "regressed"
        out.append({
            "key": key, "base": b, "cand": c,
            "delta_pct": (None if delta_frac is None
                          else round(100 * delta_frac, 4)),
            "floor": floor_desc, "direction": direction,
            "verdict": verdict, "floor_source": source,
        })
    return out


def summarize(comparisons: List[Dict[str, Any]]) -> Dict[str, Any]:
    counts = {"improved": 0, "regressed": 0, "within_noise": 0, "changed": 0}
    for c in comparisons:
        counts[c["verdict"]] += 1
    if not comparisons:
        # schema drift / a --dry record / the wrong file: "nothing was
        # checked" must never read as "nothing regressed"
        verdict = "no_comparable_metrics"
    elif counts["regressed"]:
        verdict = "regressed"
    elif counts["improved"]:
        verdict = "improved"
    elif counts["changed"]:
        verdict = "changed"
    else:
        verdict = "within_noise"
    return {**counts, "verdict": verdict}


def main() -> int:
    parser = argparse.ArgumentParser(
        description="noise-floor-aware bench record comparison")
    parser.add_argument("records", nargs="+", metavar="RECORD.json",
                        help="baseline first, then candidate(s)")
    parser.add_argument("--keys", default=None,
                        help="comma-separated flattened keys to compare "
                             "(default: every shared key a floor class "
                             "recognizes)")
    parser.add_argument("--all", action="store_true",
                        help="compare every shared numeric key; "
                             "unrecognized keys get the conservative "
                             "host-class 100%% floor")
    parser.add_argument("--fail_on_regress", action="store_true",
                        help="exit nonzero when any candidate regressed")
    args = parser.parse_args()
    if len(args.records) < 2:
        parser.error("need a baseline and at least one candidate record")

    keys = ([k.strip() for k in args.keys.split(",") if k.strip()]
            if args.keys else None)
    base = load_record(args.records[0])
    candidates = []
    any_regressed = False
    for path in args.records[1:]:
        cand = load_record(path)
        comparisons = compare(base, cand, keys=keys, include_all=args.all)
        summary = summarize(comparisons)
        any_regressed = any_regressed or summary["verdict"] == "regressed"
        if not comparisons:
            log(f"compare: {path}: NO comparable metrics vs the baseline "
                "(schema drift or a non-measurement record?) — nothing "
                "was checked")
        for c in comparisons:
            log(f"compare: {c['key']}: {c['base']:g} -> {c['cand']:g} "
                + (f"({c['delta_pct']:+.3f}%) " if c["delta_pct"] is not None
                   else "")
                + f"[{c['verdict']}; floor {c['floor']} — "
                + f"{c['floor_source']}]")
        candidates.append({
            "record": path,
            "summary": summary,
            "comparisons": comparisons,
        })

    compared = sum(len(c["comparisons"]) for c in candidates)
    verdict = ("regressed" if any_regressed else
               summarize([x for c in candidates
                          for x in c["comparisons"]])["verdict"])
    # under --fail_on_regress an unchecked CANDIDATE fails, not just an
    # all-empty run: a gate that skipped one record must not pass because
    # a sibling record compared fine
    any_unchecked = any(not c["comparisons"] for c in candidates)
    failed = args.fail_on_regress and (any_regressed or any_unchecked)
    emit_json_line({
        "tool": "bench_compare",
        "baseline": args.records[0],
        "candidates": candidates,
        "compared": compared,
        "verdict": verdict,
        "ok": not failed,
    })
    return 1 if failed else 0


if __name__ == "__main__":
    sys.exit(main())
