"""Cold-start bench: same-process cold-vs-warm warmup A/B over the AOT cache.

Measures what the persistent executable cache (``perceiver_io_tpu.aot``,
PERF.md §Cold start) actually buys at process start:

1. **cold**: a fresh ``ServingEngine`` warms its full bucket-program family
   against an EMPTY cache directory — every program traces, lowers, and
   compiles (and is persisted);
2. **warm**: a second engine (same model/config/signatures, new instance —
   a fresh closure, so jax's in-process jit cache cannot help it) warms the
   same family against the now-populated cache — every program deserializes.
   The ``jax_compilations_total`` delta over this phase is reported
   (``compiles_warm``; the zero-recompile claim) alongside the wall-clock
   ratio (``speedup``);
3. **first-result latency under background warmup**: a third engine starts a
   priority-ordered background warmup and immediately receives one request —
   ``first_result_s`` is how long that first caller waited, against
   ``bg_warmup_s`` for the whole family (the serve-before-warm claim).

Both arms run in ONE process, interleaved with nothing — compile wall time
is host-side work (trace + lower + backend compile round-trip), so the
tunnel's session-to-session throughput swing cancels out of the ratio the
same way the interleaved A/B discipline handles dispatch benches (PERF.md).
The device-trace step-time methodology is untouched: this bench never times
steady-state dispatch.

Emits exactly ONE JSON line on stdout (progress on stderr). ``--cpu`` pins
the CPU backend (tier-1 contract mode); on the real chip the same script
measures the remote-compiler round-trips the cache eliminates.

Usage::

    timeout 900 python tools/coldstart_bench.py --cpu [--cache_dir DIR]
        [--max_batch N] [--widths W ...]
"""

from __future__ import annotations

import argparse
import json
import os
import shutil
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from perceiver_io_tpu.utils.jsonline import emit_json_line
from perceiver_io_tpu.utils.platform import probe_backend

import numpy as np


def _log(*a) -> None:
    print(*a, file=sys.stderr)


def main() -> None:
    parser = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    parser.add_argument("--cpu", action="store_true",
                        help="pin to the CPU backend (ensure_cpu_only before "
                             "jax initializes) — the offline/tier-1 mode")
    parser.add_argument("--cache_dir", default=None,
                        help="cache directory (default: a fresh temp dir, "
                             "removed afterwards; pass one to inspect "
                             "entries or A/B across invocations)")
    parser.add_argument("--max_batch", type=int, default=16,
                        help="micro-batch cap → power-of-two bucket family")
    parser.add_argument("--widths", type=int, nargs="+", default=[32, 64],
                        help="sequence widths (one program family per width)")
    args = parser.parse_args()

    if args.cpu:
        from perceiver_io_tpu.utils.platform import ensure_cpu_only

        ensure_cpu_only()
    import jax

    from perceiver_io_tpu.inference import ServingEngine
    from perceiver_io_tpu.models.presets import tiny_mlm
    from perceiver_io_tpu.obs import install_compile_counter

    backend = probe_backend().backend
    widths = sorted({int(w) for w in args.widths})
    _log(f"backend: {backend}; widths {widths}; max_batch {args.max_batch}")

    model = tiny_mlm(max_seq_len=widths[-1])
    ids0 = np.zeros((1, widths[-1]), np.int32)
    variables = model.init(
        {"params": jax.random.key(0), "masking": jax.random.key(1)},
        ids0, ids0 == 0,
    )
    params = variables["params"]

    def gathered_apply(p, token_ids, pad_mask, pos):
        logits, _ = model.apply(
            {"params": p}, token_ids, pad_mask, masking=False,
            deterministic=True, positions=pos,
        )
        return logits

    def examples(width: int):
        return (np.zeros((1, width), np.int32),
                np.zeros((1, width), bool),
                np.zeros((1, 2), np.int32))

    counter = install_compile_counter()
    cache_dir = args.cache_dir or tempfile.mkdtemp(prefix="coldstart_cache_")
    ephemeral = args.cache_dir is None

    def warm_family(name: str):
        """Fresh engine, full-family blocking warmup; returns
        (wall_s, compiles, programs)."""
        engine = ServingEngine(
            gathered_apply, params, max_batch=args.max_batch,
            compile_cache=cache_dir, name=name,
        )
        c0 = counter.value
        t0 = time.perf_counter()
        for width in widths:
            engine.warmup(*examples(width))
        wall = time.perf_counter() - t0
        programs = engine.num_programs
        engine.close()
        return wall, counter.value - c0, programs

    try:
        cold_s, compiles_cold, programs = warm_family("coldstart_cold")
        _log(f"cold: {programs} programs in {cold_s:.3f}s "
             f"({compiles_cold:.0f} compiles)")
        warm_s, compiles_warm, _ = warm_family("coldstart_warm")
        _log(f"warm: {warm_s:.3f}s ({compiles_warm:.0f} compiles)")

        # serve-before-warm: background warmup + an immediate request
        engine = ServingEngine(
            gathered_apply, params, max_batch=args.max_batch,
            compile_cache=cache_dir, name="coldstart_bg",
        )
        handle = engine.warmup(*examples(widths[0]), background=True)
        t0 = time.perf_counter()
        fut = engine.submit(*examples(widths[0]))
        fut.result(timeout=600)
        first_result_s = time.perf_counter() - t0
        handle.wait(timeout=600)
        bg_warmup_s = time.perf_counter() - t0
        engine.close()
        _log(f"background: first result {first_result_s:.3f}s, family warm "
             f"{bg_warmup_s:.3f}s")
    finally:
        if ephemeral:
            shutil.rmtree(cache_dir, ignore_errors=True)

    emit_json_line({
        "metric": "coldstart_warmup_speedup",
        "value": round(cold_s / warm_s, 2) if warm_s > 0 else None,
        "unit": "x (cold/warm wall)",
        "backend": backend,
        "widths": widths,
        "max_batch": args.max_batch,
        "programs": programs,
        "cold_warmup_s": round(cold_s, 3),
        "warm_warmup_s": round(warm_s, 3),
        "speedup": round(cold_s / warm_s, 2) if warm_s > 0 else None,
        "compiles_cold": int(compiles_cold),
        "compiles_warm": int(compiles_warm),
        "bg_first_result_s": round(first_result_s, 3),
        "bg_family_warm_s": round(bg_warmup_s, 3),
    })


if __name__ == "__main__":
    main()
