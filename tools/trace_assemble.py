"""Merge per-process event logs into per-request distributed trace trees.

The fleet writes one JSONL per process (router: ``--events_jsonl``, each
replica: ``<events_jsonl>.<name>``; every record dual-stamped wall+monotonic
and pid-labeled). This tool performs the offline half of the r15 tracing
story (``perceiver_io_tpu.obs.reqtrace``):

1. **cross-process clock alignment** — each process's monotonic span stamps
   are anchored onto the shared wall timeline via that process's median
   ``wall − mono`` offset;
2. **trace assembly** — span records (and the engine's ``request_phases``
   records, expanded into six phase child spans) join across processes into
   one tree per trace id;
3. **tail-based sampling** — error / reroute / affinity-spill traces and the
   slowest ``1 − slow_pct`` fraction are always kept; the boring majority is
   kept at ``--sample`` rate;
4. **reconciliation** — per trace, the sum of exclusive span self-times is
   compared with the root duration (the e2e latency the router histogram
   observed): the ``reconcile_p50`` ratio is the cross-process extension of
   the r11 phase-sum self-check.

Kept traces are written (one JSON tree per line) to ``--out``; the stdout is
exactly ONE JSON summary line (tool contract). ``--trace ID`` pretty-prints
one assembled tree to stderr — the "show me my p99 request" workflow, fed a
trace id from a latency histogram's ``exemplars`` (``/statz``).

Usage::

    python tools/trace_assemble.py events.jsonl events.jsonl.r0 \
        [--out traces.jsonl] [--slow_pct 0.95] [--sample 0.1] [--trace ID]
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import sys
from typing import Any, Dict, List

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from perceiver_io_tpu.obs.reqtrace import assemble_traces, tail_sample
from perceiver_io_tpu.utils.jsonline import emit_json_line, log


def read_records(paths: List[str]) -> List[Dict[str, Any]]:
    """Every parseable JSON line across ``paths`` (rotated segments welcome:
    pass ``events.jsonl*``). Torn lines (a crashed writer's last write) are
    skipped, counted, never fatal."""
    records: List[Dict[str, Any]] = []
    torn = 0
    for path in paths:
        with open(path, encoding="utf-8") as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    records.append(json.loads(line))
                except ValueError:
                    torn += 1
    if torn:
        log(f"trace_assemble: skipped {torn} unparseable line(s)")
    return records


def render_trace(trace: Dict[str, Any]) -> str:
    """Human tree view of one assembled trace (stderr)."""
    by_id = {s["span"]: s for s in trace["spans"]}
    children: Dict[str, List[str]] = {s["span"]: list(s["children"])
                                      for s in trace["spans"]}
    lines = [f"trace {trace['trace']}  total {trace['total_s'] * 1e3:.3f} ms"
             f"  span_sum {trace['span_sum_s'] * 1e3:.3f} ms"
             f"  processes {','.join(trace['processes'])}"
             f"  flags {trace['flags']}"]

    def walk(span_id: str, depth: int) -> None:
        s = by_id[span_id]
        extra = " ".join(
            f"{k}={s[k]}" for k in ("replica", "engine", "attempt", "error")
            if s.get(k) is not None)
        lines.append(f"  {'  ' * depth}{s['name']:<24} "
                     f"{s['dur_s'] * 1e3:9.3f} ms  pid={s.get('pid')}"
                     + (f"  {extra}" if extra else ""))
        for c in sorted(children.get(span_id, ()),
                        key=lambda cid: by_id[cid]["abs_start"]):
            walk(c, depth + 1)

    walk(trace["root"]["span"], 0)
    return "\n".join(lines)


def main() -> None:
    parser = argparse.ArgumentParser(
        description="assemble per-process event logs into request traces")
    parser.add_argument("paths", nargs="+",
                        help="event JSONL files (globs ok: events.jsonl*)")
    parser.add_argument("--out", default=None,
                        help="write kept assembled traces here, one JSON "
                             "tree per line")
    parser.add_argument("--slow_pct", type=float, default=0.95,
                        help="always keep traces at/above this duration "
                             "percentile (the tail)")
    parser.add_argument("--sample", type=float, default=0.1,
                        help="retention rate for unflagged, non-tail traces")
    parser.add_argument("--all", action="store_true",
                        help="keep every assembled trace (skip tail "
                             "sampling)")
    parser.add_argument("--trace", default=None, metavar="ID",
                        help="pretty-print this assembled trace to stderr "
                             "(e.g. an exemplar trace id from /statz)")
    args = parser.parse_args()

    paths = sorted({p for pattern in args.paths
                    for p in (glob.glob(pattern) or [pattern])})
    missing = [p for p in paths if not os.path.exists(p)]
    if missing:
        raise SystemExit(f"trace_assemble: no such file(s): {missing}")
    records = read_records(paths)
    traces, context = assemble_traces(records)

    kept = (dict(traces) if args.all
            else tail_sample(traces, slow_pct=args.slow_pct,
                             sample=args.sample))
    kept_for: Dict[str, int] = {}
    for t in kept.values():
        reason = t.get("kept_for", "all")
        kept_for[reason] = kept_for.get(reason, 0) + 1

    # the cross-process extension of the r11 reconciliation self-check:
    # exclusive span self-times should partition the root's duration
    ratios = sorted(t["span_sum_s"] / t["total_s"]
                    for t in traces.values() if t["total_s"] > 0)
    reconcile_p50 = (ratios[len(ratios) // 2] if ratios else None)
    cross = sum(1 for t in traces.values() if len(t["processes"]) > 1)

    if args.trace is not None:
        t = traces.get(args.trace)
        if t is None:
            log(f"trace_assemble: trace {args.trace!r} not found "
                f"({len(traces)} assembled)")
        else:
            log(render_trace(t))

    if args.out:
        with open(args.out, "w", encoding="utf-8") as f:
            for trace_id in sorted(kept):
                f.write(json.dumps(kept[trace_id], default=str) + "\n")
        log(f"trace_assemble: wrote {len(kept)} trace(s) -> {args.out}")

    emit_json_line({
        "tool": "trace_assemble",
        "files": len(paths),
        "records": len(records),
        "traces": len(traces),
        "cross_process_traces": cross,
        "kept": len(kept),
        "kept_for": dict(sorted(kept_for.items())),
        "context_spans": len(context),
        "reconcile_p50": (None if reconcile_p50 is None
                          else round(reconcile_p50, 4)),
        "slow_pct": args.slow_pct,
        "sample": args.sample,
        "ok": True,
    })


if __name__ == "__main__":
    main()
