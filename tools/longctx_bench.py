"""Long-context MLM train-step bench: device-trace step time per (seq, batch).

Reproduces PERF.md's long-context family table (8k-131k tokens on one chip):
the flagship-MLM architecture at a longer ``max_seq_len``, bf16, auto
attention dispatch (→ the streaming fused kernel with auto-sized KV blocks at
these S), masked-position gather decode, and the flash-CE head. One line per
config:

    seq 32768 batch 4: 17.77 ms/step  7374577 tokens/s/chip

Usage: ``timeout 1800 python tools/longctx_bench.py [SEQ:BATCH ...]``
(default sweep = PERF.md's family table: 8192:8 32768:2 65536:1 131072:1;
the measured throughput PEAK is 32768:4). Timing discipline: the device
trace's lower-quartile step duration (PERF.md — reproducible ±0.04% across
sessions on the tunneled chip); off-TPU backends fall back to the
host-clock chained-window recipe and say so.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from perceiver_io_tpu.utils.platform import probe_backend

import jax
import jax.numpy as jnp
import numpy as np

DEFAULT_CONFIGS = ["8192:8", "32768:2", "65536:1", "131072:1"]


def main() -> None:
    from perceiver_io_tpu.models.presets import flagship_mlm
    from perceiver_io_tpu.training import (
        OptimizerConfig,
        TrainState,
        make_mlm_steps,
        make_optimizer,
        mlm_gather_capacity,
    )
    from perceiver_io_tpu.utils.benchmarking import (
        time_train_step,
        time_train_step_device,
    )

    configs = sys.argv[1:] or DEFAULT_CONFIGS
    vocab = 10003
    rng = np.random.default_rng(0)
    on_tpu = probe_backend().backend == "tpu"
    for spec in configs:
        seq_len, batch = (int(x) for x in spec.split(":"))
        model = flagship_mlm(
            vocab_size=vocab, max_seq_len=seq_len, dtype=jnp.bfloat16
        )
        b = {
            "token_ids": jnp.asarray(
                rng.integers(3, vocab, (batch, seq_len)).astype(np.int32)
            ),
            "pad_mask": jnp.zeros((batch, seq_len), dtype=bool),
        }
        variables = model.init(
            {"params": jax.random.key(0), "masking": jax.random.key(1)},
            b["token_ids"], b["pad_mask"],
        )
        tx, sched = make_optimizer(OptimizerConfig(learning_rate=1e-3))
        state = TrainState.create(variables["params"], tx, jax.random.key(2))
        train_step, _, _ = make_mlm_steps(
            model, sched,
            loss_gather_capacity=mlm_gather_capacity(seq_len),
            # the flash-CE head is a TPU kernel; off-TPU interpret mode is
            # orders of magnitude slower than the unfused path
            fused_head="pallas" if on_tpu else False,
        )
        jitted = jax.jit(train_step, donate_argnums=(0,))
        if on_tpu:
            dev_s, _, _ = time_train_step_device(
                train_step, state, b, 12, jitted=jitted
            )
            method = "device_trace"
        else:
            dev_s, _ = time_train_step(
                train_step, state, b, 12, windows=3, jitted=jitted
            )
            method = "host_clock"
        print(
            f"seq {seq_len} batch {batch}: {dev_s * 1e3:7.3f} ms/step  "
            f"{batch * seq_len / dev_s:9.0f} tokens/s/chip  [{method}]", file=sys.stderr)


if __name__ == "__main__":
    main()
