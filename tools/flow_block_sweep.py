"""Block-size sweep for the flow encoder-cross fused-attention kernel.

PERF.md r2 pinned flow's remaining headroom on the encoder-cross kernel's
14-16 TF/s MXU rate and left block tuning "blocked by infra". Subtlety the
sweep must cover: S = 368·496 = 182528 = 2^8·23·31, whose lane-aligned
divisors are 128, 256, then nothing until 2944 (= 128·23) and 3968
(= 128·31) — so the default kv_block_size=512 silently degrades to 256
(`_kv_block_size` picks the largest aligned divisor ≤ request), mid-range
blocks require the PAD path (S padded up to a block multiple with PAD_BIAS
keys), and the big exact divisors stream with no padding at all. This
script times fwd+bwd at the flow encoder-cross shape across (kv_block,
q_block) grids covering all three regimes.

Usage: ``timeout 1800 python tools/flow_block_sweep.py [--batch 4]``
"""

from __future__ import annotations

import functools
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np

# one copy of the tunnel-honest timing discipline (fori_loop chaining,
# DCE-proof dep sum, 1-iter subtraction) — shared with the shapes bench
from attn_shapes_bench import grad_of, timeit
from perceiver_io_tpu.ops.pallas_attention import fused_attention

T, S, H, D = 2048, 182528, 1, 512
KV_BLOCKS = [256, 512, 1024, 2048, 2944, 3968]  # 2944/3968: exact divisors
Q_BLOCKS = [256, 512, 1024]


def main() -> None:
    b = 4
    if "--batch" in sys.argv:
        b = int(sys.argv[sys.argv.index("--batch") + 1])
    rng = np.random.default_rng(0)
    q = jnp.asarray(rng.standard_normal((b, T, H, D)), jnp.bfloat16)
    k = jnp.asarray(rng.standard_normal((b, S, H, D)), jnp.bfloat16)
    v = jnp.asarray(rng.standard_normal((b, S, H, D)), jnp.bfloat16)
    flops = 4 * b * H * T * S * D * 3.5  # fwd+bwd

    print(f"flow encoder-cross (B={b}, T={T}, S={S}, H={H}, D={D}), fwd+bwd", file=sys.stderr)
    for kv_blk in KV_BLOCKS:
        for q_blk in Q_BLOCKS:
            attn = functools.partial(
                fused_attention, kv_block_size=kv_blk, q_block_size=q_blk
            )
            fn = grad_of(attn)
            try:
                t = timeit(fn, (q, k, v))
                print(f"  kv {kv_blk:5d} q {q_blk:5d}: {t*1e3:8.2f} ms "
                      f"({flops/t/1e12:5.1f} TF/s)", file=sys.stderr)
            except Exception as e:
                print(f"  kv {kv_blk:5d} q {q_blk:5d}: FAILED "
                      f"{type(e).__name__}: {str(e)[:90]}", file=sys.stderr)


if __name__ == "__main__":
    main()
